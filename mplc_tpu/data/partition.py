"""Data partitioning among partners, and the stacked device layout.

Host-side splitting reproduces the reference semantics exactly:
  - basic random / stratified splits (/root/reference/mplc/scenario.py:571-681),
    including the seed-42 shuffle and the label-argsort "stratified" option;
  - the advanced shared/specific cluster split
    (/root/reference/mplc/scenario.py:392-569);
  - per-partner batch-size derivation (/root/reference/mplc/scenario.py:705-724).

The TPU-side novelty is `StackedPartners`: instead of the reference's
per-partner Python lists of arrays, all partners' train data is padded to a
common length and stacked on a leading axis `[P, Nmax, ...]` with a validity
mask. That single layout choice is what makes every multi-partner strategy a
`vmap`/`scan` over axis 0 and every coalition a length-P mask — no ragged
shapes ever reach XLA.
"""

from __future__ import annotations

import random as _pyrandom
from typing import NamedTuple, Sequence

import numpy as np
import jax.numpy as jnp
from sklearn.model_selection import train_test_split
from sklearn.preprocessing import LabelEncoder

from .datasets import Dataset
from .partner import Partner


# ---------------------------------------------------------------------------
# Basic split (reference scenario.py:571-681)
# ---------------------------------------------------------------------------

def split_basic(dataset: Dataset, partners_list: Sequence[Partner],
                amounts_per_partner: Sequence[float], description: str,
                minibatch_count: int) -> None:
    partners_count = len(partners_list)
    y_train_enc = LabelEncoder().fit_transform([str(y) for y in dataset.y_train])

    assert len(amounts_per_partner) == partners_count, (
        "Error: amounts_per_partner list should have a size equal to partners_count")
    assert abs(np.sum(amounts_per_partner) - 1.0) < 1e-9, (
        "Error: the sum of the amounts_per_partner proportions isn't equal to 1")

    if partners_count == 1:
        train_idx_list = [np.arange(len(y_train_enc))]
    else:
        cum = np.cumsum(amounts_per_partner)[:-1]
        splitting_indices_train = (cum * len(y_train_enc)).astype(int)
        if description == "stratified":
            train_idx = np.asarray(y_train_enc).argsort()
        elif description == "random":
            train_idx = np.arange(len(y_train_enc))
            np.random.RandomState(42).shuffle(train_idx)
        else:
            raise NameError(f"This samples_split option [{description}] is not recognized.")
        train_idx_list = np.split(train_idx, splitting_indices_train)

    for p, idx in zip(partners_list, train_idx_list):
        p.x_train = np.asarray(dataset.x_train)[idx]
        p.y_train = np.asarray(dataset.y_train)[idx]
        p.x_train, p.x_test, p.y_train, p.y_test = dataset.train_test_split_local(
            p.x_train, p.y_train)
        p.x_train, p.x_val, p.y_train, p.y_val = dataset.train_val_split_local(
            p.x_train, p.y_train)
        p.final_nb_samples = len(p.x_train)
        p.clusters_list = sorted(set(np.asarray(y_train_enc)[idx].tolist()))

    assert minibatch_count <= min(amounts_per_partner) * len(y_train_enc), (
        "Error: a partner doesn't have enough data samples to create the minibatches")


# ---------------------------------------------------------------------------
# Advanced split (reference scenario.py:392-569)
# ---------------------------------------------------------------------------

def split_advanced(dataset: Dataset, partners_list: Sequence[Partner],
                   amounts_per_partner: Sequence[float],
                   description: Sequence, minibatch_count: int) -> tuple[int, list[float]]:
    """Cluster-per-label split with 'shared'/'specific' cluster assignment.

    Returns (nb_samples_used, final_relative_nb_samples)."""
    y_train = LabelEncoder().fit_transform([str(y) for y in dataset.y_train])
    x_full = np.asarray(dataset.x_train)
    y_full = np.asarray(dataset.y_train)

    for p in partners_list:
        p.cluster_count = int(description[p.id][0])
        p.cluster_split_option = description[p.id][1]
    shared_ps = [p for p in partners_list if p.cluster_split_option == "shared"]
    specific_ps = [p for p in partners_list if p.cluster_split_option == "specific"]
    shared_ps.sort(key=lambda p: p.cluster_count, reverse=True)
    specific_ps.sort(key=lambda p: p.cluster_count, reverse=True)

    labels = sorted(set(y_train.tolist()))
    rnd = _pyrandom.Random(42)
    rnd.shuffle(labels)

    nb_diff_labels = len(labels)
    specific_clusters_count = sum(p.cluster_count for p in specific_ps)
    shared_clusters_count = max((p.cluster_count for p in shared_ps), default=0)
    assert specific_clusters_count + shared_clusters_count <= nb_diff_labels, (
        "Incompatibility between the advanced split arguments and the dataset's "
        "label count: total requested clusters exceed the number of labels")

    x_c, y_c, n_c = {}, {}, {}
    for label in labels:
        idx = np.where(y_train == label)[0]
        x_c[label] = x_full[idx]
        y_c[label] = y_full[idx]
        n_c[label] = len(idx)

    index = 0
    for p in specific_ps:
        p.clusters_list = labels[index:index + p.cluster_count]
        index += p.cluster_count
    shared_clusters = labels[index:index + shared_clusters_count]
    for p in shared_ps:
        p.clusters_list = rnd.sample(shared_clusters, k=p.cluster_count)

    resize_specific = 1.0
    for p in specific_ps:
        available = sum(n_c[cl] for cl in p.clusters_list)
        requested = int(amounts_per_partner[p.id] * len(y_train))
        resize_specific = min(resize_specific, available / requested)

    resize_shared = 1.0
    needed = dict.fromkeys(shared_clusters, 0)
    for p in shared_ps:
        amount = int(amounts_per_partner[p.id] * len(y_train) * resize_specific)
        per_cluster = int(amount / p.cluster_count)
        for cl in p.clusters_list:
            needed[cl] += per_cluster
    for cl in needed:
        if needed[cl] > 0:
            resize_shared = min(resize_shared, n_c[cl] / needed[cl])

    final_resize = resize_specific * resize_shared
    for p in partners_list:
        p.final_nb_samples = int(amounts_per_partner[p.id] * len(y_train) * final_resize)
        p.final_nb_samples_p_cluster = int(p.final_nb_samples / p.cluster_count)
    nb_samples_used = sum(p.final_nb_samples for p in partners_list)
    final_relative = [p.final_nb_samples / nb_samples_used for p in partners_list]

    shared_index = dict.fromkeys(shared_clusters, 0)
    for p in partners_list:
        xs, ys = [], []
        if p in shared_ps:
            for cl in p.clusters_list:
                i0 = shared_index[cl]
                xs.append(x_c[cl][i0:i0 + p.final_nb_samples_p_cluster])
                ys.append(y_c[cl][i0:i0 + p.final_nb_samples_p_cluster])
                shared_index[cl] += p.final_nb_samples_p_cluster
        else:
            for cl in p.clusters_list:
                xs.append(x_c[cl][:p.final_nb_samples_p_cluster])
                ys.append(y_c[cl][:p.final_nb_samples_p_cluster])
        p.x_train = np.concatenate(xs)
        p.y_train = np.concatenate(ys)
        p.x_train, p.x_val, p.y_train, p.y_val = train_test_split(
            p.x_train, p.y_train, test_size=0.1, random_state=42)
        p.x_train, p.x_test, p.y_train, p.y_test = train_test_split(
            p.x_train, p.y_train, test_size=0.1, random_state=42)

    assert minibatch_count <= min(len(p.x_train) for p in partners_list), (
        "Error: a partner doesn't have enough data samples to create the minibatches")
    return nb_samples_used, final_relative


# ---------------------------------------------------------------------------
# Batch sizes (reference scenario.py:705-724)
# ---------------------------------------------------------------------------

def compute_batch_sizes(partners_list: Sequence[Partner], minibatch_count: int,
                        gradient_updates_per_pass_count: int,
                        max_batch_size: int) -> None:
    if len(partners_list) == 1:
        p = partners_list[0]
        p.batch_size = int(np.clip(len(p.x_train) // gradient_updates_per_pass_count,
                                   1, max_batch_size))
    else:
        for p in partners_list:
            bs = len(p.x_train) // (minibatch_count * gradient_updates_per_pass_count)
            p.batch_size = int(np.clip(bs, 1, max_batch_size))


# ---------------------------------------------------------------------------
# Stacked device layout
# ---------------------------------------------------------------------------

class StackedPartners(NamedTuple):
    """All partners' train data as padded stacked device tensors (a pytree).

    x:     [P, Nmax, ...]   float32 (or int32 tokens)
    y:     [P, Nmax, L]     float32 (one-hot, or [.,1] binary)
    mask:  [P, Nmax]        float32 validity
    sizes: [P]              int32 true sample counts
    """

    x: jnp.ndarray
    y: jnp.ndarray
    mask: jnp.ndarray
    sizes: jnp.ndarray

    @property
    def partners_count(self) -> int:
        return int(self.x.shape[0])

    @property
    def n_max(self) -> int:
        return int(self.x.shape[1])

    @staticmethod
    def build(partners_list: Sequence[Partner], label_dim: int) -> "StackedPartners":
        P = len(partners_list)
        n_max = max(len(p.x_train) for p in partners_list)
        x0 = np.asarray(partners_list[0].x_train)
        # int32 (token ids) only when EVERY partner's features are
        # integer: per-partner corruption ('noisy' feature noise) can
        # float one silo's features, and deciding from partner 0 alone
        # would silently truncate the others' values back to ints
        x_dtype = (np.int32
                   if all(np.issubdtype(np.asarray(p.x_train).dtype,
                                        np.integer) for p in partners_list)
                   else np.float32)
        x = np.zeros((P, n_max) + x0.shape[1:], x_dtype)
        y = np.zeros((P, n_max, label_dim), np.float32)
        mask = np.zeros((P, n_max), np.float32)
        sizes = np.zeros((P,), np.int32)
        for i, p in enumerate(partners_list):
            n = len(p.x_train)
            x[i, :n] = p.x_train
            yi = np.asarray(p.y_train, np.float32)
            if yi.ndim == 1:
                yi = yi[:, None]
            y[i, :n] = yi
            mask[i, :n] = 1.0
            sizes[i] = n
        return StackedPartners(jnp.asarray(x), jnp.asarray(y),
                               jnp.asarray(mask), jnp.asarray(sizes))


def stack_eval_set(x: np.ndarray, y: np.ndarray, label_dim: int,
                   chunk: int) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pad an eval set to a multiple of `chunk` and reshape to
    [n_chunks, chunk, ...] so in-jit evaluation is a `lax.scan` over chunks
    (bounded activation memory even when vmapped over partners x coalitions)."""
    n = len(x)
    n_pad = (-n) % chunk
    x = np.asarray(x)
    y = np.asarray(y, np.float32)
    if y.ndim == 1:
        y = y[:, None]
    x_dtype = np.int32 if np.issubdtype(x.dtype, np.integer) else np.float32
    xp = np.concatenate([x, np.zeros((n_pad,) + x.shape[1:], x.dtype)]).astype(x_dtype)
    yp = np.concatenate([y, np.zeros((n_pad, y.shape[1]), np.float32)])
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(n_pad, np.float32)])
    n_chunks = (n + n_pad) // chunk
    return (jnp.asarray(xp.reshape((n_chunks, chunk) + x.shape[1:])),
            jnp.asarray(yp.reshape(n_chunks, chunk, y.shape[1])),
            jnp.asarray(mask.reshape(n_chunks, chunk)))
