"""mplc_tpu: a TPU-native multi-partner learning & contributivity framework.

From-scratch JAX/XLA re-design of the capabilities of MPLC
(multi-partner learning simulation + contributivity measurement,
reference at /root/reference). See SURVEY.md for the structural map.

Unlike the reference, importing this package has no side effects
(the reference runs GPU/logging setup on import, mplc/__init__.py:8-9);
call `mplc_tpu.utils.init_logger()` explicitly if desired.
"""

from . import constants  # noqa: F401
from . import obs  # noqa: F401  (stdlib-only; no jax import at module load)

__version__ = "0.1.0"
