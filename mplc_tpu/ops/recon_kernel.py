"""Fused Pallas reconstruction kernel (the live tier's hot path).

The reference reconstruction (contrib/reconstruct.py) replays the
recorded rounds as a `lax.scan`: per round, renormalize the masked
weights and accumulate one weighted delta into the carried params. That
shape is R sequential small contractions — each round re-reads the
carried params from HBM and writes them back, so the scan is bound by
R round-trips over the param footprint plus the scan's sequential
dispatch overhead.

The kernel reassociates the whole replay into ONE contraction. The
renormalized weight of partner p in round r depends only on the mask
and the recorded weights:

    WN[b, r, p] = w[r, p] * m[b, p] / sum_q w[r, q] * m[b, q]   (0 when
                  the denominator is 0 — the zero-weight pass-through)

so the reconstructed params are

    out[b, :] = init[:] + sum_{r,p} WN[b, r, p] * delta[r, p, :]
              = init[:] + (WN[b] flattened) @ (deltas flattened [R*P, D])

— the masked-weight renormalize collapses to an O(B*R*P) elementwise
prologue (computed in-graph, fused by XLA) and the per-round accumulate
becomes a single [B, K] x [K, D] matmul over the flattened recorded
stream, which this module tiles as a Pallas TPU kernel: one pass over
the recorded deltas, MXU-contracted, accumulated block-resident in VMEM
instead of R param-sized HBM round-trips.

Numerics contract: the kernel computes the SAME sum with a different
association (one fp32-accumulated dot instead of R sequential adds), so
values are ledger-bounded vs the scan, not bit-identical — the value
ledger + tau-b gate (obs/numerics.py, scripts/bench_diff.py) carry the
proof, and the interpret-mode parity test bounds the deviation
everywhere. Two exactnesses ARE preserved: a coalition whose every
round has zero surviving weight reproduces `init` bit-exactly (its WN
rows are exact zeros, the matmul contributes exact 0.0), and padding
(batch rows, K tail, D tail) is zero-filled so padded lanes contribute
exact zeros.

Fallback rule (MPLC_TPU_RECON_KERNEL, constants.recon_kernel_mode):
`auto` compiles the kernel on TPU backends only — CPU tier-1 runs the
scan reference; `interpret` runs the kernel through the Pallas
interpreter on any backend (the parity-test path); `force` requires a
compiled kernel; `off` always runs the scan. The resolved path is part
of the ProgramBank recon key — a scan executable never serves a kernel
query or vice versa.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Pallas is part of the jax distribution but keep the import soft: the
# scan fallback must survive a build without it (resolve() reports the
# kernel unavailable instead of raising at import time).
try:  # pragma: no cover - exercised by availability, not by absence
    from jax.experimental import pallas as pl
    _PALLAS_OK = True
except Exception:  # pragma: no cover
    pl = None
    _PALLAS_OK = False


def kernel_available() -> bool:
    """True when the compiled (non-interpret) kernel can run here."""
    return _PALLAS_OK and jax.default_backend() == "tpu"


def resolve(mode: str) -> tuple:
    """(use_kernel, interpret) for a MPLC_TPU_RECON_KERNEL mode."""
    if mode == "off" or not _PALLAS_OK:
        if mode == "force":
            raise RuntimeError(
                "MPLC_TPU_RECON_KERNEL=force but Pallas is not importable "
                "on this toolchain")
        return (False, False)
    if mode == "interpret":
        return (True, True)
    if mode == "force":
        return (True, False)
    return (kernel_available(), False)  # auto


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _largest_divisor_block(n: int, candidates: tuple) -> int:
    """Largest candidate block edge that tiles `n` exactly (the caller
    pads to a multiple of the smallest candidate first)."""
    for c in candidates:
        if n % c == 0:
            return c
    return n


def _recon_matmul_kernel(wn_ref, d_ref, init_ref, o_ref):
    """One (bm, bn) output block: init + WN-block @ delta-block,
    accumulated across the K grid axis (innermost, sequential on TPU —
    the output block stays resident while k sweeps the recorded
    stream)."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _seed():
        o_ref[...] = jnp.broadcast_to(
            init_ref[...], o_ref.shape).astype(o_ref.dtype)

    o_ref[...] += jnp.dot(wn_ref[...], d_ref[...],
                          preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=())
def _fused_contract(wn2, d2, init, *, interpret: bool):
    """out[B, D] = init[None, :] + wn2 @ d2 via the tiled Pallas kernel.

    wn2 [B, K] and d2 [K, D] arrive already zero-padded to tile-friendly
    shapes by the caller; init [1, D] likewise. fp32 accumulation always
    (preferred_element_type), whatever the input dtype."""
    B, K = wn2.shape
    _, D = d2.shape
    bm = _largest_divisor_block(B, (128, 64, 32, 16, 8))
    bn = _largest_divisor_block(D, (512, 256, 128))
    bk = _largest_divisor_block(K, (512, 256, 128))
    grid = (B // bm, D // bn, K // bk)
    return pl.pallas_call(
        _recon_matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((B, D), jnp.float32),
        interpret=interpret,
    )(wn2, d2, init)


def normalized_round_weights(masks, weights):
    """WN [B, R, P]: the scan's per-round masked renormalize, batched.
    Zero-denominator rounds (early-stopped tail, no surviving member)
    produce exact-zero rows — the pass-through rule."""
    ws = weights[None, :, :] * masks[:, None, :]          # [B, R, P]
    denom = jnp.sum(ws, axis=-1, keepdims=True)
    return jnp.where(denom > 0, ws / jnp.maximum(denom, 1e-12), 0.0)


def reconstruct_batch(masks, init_params, deltas, weights, *,
                      precision: str = "fp32", interpret: bool = False):
    """Reconstruct a batch of coalition models in one fused pass.

    masks [B, P] float; init_params pytree; deltas pytree with leaves
    [R, P, ...]; weights [R, P]. Returns the reconstructed params pytree
    with a leading batch axis [B, ...], leaf dtypes matching the scan
    path's for the given precision mode (bf16 leaves under
    MPLC_TPU_PRECISION=bf16, the recorded dtypes otherwise).
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    init_leaves = jax.tree_util.tree_leaves(init_params)
    B = masks.shape[0]
    R, P = weights.shape

    wn = normalized_round_weights(masks, weights)         # [B, R, P]
    K = R * P
    wn2 = wn.reshape(B, K)

    # flatten every leaf's [R, P, *s] to [K, prod(s)] and contract them
    # through ONE kernel call: the concatenated [K, D_total] layout keeps
    # the MXU busy on one big matmul instead of a per-leaf tail of thin
    # ones (and the per-leaf D offsets below undo it exactly)
    sizes = [int(l.size) // K for l in leaves]
    d2 = jnp.concatenate(
        [l.reshape(K, -1) for l in leaves], axis=1)       # [K, D_total]
    init_flat = jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in init_leaves])
    D = d2.shape[1]

    compute_dtype = jnp.bfloat16 if precision == "bf16" else jnp.float32
    wn2 = wn2.astype(compute_dtype)
    d2 = d2.astype(compute_dtype)

    # zero-pad to tile-friendly shapes (padded rows/cols contribute
    # exact zeros; padded batch rows are sliced off below)
    Bp = _round_up(B, 8)
    Kp = _round_up(K, 128)
    Dp = _round_up(D, 128)
    wn2 = jnp.pad(wn2, ((0, Bp - B), (0, Kp - K)))
    d2 = jnp.pad(d2, ((0, Kp - K), (0, Dp - D)))
    init_pad = jnp.pad(init_flat, (0, Dp - D)).reshape(1, Dp)

    out = _fused_contract(wn2, d2, init_pad, interpret=interpret)
    out = out[:B, :D]

    # unflatten back into per-leaf [B, *s] params, matching the scan
    # path's carried dtype (bf16 accumulate under precision=bf16 — the
    # kernel still sums in fp32, one rounding instead of R)
    outs, off = [], 0
    for leaf, init_leaf, size in zip(leaves, init_leaves, sizes):
        part = out[:, off:off + size]
        off += size
        shape = (B,) + tuple(init_leaf.shape)
        dtype = jnp.bfloat16 if precision == "bf16" else init_leaf.dtype
        outs.append(part.reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(treedef, outs)
