"""Weight aggregation: the "communication backend".

In the reference, inter-partner communication is literally a layer-by-layer
`np.average` over Python lists of weights (/root/reference/mplc/
mpl_utils.py:90-102) with three weighting policies (:105-128). Here partner
models are one pytree with a stacked leading axis `[P, ...]`, so aggregation
is a single fused einsum per leaf — and when partners are sharded over a
device mesh axis, the same code lowers to a `psum`-style reduction over ICI
via `shard_map` (see mplc_tpu/parallel/).

Coalition membership composes in at this exact point: the coalition bitmask
multiplies the weight vector before normalization, which is what makes a
characteristic-function evaluation "training with a masked reduction" and
therefore vmappable over all 2^N masks at once.

The reference's "local-score" policy forgets its `return` and is broken
upstream (mpl_utils.py:126-128, noted in SURVEY.md §7); implemented
correctly here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

AGGREGATOR_NAMES = ("uniform", "data-volume", "local-score")


def aggregation_weights(kind: str, coalition_mask: jax.Array,
                        sizes: jax.Array, last_scores: jax.Array,
                        axis_name: str | None = None) -> jax.Array:
    """Build the normalized weight vector w[P] for one aggregation step.

    kind: 'uniform' | 'data-volume' | 'local-score'
    coalition_mask: [P] float 0/1 — inactive partners get weight 0.
    sizes: [P] sample counts (data-volume policy).
    last_scores: [P] last-round val accuracy (local-score policy).
    axis_name: if the partner axis is sharded over a mesh axis (shard_map),
        its name — normalization then uses the GLOBAL total via `psum`.
    """
    if kind == "uniform":
        raw = coalition_mask
    elif kind == "data-volume":
        raw = coalition_mask * sizes.astype(jnp.float32)
    elif kind == "local-score":
        raw = coalition_mask * last_scores
    else:
        raise KeyError(f"aggregation approach '{kind}' is not a valid approach. "
                       f"Supported: {AGGREGATOR_NAMES}")
    total = jnp.sum(raw)
    if axis_name is not None:
        total = jax.lax.psum(total, axis_name)
    return raw / jnp.maximum(total, 1e-12)


def aggregate(stacked_params, weights: jax.Array, axis_name: str | None = None):
    """Fused weighted mean over the partner axis, per pytree leaf.

    stacked_params: pytree with leaves [P, ...]; weights: [P].
    Returns the aggregated (unstacked) pytree. With `axis_name`, the local
    partial sums are `psum`ed over the mesh axis the partner dimension is
    sharded on — this is the framework's cross-chip weight "communication"
    (one reduce per aggregation, riding ICI).
    """
    def reduce_leaf(leaf):
        w = weights.astype(leaf.dtype).reshape((-1,) + (1,) * (leaf.ndim - 1))
        s = jnp.sum(leaf * w, axis=0)
        return jax.lax.psum(s, axis_name) if axis_name is not None else s
    return jax.tree_util.tree_map(reduce_leaf, stacked_params)


def broadcast(params, partners_count: int):
    """Replicate one pytree along a new leading partner axis (the reference's
    `partner.model_weights = self.model_weights` broadcast,
    multi_partner_learning.py:310-311)."""
    return jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf[None], (partners_count,) + leaf.shape), params)
