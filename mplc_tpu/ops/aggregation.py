"""Weight aggregation: the "communication backend".

In the reference, inter-partner communication is literally a layer-by-layer
`np.average` over Python lists of weights (/root/reference/mplc/
mpl_utils.py:90-102) with three weighting policies (:105-128). Here partner
models are one pytree with a stacked leading axis `[P, ...]`, so aggregation
is a single fused einsum per leaf — and when partners are sharded over a
device mesh axis, the same code lowers to a `psum`-style reduction over ICI
via `shard_map` (see mplc_tpu/parallel/).

Coalition membership composes in at this exact point: the coalition bitmask
multiplies the weight vector before normalization, which is what makes a
characteristic-function evaluation "training with a masked reduction" and
therefore vmappable over all 2^N masks at once.

The reference's "local-score" policy forgets its `return` and is broken
upstream (mpl_utils.py:126-128, noted in SURVEY.md §7); implemented
correctly here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

AGGREGATOR_NAMES = ("uniform", "data-volume", "local-score")


def _install_barrier_batching_rule() -> bool:
    """Register the (missing) trivial batching rule for
    `optimization_barrier` on this toolchain: the barrier is an identity
    op, so batching passes every operand through with its batch dim
    unchanged. Without the rule, a barrier anywhere under the engine's
    coalition `vmap` raises NotImplementedError — and the
    deterministic-reduce mode needs barriers INSIDE the vmapped trainer
    (`fusion_fence`) to pin cross-boundary fusion. Returns False (and
    deterministic mode degrades to fence-less, still fold-ordered) if
    the internal primitive moved."""
    try:
        from jax._src.lax import lax as _lax_internal
        from jax.interpreters import batching as _batching
        p = _lax_internal.optimization_barrier_p
        if p not in _batching.primitive_batchers:
            _batching.primitive_batchers[p] = \
                lambda args, dims: (p.bind(*args), dims)
        return True
    except Exception:  # pragma: no cover — toolchain drift
        return False


_BARRIER_OK = _install_barrier_batching_rule()


def fusion_fence(tree):
    """`optimization_barrier` over a pytree, usable under vmap (the
    batching rule above). The deterministic-reduction mode uses it to cut
    XLA fusion across chosen boundaries — e.g. between the rng/permutation
    generation and the training pass that consumes them, or between the
    weighting multiply and the ordered fold — because cross-boundary
    fusion (FMA formation, consumer-driven tiling) rounds differently per
    program embedding and breaks cross-topology bit-identity. Semantically
    the identity function; no-op if the rule could not be installed."""
    if not _BARRIER_OK:
        return tree
    return jax.lax.optimization_barrier(tree)


def ordered_fold(terms: jax.Array) -> jax.Array:
    """Strict left-to-right fold over axis 0: ((t0 + t1) + t2) + ...

    The deterministic-reduction primitive (MPLC_TPU_DETERMINISTIC_REDUCE,
    obs/numerics.py): explicit chained adds pin the reduction order — XLA
    does not reassociate them the way it may an opaque `reduce`/`psum` —
    so the result is bit-identical wherever the fold runs: one device, or
    every shard of an N-device mesh after an `all_gather` restored the
    global partner order. A left fold (not a pairwise tree) on purpose:
    partial sums are insensitive to exactly-zero terms riding along
    (x + 0.0 == x bitwise), which is the property that keeps the slot
    path (k compact terms) and the masked path (k active terms spread
    over P rows) bit-identical — a balanced tree re-pairs around zero
    rows and loses it."""
    out = terms[0]
    for i in range(1, terms.shape[0]):
        out = out + terms[i]
    return out


def aggregation_weights(kind: str, coalition_mask: jax.Array,
                        sizes: jax.Array, last_scores: jax.Array,
                        axis_name: str | None = None,
                        deterministic: bool = False) -> jax.Array:
    """Build the normalized weight vector w[P] for one aggregation step.

    kind: 'uniform' | 'data-volume' | 'local-score'
    coalition_mask: [P] float 0/1 — inactive partners get weight 0.
    sizes: [P] sample counts (data-volume policy).
    last_scores: [P] last-round val accuracy (local-score policy).
    axis_name: if the partner axis is sharded over a mesh axis (shard_map),
        its name — normalization then uses the GLOBAL total via `psum`.
    deterministic: fixed-order total (MPLC_TPU_DETERMINISTIC_REDUCE): the
        [P] raw weights are folded strictly left-to-right — all-gathered
        into global partner order first when sharded — so the normalizer
        is bit-identical on 1 and N devices.
    """
    if kind == "uniform":
        raw = coalition_mask
    elif kind == "data-volume":
        raw = coalition_mask * sizes.astype(jnp.float32)
    elif kind == "local-score":
        raw = coalition_mask * last_scores
    else:
        raise KeyError(f"aggregation approach '{kind}' is not a valid approach. "
                       f"Supported: {AGGREGATOR_NAMES}")
    if deterministic:
        if axis_name is not None:
            full = jax.lax.all_gather(raw, axis_name, axis=0, tiled=True)
        else:
            # fence so the fold sees the same materialized terms the
            # sharded path's all_gather produces — without it XLA fuses
            # the producing multiply into the fold's adds (FMA), and the
            # different rounding breaks 1-vs-N-device bit-identity
            full = fusion_fence(raw)
        total = ordered_fold(full)
    else:
        total = jnp.sum(raw)
        if axis_name is not None:
            total = jax.lax.psum(total, axis_name)
    return raw / jnp.maximum(total, 1e-12)


def aggregate(stacked_params, weights: jax.Array, axis_name: str | None = None,
              deterministic: bool = False):
    """Fused weighted mean over the partner axis, per pytree leaf.

    stacked_params: pytree with leaves [P, ...]; weights: [P].
    Returns the aggregated (unstacked) pytree. With `axis_name`, the local
    partial sums are `psum`ed over the mesh axis the partner dimension is
    sharded on — this is the framework's cross-chip weight "communication"
    (one reduce per aggregation, riding ICI).

    deterministic (MPLC_TPU_DETERMINISTIC_REDUCE): instead of the
    order-sensitive local-`sum` + `psum` pair, each leaf's weighted terms
    are folded strictly left-to-right in GLOBAL partner order
    (`ordered_fold`); when sharded, the terms are `all_gather`ed over the
    partner mesh axis first — the collective moves bytes but performs no
    arithmetic, so the fold is the same computation on the same values
    everywhere, and the partner-sharded result is bit-identical to the
    unsharded one (tests/test_partner_shard.py, tests/test_numerics.py).
    """
    def reduce_leaf(leaf):
        w = weights.astype(leaf.dtype).reshape((-1,) + (1,) * (leaf.ndim - 1))
        terms = leaf * w
        if deterministic:
            if axis_name is not None:
                terms = jax.lax.all_gather(terms, axis_name, axis=0,
                                           tiled=True)
            else:
                # same materialization fence as the sharded path's
                # all_gather: stop XLA from fusing the weighting multiply
                # into the fold's adds (FMA rounds differently), which
                # would break 1-vs-N-device bit-identity
                terms = fusion_fence(terms)
            return ordered_fold(terms)
        s = jnp.sum(terms, axis=0)
        return jax.lax.psum(s, axis_name) if axis_name is not None else s
    return jax.tree_util.tree_map(reduce_leaf, stacked_params)


def broadcast(params, partners_count: int):
    """Replicate one pytree along a new leading partner axis (the reference's
    `partner.model_weights = self.model_weights` broadcast,
    multi_partner_learning.py:310-311)."""
    return jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf[None], (partners_count,) + leaf.shape), params)
