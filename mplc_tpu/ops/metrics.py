"""Masked losses and metrics.

The reference delegates loss/accuracy to Keras `model.compile(loss=...,
metrics=["accuracy"])` (/root/reference/mplc/dataset.py:196-199, :473-477).
Here they are pure functions over logits so they can live inside `jit`,
`vmap` (over partners and coalitions) and `shard_map` without modification.

Every function takes an explicit `mask` because partner data is stored as
padded stacked tensors: padded rows must contribute exactly zero loss and
zero gradient.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, y_onehot: jax.Array) -> jax.Array:
    """Per-example categorical cross-entropy from logits. [N, C] -> [N]."""
    logz = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(y_onehot * logz, axis=-1)


def sigmoid_binary_cross_entropy(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Per-example binary cross-entropy from a single logit. [N, 1] -> [N]."""
    logits = logits.reshape(logits.shape[0])
    y = y.reshape(y.shape[0])
    return jnp.maximum(logits, 0.0) - logits * y + jnp.log1p(jnp.exp(-jnp.abs(logits)))


def categorical_correct(logits: jax.Array, y_onehot: jax.Array) -> jax.Array:
    return (jnp.argmax(logits, axis=-1) == jnp.argmax(y_onehot, axis=-1)).astype(jnp.float32)


def binary_correct(logits: jax.Array, y: jax.Array) -> jax.Array:
    logits = logits.reshape(logits.shape[0])
    y = y.reshape(y.shape[0])
    return ((logits > 0.0) == (y > 0.5)).astype(jnp.float32)


def masked_loss_and_metrics(loss_kind: str, logits: jax.Array, y: jax.Array,
                            mask: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Return (mean_loss, accuracy, valid_count) under `mask`.

    Zero-valid-row batches (fully padded, e.g. an inactive partner slot in a
    coalition) return loss=0, acc=0 rather than NaN so the surrounding
    vmap/scan stays finite.
    """
    if loss_kind == "binary":
        per_ex_loss = sigmoid_binary_cross_entropy(logits, y)
        per_ex_correct = binary_correct(logits, y)
    else:
        per_ex_loss = softmax_cross_entropy(logits, y)
        per_ex_correct = categorical_correct(logits, y)
    mask = mask.astype(jnp.float32)
    count = jnp.sum(mask)
    denom = jnp.maximum(count, 1.0)
    mean_loss = jnp.sum(per_ex_loss * mask) / denom
    acc = jnp.sum(per_ex_correct * mask) / denom
    return mean_loss, acc, count
