from .aggregation import aggregation_weights, aggregate, broadcast, AGGREGATOR_NAMES
from .metrics import (masked_loss_and_metrics, softmax_cross_entropy,
                      sigmoid_binary_cross_entropy)

__all__ = [
    "aggregation_weights", "aggregate", "broadcast", "AGGREGATOR_NAMES",
    "masked_loss_and_metrics", "softmax_cross_entropy",
    "sigmoid_binary_cross_entropy",
]
