"""Deterministic fault injection for the coalition sweep engine.

Long Shapley sweeps die to three families of failure on real fleets:
transient XLA/runtime errors (tunnel hiccups, preempted programs), HBM
exhaustion (RESOURCE_EXHAUSTED on a batch that autotuned too wide), and
hard kills mid-run (OS OOM killer, preemption, power). Every recovery
path in `contrib/engine.py` — retry/backoff, cap degradation, autosave
resume — must be testable on CPU in the fast tier, so this module turns
each failure family into an *injectable*, deterministic event.

Plan grammar (`MPLC_TPU_FAULT_PLAN`): comma-separated entries

    <kind>@<site><ordinal>

      kind  ::= transient | oom | crash
      site  ::= batch   (the dispatch boundary of the Nth device batch)
              | harvest (the result-fetch boundary of the Nth batch)

    e.g.  MPLC_TPU_FAULT_PLAN=transient@batch3,oom@batch5,crash@batch7

Batches are numbered 1-based in engine dispatch order, counted once per
batch (a RETRY of batch N keeps ordinal N — so `transient@batch3` fails
batch 3's first attempt and lets the bit-identical retry through).
Repeating an entry queues multiple faults at the same boundary
(`transient@batch1,transient@batch1` fails the first attempt AND the
first retry). Each entry fires exactly once.

Partner-level fault plan (`MPLC_TPU_PARTNER_FAULT_PLAN`). Where the plan
above injects *infrastructure* failures at batch boundaries, this plan
injects *partner* misbehavior — the cross-silo failure modes (stragglers,
dropouts, corrupted silos) that change the GAME, not the schedule.
Comma-separated entries

    <kind>@p<ID>:<param><value>

      dropout@p2:epoch3     partner 2 leaves at epoch 3 (1-based) and never
                            returns; its slot is masked out with FedAvg
                            weight renormalization over the survivors.
                            `epoch1` = the partner never participates.
      straggler@p0:delay2   partner 0's per-round contribution is computed
                            from the global params 2 aggregation rounds
                            stale (delay-k staleness, k >= 1).
      noisy@p1:sigma0.1     seeded Gaussian feature noise (sigma = 0.1) on
                            partner 1's training features (data plane:
                            applied at Scenario.data_corruption time).
      glabel@p3:frac0.5     50% of partner 3's labels flipped to one
                            seeded "global" target class (the targeted
                            label-poisoning attack; data plane).

Entries are deterministic: dropout/straggler fire by partner id +
epoch/round ordinal inside the compiled trainer, noisy/glabel draw from
the partner's seeded generator. A repeated (kind, partner) pair warns and
keeps the first entry; malformed entries warn and are skipped — same
contract as the batch-fault plan.

Service-level fault plan (`MPLC_TPU_SERVICE_FAULT_PLAN`): targets the
multi-tenant sweep service (mplc_tpu/service/) by 1-based job submission
ordinal — `crash@job2:batch3,reject@job4,stall@job1:sec2` — so isolation
tests can fault exactly one tenant's job and assert the others
unperturbed. Grammar and semantics with `parse_service_fault_plan` below.

Injected exception classes mirror the real failures' types so the
engine's classifier code paths are the ones exercised:

  - `InjectedTransient` subclasses the runtime's `XlaRuntimeError` (when
    available) with an `INTERNAL:` status prefix — retryable.
  - `InjectedOom` ditto with a `RESOURCE_EXHAUSTED:` prefix — triggers
    cap degradation, never retried as-is.
  - `InjectedCrash` subclasses `BaseException` (like `KeyboardInterrupt`)
    so no recovery path can swallow it — it simulates a process kill and
    unwinds everything; resume happens from the autosave in a new engine.

Malformed plan entries warn and are skipped: a typo in a fault plan must
never itself crash a production run.
"""

from __future__ import annotations

import os
import re
import warnings

FAULT_PLAN_ENV = "MPLC_TPU_FAULT_PLAN"
PARTNER_FAULT_PLAN_ENV = "MPLC_TPU_PARTNER_FAULT_PLAN"
SERVICE_FAULT_PLAN_ENV = "MPLC_TPU_SERVICE_FAULT_PLAN"
ROUTER_FAULT_PLAN_ENV = "MPLC_TPU_ROUTER_FAULT_PLAN"

try:  # the concrete class jax raises for device/runtime failures
    from jaxlib.xla_extension import XlaRuntimeError as _XlaRuntimeError
except Exception:  # pragma: no cover - toolchain without the symbol
    _XlaRuntimeError = RuntimeError


class InjectedTransient(_XlaRuntimeError):
    """A retryable runtime failure (same class as the real thing)."""


class InjectedOom(_XlaRuntimeError):
    """An injected RESOURCE_EXHAUSTED — drives the cap-degradation ladder."""


class InjectedCrash(BaseException):
    """A simulated hard kill. BaseException: retry/degradation code paths
    catching `Exception` can never swallow it, mirroring a real SIGKILL's
    absence of in-process recovery."""


class LadderExhaustedError(RuntimeError):
    """The OOM degrade ladder ran out of rungs with work still missing —
    the classified form of what used to escape the 2-D mode as a raw
    `XlaRuntimeError` (the 1-D paths have a terminal CPU rung; the
    partner-sharded 2-D programs need the device mesh and cannot take
    it). Carries the rung count and the mode so callers — the sweep
    service above all — can act on it: it is PERMANENT (re-dispatching at
    the same exhausted cap would OOM identically), so the service
    quarantines only the owning tenant's job instead of retrying
    forever, and the resilience report row records the exhaustion.
    `postmortem_path` names the crash flight-recorder dump
    (obs/flight.py) written when the ladder died — the recent-span ring
    plus a metrics snapshot — or None when no dump could be written."""

    def __init__(self, msg: str, *, halvings: int = 0, mode: str = "2d",
                 postmortem_path: "str | None" = None):
        super().__init__(msg)
        self.halvings = halvings
        self.mode = mode
        self.postmortem_path = postmortem_path


# Real XlaRuntimeError messages lead with a gRPC-style status code. Codes
# that indicate a broken *program or request* are permanent: retrying the
# identical dispatch can only fail identically. Everything else (INTERNAL,
# UNAVAILABLE, DEADLINE_EXCEEDED, ABORTED, UNKNOWN, ...) is presumed
# transient — the tunnel/fleet class of failure retries are for.
_PERMANENT_STATUS = ("INVALID_ARGUMENT", "NOT_FOUND", "FAILED_PRECONDITION",
                     "UNIMPLEMENTED", "PERMISSION_DENIED", "UNAUTHENTICATED")
# Statuses that are transient REGARDLESS of the exception class: the
# service layer (queue timeouts, tunnel RPCs, control-plane calls) raises
# them as plain RuntimeError/OSError on toolchains without the real
# XlaRuntimeError symbol, and a DEADLINE_EXCEEDED that only rides the
# retry ladder when jaxlib exports a class is a classifier bug — PR 4
# only covered the statuses its injected faults carried.
_TRANSIENT_STATUS = ("DEADLINE_EXCEEDED", "UNAVAILABLE")
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating")


def is_oom(err: BaseException) -> bool:
    """True for HBM/host exhaustion failures: the cap-degradation family,
    never blind-retried (the identical batch would exhaust identically)."""
    if isinstance(err, InjectedOom):
        return True
    if isinstance(err, LadderExhaustedError):
        # the ladder's own terminal error must never re-enter the ladder
        return False
    if not isinstance(err, Exception):
        return False
    msg = str(err)
    return any(m in msg for m in _OOM_MARKERS)


def is_transient(err: BaseException) -> bool:
    """True for failures worth retrying bit-identically: injected
    transients, real `XlaRuntimeError`s whose status code is not in the
    permanent family, and ANY exception whose message leads with an
    always-transient gRPC status (DEADLINE_EXCEEDED / UNAVAILABLE — the
    service-layer timeout family, which surfaces as plain exceptions on
    toolchains without the XlaRuntimeError symbol). OOM is classified
    separately (`is_oom`); other plain Python exceptions (bugs) are never
    transient."""
    if isinstance(err, InjectedTransient):
        return True
    if is_oom(err):
        return False
    if isinstance(err, LadderExhaustedError):
        return False
    msg = str(err).lstrip()

    def leads_with(code: str) -> bool:
        # the STATUS TOKEN must lead the message: a real gRPC status is
        # followed by ':' or whitespace (or is the whole message), so
        # "UNAVAILABLE_RESOURCE: config bug" must not ride the ladder
        if not msg.startswith(code):
            return False
        rest = msg[len(code):]
        return not rest or not (rest[0].isalnum() or rest[0] == "_")

    if isinstance(err, Exception) and \
            any(leads_with(code) for code in _TRANSIENT_STATUS):
        return True
    if _XlaRuntimeError is RuntimeError:
        # toolchain without the real class: every RuntimeError would
        # match — refuse to blind-retry host-side bugs there
        return False
    if not isinstance(err, _XlaRuntimeError):
        return False
    return not any(msg.startswith(code) for code in _PERMANENT_STATUS)


_ENTRY_RE = re.compile(
    r"^(transient|oom|crash)@(batch|harvest)([0-9]+)$")


def parse_fault_plan(spec: str | None) -> dict:
    """`{(site, ordinal): [kind, ...]}` from the plan grammar. Unknown or
    malformed entries warn and are dropped; an empty/unset spec is an
    empty plan (the production no-op)."""
    plan: dict = {}
    if not spec:
        return plan
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _ENTRY_RE.match(entry)
        if m is None or int(m.group(3)) < 1:
            warnings.warn(
                f"{FAULT_PLAN_ENV}: ignoring malformed entry {entry!r} "
                f"(expected <transient|oom|crash>@<batch|harvest><N>, N >= 1)",
                stacklevel=2)
            continue
        kind, site, ordinal = m.group(1), m.group(2), int(m.group(3))
        # 'batch' is the dispatch boundary in the engine's vocabulary
        site = "dispatch" if site == "batch" else site
        plan.setdefault((site, ordinal), []).append(kind)
    return plan


class FaultInjector:
    """Consulted by the engine at every dispatch/harvest boundary.

    `check(site, ordinal)` raises the next planned fault for that
    boundary, at most once per plan entry; with an empty plan it is a
    no-op attribute read. The engine numbers batches itself and passes
    the ordinal in, so retries of a batch re-check the SAME ordinal and a
    consumed entry lets the retry through — that property is what makes
    `transient@batchK` mean "batch K fails once, then recovers"."""

    __slots__ = ("plan", "injected")

    def __init__(self, plan: dict | None = None):
        self.plan = plan or {}
        self.injected = 0

    @classmethod
    def from_env(cls) -> "FaultInjector":
        return cls(parse_fault_plan(os.environ.get(FAULT_PLAN_ENV)))

    @property
    def armed(self) -> bool:
        return bool(self.plan)

    def check(self, site: str, ordinal: int) -> None:
        if not self.plan:
            return
        kinds = self.plan.get((site, ordinal))
        if not kinds:
            return
        kind = kinds.pop(0)
        if not kinds:
            del self.plan[(site, ordinal)]
        self.injected += 1
        from .obs import metrics as obs_metrics
        from .obs import trace as obs_trace
        obs_metrics.counter("engine.faults_injected").inc()
        obs_trace.event("engine.fault", kind=kind, site=site, ordinal=ordinal)
        where = f"({site} boundary, batch {ordinal})"
        if kind == "transient":
            raise InjectedTransient(f"INTERNAL: injected transient fault {where}")
        if kind == "oom":
            raise InjectedOom(
                f"RESOURCE_EXHAUSTED: injected device OOM {where}")
        raise InjectedCrash(f"injected crash {where}")


# ---------------------------------------------------------------------------
# Partner-level fault plan (MPLC_TPU_PARTNER_FAULT_PLAN)
# ---------------------------------------------------------------------------

# kind -> (expected param name, value parser, validator). dropout's epoch
# and straggler's delay are 1-based ordinals; noisy's sigma is a noise
# stddev; glabel's frac is a corrupted-label fraction.
_PARTNER_KINDS = {
    "dropout": ("epoch", int, lambda v: v >= 1),
    "straggler": ("delay", int, lambda v: v >= 1),
    "noisy": ("sigma", float, lambda v: v >= 0.0),
    "glabel": ("frac", float, lambda v: 0.0 <= v <= 1.0),
}

_PARTNER_ENTRY_RE = re.compile(
    r"^(dropout|straggler|noisy|glabel)@p([0-9]+):"
    r"(epoch|delay|sigma|frac)([0-9]+(?:\.[0-9]+)?)$")


def parse_partner_fault_plan(spec: str | None) -> dict:
    """`{partner_id: {kind: value, ...}}` from the partner-plan grammar.

    Malformed entries (unknown kind, kind/param mismatch, out-of-range
    value) warn and are dropped; a repeated (kind, partner) pair warns and
    keeps the FIRST entry. An empty/unset spec is the empty plan — the
    production no-op, same contract as `parse_fault_plan`."""
    plan: dict = {}
    if not spec:
        return plan
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _PARTNER_ENTRY_RE.match(entry)
        if m is not None:
            kind, pid, param, value = (m.group(1), int(m.group(2)),
                                       m.group(3), m.group(4))
            want_param, cast, ok = _PARTNER_KINDS[kind]
            if param == want_param:
                try:
                    v = cast(value)
                except ValueError:
                    v = None
                if v is not None and ok(v):
                    if kind in plan.get(pid, {}):
                        warnings.warn(
                            f"{PARTNER_FAULT_PLAN_ENV}: duplicate "
                            f"{kind}@p{pid} entry {entry!r} ignored "
                            "(keeping the first)", stacklevel=2)
                    else:
                        plan.setdefault(pid, {})[kind] = v
                    continue
        warnings.warn(
            f"{PARTNER_FAULT_PLAN_ENV}: ignoring malformed entry {entry!r} "
            "(expected dropout@p<I>:epoch<N> | straggler@p<I>:delay<K> | "
            "noisy@p<I>:sigma<F> | glabel@p<I>:frac<F>)", stacklevel=2)
    return plan


def partner_fault_plan_from_env() -> dict:
    return parse_partner_fault_plan(os.environ.get(PARTNER_FAULT_PLAN_ENV))


def clip_partner_plan(plan: dict, partners_count: int) -> dict:
    """Drop (with a warning) entries addressing partner ids outside the
    scenario — a plan written for a bigger game must degrade, not crash."""
    bad = sorted(p for p in plan if p >= partners_count)
    if bad:
        warnings.warn(
            f"{PARTNER_FAULT_PLAN_ENV}: ignoring entries for partner ids "
            f"{bad} (scenario has {partners_count} partners)", stacklevel=2)
    return {p: f for p, f in plan.items() if p < partners_count}


def trainer_fault_arrays(plan: dict, partners_count: int
                         ) -> tuple[tuple | None, tuple | None]:
    """The trainer-plane view of a partner plan: per-partner
    `(drop_epochs, straggler_delays)` tuples of length P (0 = no fault for
    that partner), or None in a slot when NO partner carries that fault —
    the None lets TrainConfig/compiled programs stay byte-identical to the
    fault-free build."""
    drops = [0] * partners_count
    delays = [0] * partners_count
    for pid, entry in plan.items():
        drops[pid] = int(entry.get("dropout", 0))
        delays[pid] = int(entry.get("straggler", 0))
    return (tuple(drops) if any(drops) else None,
            tuple(delays) if any(delays) else None)


def data_fault_specs(plan: dict) -> dict:
    """The data-plane view: `{partner_id: [(kind, value), ...]}` for the
    corruption-style faults (noisy feature noise, glabel label poisoning),
    applied by `Scenario.data_corruption` through the partner's seeded
    generator."""
    out: dict = {}
    for pid, entry in plan.items():
        specs = [(k, entry[k]) for k in ("noisy", "glabel") if k in entry]
        if specs:
            out[pid] = specs
    return out


def forever_dropped(plan: dict) -> frozenset:
    """Partner ids dropped from epoch 1 — they never participate, so a
    coalition containing one is, for rng purposes, the coalition without
    it (the engine canonicalizes the per-coalition rng stream over this
    set; that is what makes `dropout@pK:epoch1` runs BIT-IDENTICAL to
    partner-excluded fault-free runs)."""
    return frozenset(p for p, entry in plan.items()
                     if entry.get("dropout") == 1)


# ---------------------------------------------------------------------------
# Service-level fault plan (MPLC_TPU_SERVICE_FAULT_PLAN)
# ---------------------------------------------------------------------------
#
# Where MPLC_TPU_FAULT_PLAN injects batch-boundary faults into ONE engine,
# this plan targets the multi-tenant sweep service (mplc_tpu/service/):
# entries address jobs by their 1-based SUBMISSION ordinal, so a two-tenant
# isolation test can fault exactly tenant A's job and assert tenant B's
# results bit-identical to a solo run. Comma-separated entries:
#
#   crash@job2:batch3      an InjectedCrash at the dispatch boundary of
#                          job 2's 3rd device batch (batch ordinals are
#                          per-JOB: each tenant engine counts its own)
#   oom@job2:batch3        ditto, InjectedOom (drives that job's private
#                          degrade ladder; other tenants' caps untouched)
#   transient@job2:batch3  ditto, InjectedTransient (rides the retry rung)
#   reject@job4            admission control refuses the 4th submission
#                          (clean ServiceRejected, counted as rejected)
#   stall@job1:sec2        the scheduler sleeps 2 s before job 1's next
#                          quantum (a simulated hang; consumed once, and
#                          the stall bills against THAT job's deadline)
#
# Batch-kind entries are installed into the target job's private engine
# injector at job start, so the firing semantics (once per entry, retries
# keep their ordinal) are exactly `FaultInjector`'s. Malformed entries
# warn and are skipped — same contract as the other plans.
#
# Chaos mode (the load harness's randomized-but-seeded extension):
#
#   chaos@rate0.05:seed7   every submitted job independently draws, with
#                          probability 0.05, ONE random fault — an
#                          injected crash, a transient, or a scheduler
#                          stall — from a generator seeded by
#                          (seed, job ordinal). The draw depends only on
#                          the ordinal, never on scheduling order or
#                          thread interleaving, so a chaos run is
#                          REPLAYABLE: same seed + same submission order
#                          = same faults, under any worker count.
#
# A chaos entry composes with explicit per-job entries (both apply); at
# most one chaos entry is honored per plan (a duplicate warns and is
# ignored). Chaos never injects `reject` or `oom`: admission decisions
# stay deterministic, and an OOM would re-shape the victim's batch caps
# rather than exercise the retry/stall recovery paths the harness is
# probing (it remains available as an explicit per-job entry).

_SERVICE_ENTRY_RE = re.compile(
    r"^(crash|oom|transient)@job([0-9]+):batch([0-9]+)$"
    r"|^(reject)@job([0-9]+)$"
    r"|^(stall)@job([0-9]+):sec([0-9]+(?:\.[0-9]+)?)$"
    r"|^(chaos)@rate([0-9]+(?:\.[0-9]+)?):seed([0-9]+)$")


def parse_service_fault_plan(spec: str | None) -> dict:
    """`{job_ordinal: {"batch": {(site, ordinal): [kind, ...]},
    "reject": bool, "stall_sec": float}}` from the service-plan grammar,
    plus — when the plan carries a chaos entry — a `"chaos"` key (a
    string, so it can never collide with the integer job ordinals)
    holding `{"rate": float, "seed": int}`. Job ordinals are 1-based
    submission order. Malformed entries warn and are dropped;
    empty/unset spec is the empty plan."""
    plan: dict = {}
    if not spec:
        return plan

    def slot(job: int) -> dict:
        return plan.setdefault(job, {"batch": {}, "reject": False,
                                     "stall_sec": 0.0})

    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _SERVICE_ENTRY_RE.match(entry)
        if m is None:
            warnings.warn(
                f"{SERVICE_FAULT_PLAN_ENV}: ignoring malformed entry "
                f"{entry!r} (expected <crash|oom|transient>@job<J>:batch<B> "
                "| reject@job<J> | stall@job<J>:sec<F> | "
                "chaos@rate<F>:seed<N>)", stacklevel=2)
            continue
        if m.group(1):  # batch-boundary kind
            job, ordinal = int(m.group(2)), int(m.group(3))
            if job < 1 or ordinal < 1:
                warnings.warn(
                    f"{SERVICE_FAULT_PLAN_ENV}: ignoring entry {entry!r} "
                    "(job and batch ordinals are 1-based)", stacklevel=2)
                continue
            slot(job)["batch"].setdefault(
                ("dispatch", ordinal), []).append(m.group(1))
        elif m.group(4):  # reject
            job = int(m.group(5))
            if job < 1:
                warnings.warn(
                    f"{SERVICE_FAULT_PLAN_ENV}: ignoring entry {entry!r} "
                    "(job ordinals are 1-based)", stacklevel=2)
                continue
            slot(job)["reject"] = True
        elif m.group(6):  # stall
            job, sec = int(m.group(7)), float(m.group(8))
            if job < 1:
                warnings.warn(
                    f"{SERVICE_FAULT_PLAN_ENV}: ignoring entry {entry!r} "
                    "(job ordinals are 1-based)", stacklevel=2)
                continue
            slot(job)["stall_sec"] += sec
        else:  # chaos
            rate, seed = float(m.group(10)), int(m.group(11))
            if not 0.0 <= rate <= 1.0:
                warnings.warn(
                    f"{SERVICE_FAULT_PLAN_ENV}: ignoring entry {entry!r} "
                    "(chaos rate must be in [0, 1])", stacklevel=2)
                continue
            if "chaos" in plan:
                warnings.warn(
                    f"{SERVICE_FAULT_PLAN_ENV}: ignoring duplicate chaos "
                    f"entry {entry!r} (keeping the first)", stacklevel=2)
                continue
            plan["chaos"] = {"rate": rate, "seed": seed}
    return plan


# chaos stall draws are short: the point is scheduling jitter (a quantum
# that takes noticeably longer than its work), not wall-clock burn — a
# thousand-job harness run at rate 0.05 sleeps ~1-4 s total
_CHAOS_KINDS = ("crash", "transient", "stall")
_CHAOS_STALL_RANGE = (0.02, 0.2)
_CHAOS_MAX_BATCH = 3


def chaos_entry(chaos: "dict | None", ordinal: int) -> "dict | None":
    """The chaos plan's deterministic per-job draw: None (no fault for
    this submission) or a plan-slot-shaped entry — `{"batch": {...},
    "reject": False, "stall_sec": s}` — to merge with any explicit entry
    for the same ordinal. The generator is seeded by (seed, ordinal)
    alone, so the draw is identical under any worker count, submission
    interleaving or retry schedule; batch-kind faults target an early
    batch ordinal (1..3) so they reliably fire even on small games."""
    if not chaos:
        return None
    rate = float(chaos.get("rate", 0.0))
    if rate <= 0.0:
        return None
    import random
    rng = random.Random((int(chaos.get("seed", 0)) << 24) ^ int(ordinal))
    if rng.random() >= rate:
        return None
    kind = rng.choice(_CHAOS_KINDS)
    if kind == "stall":
        lo, hi = _CHAOS_STALL_RANGE
        return {"batch": {}, "reject": False,
                "stall_sec": round(rng.uniform(lo, hi), 3)}
    batch = rng.randint(1, _CHAOS_MAX_BATCH)
    return {"batch": {("dispatch", batch): [kind]}, "reject": False,
            "stall_sec": 0.0}


def merge_service_entries(*entries) -> "dict | None":
    """Combine explicit and chaos-drawn plan entries for one job into a
    fresh slot dict (batch fault lists concatenated per boundary, stall
    seconds summed, reject OR'd). Returns None when every input is None
    — the common no-fault case stays allocation-free."""
    live = [e for e in entries if e]
    if not live:
        return None
    out = {"batch": {}, "reject": False, "stall_sec": 0.0}
    for e in live:
        for key, kinds in (e.get("batch") or {}).items():
            out["batch"].setdefault(key, []).extend(kinds)
        out["reject"] = out["reject"] or bool(e.get("reject"))
        out["stall_sec"] += float(e.get("stall_sec") or 0.0)
    return out


def service_fault_plan_from_env() -> dict:
    return parse_service_fault_plan(os.environ.get(SERVICE_FAULT_PLAN_ENV))


# ---------------------------------------------------------------------------
# Router-level chaos (MPLC_TPU_ROUTER_FAULT_PLAN) — shard-granular faults
# the fleet router (service/router.py) injects into its OWN routing
# table, the way the service plan above injects into one scheduler:
#
#   shardkill@shard1:sec5   kill the named shard 5 seconds into the run
#                           (the router abandons the shard WITHOUT a
#                           clean shutdown — its state file goes stale,
#                           its journal keeps the incomplete jobs — and
#                           failover must resubmit them elsewhere)
#
# The shard name matches a routing-table shard id exactly, or `shard<N>`
# addresses the N-th shard (0-based) of the router's table — so a test
# plan works against auto-generated `pid<...>` shard ids too. Times are
# measured from FleetRouter construction (or its clock_reset()).

_ROUTER_ENTRY_RE = re.compile(
    r"^(shardkill)@([A-Za-z0-9_.-]+):sec([0-9]+(?:\.[0-9]+)?)$")


def parse_router_fault_plan(spec: str | None) -> list:
    """`[{"kind": "shardkill", "shard": str, "at_sec": float}, ...]`
    sorted by fire time, from the router-plan grammar above. Malformed
    entries warn and are dropped (a typo in a chaos plan must never
    itself crash a routed run); empty/unset spec is the empty plan."""
    plan: list = []
    if not spec:
        return plan
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _ROUTER_ENTRY_RE.match(entry)
        if m is None:
            warnings.warn(
                f"{ROUTER_FAULT_PLAN_ENV}: ignoring malformed entry "
                f"{entry!r} (expected shardkill@<shard>:sec<F>)",
                stacklevel=2)
            continue
        plan.append({"kind": m.group(1), "shard": m.group(2),
                     "at_sec": float(m.group(3))})
    plan.sort(key=lambda e: e["at_sec"])
    return plan


def router_fault_plan_from_env() -> list:
    return parse_router_fault_plan(os.environ.get(ROUTER_FAULT_PLAN_ENV))


def normalized_plan_repr(plan: dict) -> str:
    """Canonical string form of a parsed partner plan (sorted, stable) —
    the cache-fingerprint field: a coalition cache built under one partner
    fault plan describes a DIFFERENT game than any other plan's."""
    parts = []
    for pid in sorted(plan):
        for kind in sorted(plan[pid]):
            parts.append(f"{kind}@p{pid}:{plan[pid][kind]}")
    return ",".join(parts)
