"""Deterministic fault injection for the coalition sweep engine.

Long Shapley sweeps die to three families of failure on real fleets:
transient XLA/runtime errors (tunnel hiccups, preempted programs), HBM
exhaustion (RESOURCE_EXHAUSTED on a batch that autotuned too wide), and
hard kills mid-run (OS OOM killer, preemption, power). Every recovery
path in `contrib/engine.py` — retry/backoff, cap degradation, autosave
resume — must be testable on CPU in the fast tier, so this module turns
each failure family into an *injectable*, deterministic event.

Plan grammar (`MPLC_TPU_FAULT_PLAN`): comma-separated entries

    <kind>@<site><ordinal>

      kind  ::= transient | oom | crash
      site  ::= batch   (the dispatch boundary of the Nth device batch)
              | harvest (the result-fetch boundary of the Nth batch)

    e.g.  MPLC_TPU_FAULT_PLAN=transient@batch3,oom@batch5,crash@batch7

Batches are numbered 1-based in engine dispatch order, counted once per
batch (a RETRY of batch N keeps ordinal N — so `transient@batch3` fails
batch 3's first attempt and lets the bit-identical retry through).
Repeating an entry queues multiple faults at the same boundary
(`transient@batch1,transient@batch1` fails the first attempt AND the
first retry). Each entry fires exactly once.

Injected exception classes mirror the real failures' types so the
engine's classifier code paths are the ones exercised:

  - `InjectedTransient` subclasses the runtime's `XlaRuntimeError` (when
    available) with an `INTERNAL:` status prefix — retryable.
  - `InjectedOom` ditto with a `RESOURCE_EXHAUSTED:` prefix — triggers
    cap degradation, never retried as-is.
  - `InjectedCrash` subclasses `BaseException` (like `KeyboardInterrupt`)
    so no recovery path can swallow it — it simulates a process kill and
    unwinds everything; resume happens from the autosave in a new engine.

Malformed plan entries warn and are skipped: a typo in a fault plan must
never itself crash a production run.
"""

from __future__ import annotations

import os
import re
import warnings

FAULT_PLAN_ENV = "MPLC_TPU_FAULT_PLAN"

try:  # the concrete class jax raises for device/runtime failures
    from jaxlib.xla_extension import XlaRuntimeError as _XlaRuntimeError
except Exception:  # pragma: no cover - toolchain without the symbol
    _XlaRuntimeError = RuntimeError


class InjectedTransient(_XlaRuntimeError):
    """A retryable runtime failure (same class as the real thing)."""


class InjectedOom(_XlaRuntimeError):
    """An injected RESOURCE_EXHAUSTED — drives the cap-degradation ladder."""


class InjectedCrash(BaseException):
    """A simulated hard kill. BaseException: retry/degradation code paths
    catching `Exception` can never swallow it, mirroring a real SIGKILL's
    absence of in-process recovery."""


# Real XlaRuntimeError messages lead with a gRPC-style status code. Codes
# that indicate a broken *program or request* are permanent: retrying the
# identical dispatch can only fail identically. Everything else (INTERNAL,
# UNAVAILABLE, DEADLINE_EXCEEDED, ABORTED, UNKNOWN, ...) is presumed
# transient — the tunnel/fleet class of failure retries are for.
_PERMANENT_STATUS = ("INVALID_ARGUMENT", "NOT_FOUND", "FAILED_PRECONDITION",
                     "UNIMPLEMENTED", "PERMISSION_DENIED", "UNAUTHENTICATED")
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating")


def is_oom(err: BaseException) -> bool:
    """True for HBM/host exhaustion failures: the cap-degradation family,
    never blind-retried (the identical batch would exhaust identically)."""
    if isinstance(err, InjectedOom):
        return True
    if not isinstance(err, Exception):
        return False
    msg = str(err)
    return any(m in msg for m in _OOM_MARKERS)


def is_transient(err: BaseException) -> bool:
    """True for failures worth retrying bit-identically: injected
    transients and real `XlaRuntimeError`s whose status code is not in the
    permanent family. OOM is classified separately (`is_oom`); plain
    Python exceptions (bugs) are never transient."""
    if isinstance(err, InjectedTransient):
        return True
    if is_oom(err):
        return False
    if _XlaRuntimeError is RuntimeError:
        # toolchain without the real class: every RuntimeError would
        # match — refuse to blind-retry host-side bugs there
        return False
    if not isinstance(err, _XlaRuntimeError):
        return False
    msg = str(err)
    return not any(msg.lstrip().startswith(code) for code in _PERMANENT_STATUS)


_ENTRY_RE = re.compile(
    r"^(transient|oom|crash)@(batch|harvest)([0-9]+)$")


def parse_fault_plan(spec: str | None) -> dict:
    """`{(site, ordinal): [kind, ...]}` from the plan grammar. Unknown or
    malformed entries warn and are dropped; an empty/unset spec is an
    empty plan (the production no-op)."""
    plan: dict = {}
    if not spec:
        return plan
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _ENTRY_RE.match(entry)
        if m is None or int(m.group(3)) < 1:
            warnings.warn(
                f"{FAULT_PLAN_ENV}: ignoring malformed entry {entry!r} "
                f"(expected <transient|oom|crash>@<batch|harvest><N>, N >= 1)",
                stacklevel=2)
            continue
        kind, site, ordinal = m.group(1), m.group(2), int(m.group(3))
        # 'batch' is the dispatch boundary in the engine's vocabulary
        site = "dispatch" if site == "batch" else site
        plan.setdefault((site, ordinal), []).append(kind)
    return plan


class FaultInjector:
    """Consulted by the engine at every dispatch/harvest boundary.

    `check(site, ordinal)` raises the next planned fault for that
    boundary, at most once per plan entry; with an empty plan it is a
    no-op attribute read. The engine numbers batches itself and passes
    the ordinal in, so retries of a batch re-check the SAME ordinal and a
    consumed entry lets the retry through — that property is what makes
    `transient@batchK` mean "batch K fails once, then recovers"."""

    __slots__ = ("plan", "injected")

    def __init__(self, plan: dict | None = None):
        self.plan = plan or {}
        self.injected = 0

    @classmethod
    def from_env(cls) -> "FaultInjector":
        return cls(parse_fault_plan(os.environ.get(FAULT_PLAN_ENV)))

    @property
    def armed(self) -> bool:
        return bool(self.plan)

    def check(self, site: str, ordinal: int) -> None:
        if not self.plan:
            return
        kinds = self.plan.get((site, ordinal))
        if not kinds:
            return
        kind = kinds.pop(0)
        if not kinds:
            del self.plan[(site, ordinal)]
        self.injected += 1
        from .obs import metrics as obs_metrics
        from .obs import trace as obs_trace
        obs_metrics.counter("engine.faults_injected").inc()
        obs_trace.event("engine.fault", kind=kind, site=site, ordinal=ordinal)
        where = f"({site} boundary, batch {ordinal})"
        if kind == "transient":
            raise InjectedTransient(f"INTERNAL: injected transient fault {where}")
        if kind == "oom":
            raise InjectedOom(
                f"RESOURCE_EXHAUSTED: injected device OOM {where}")
        raise InjectedCrash(f"injected crash {where}")
