"""Sweep-as-a-service: the multi-tenant scheduler.

A `SweepService` is a long-lived process-level engine front end: tenants
submit `Scenario` + method jobs onto a bounded queue and get a `SweepJob`
handle that streams per-coalition values back incrementally and resolves
to the method's contributivity scores. A pool of
`MPLC_TPU_SERVICE_WORKERS` worker threads (default 1) round-robins a
scheduling quantum ("slice") across active jobs — each worker pinned to
a device slot (`worker index % local device count`; uncommitted
computation inside its quanta defaults onto that device when the host
has more than one) and beating its OWN heartbeat, so many concurrent
contributivity games share one process, one device pool and one program
bank without any tenant monopolizing the device, and one wedged worker
flips only its own liveness on /healthz.

The headline is the fault model, not the queue:

  **Per-tenant fault isolation.** Every job runs on its own private
  `CharacteristicEngine` — private memo, private retry/degrade ladder,
  private fault injector — so a transient error, OOM, fault-plan
  injection or outright crash attributable to tenant A re-buckets/retries
  only A's batches and can never numerically perturb tenant B: B's values
  are bit-identical to a solo-engine run of the same scenario
  (equality-tested in tests/test_service.py). What tenants SHARE is the
  compiled-program bank, in its shape-scoped mode (`ProgramBank
  shared=True`): same `(slots, width)` bucket => same banked executable
  regardless of which game a subset came from, so a second tenant of the
  same shape compiles nothing (`service.cross_tenant_packed_batches`
  counts the batches that rode another tenant's programs). A job whose
  attempt dies with a retryable failure (transient, OOM that escaped the
  engine ladder, injected crash) is re-queued — its harvested values
  persist in the engine memo and the journal, so the continuation is
  bit-identical — and quarantined after `MPLC_TPU_MAX_RETRIES` failed
  attempts instead of retrying forever. Permanent failures (a classified
  `LadderExhaustedError`, a genuine bug) quarantine immediately.

  **Admission control, priorities and deadlines.** The queue is bounded
  (`MPLC_TPU_SERVICE_MAX_PENDING`): past the bound, `submit` raises
  `ServiceOverloaded` — a clean, synchronous backpressure signal
  carrying a `retry_after_sec` hint (the live queue-wait p50; 0.0 with
  no history), never a silent drop. Jobs carry an integer priority tier
  (`submit(..., priority=)`, default
  `MPLC_TPU_SERVICE_PRIORITY_DEFAULT`; higher = more important): the run
  queue (service/admission.py `TierQueue`) weights scheduling quanta by
  `tier + 1` via stride scheduling, round-robin FIFO within a tier. On
  top sits the SLO-driven overload governor (`AdmissionController`):
  when the queue-wait p99 — over a sliding window of recent waits plus
  the live ages of everything still queued — crosses
  `MPLC_TPU_SERVICE_SHED_P99_SEC` (0/unset = governor off), the
  scheduler first DEFERS the lowest queued tier, then SHEDS its newest
  never-started jobs with a classified, journaled `JobShed` (counted in
  `service.jobs_shed`, separate from rejected/cancelled/quarantined,
  and also carrying `retry_after_sec`). A per-job `deadline_sec` is
  enforced cooperatively at every batch boundary (the engine's progress
  hook) and every quantum boundary: an expired job raises `JobCancelled`
  between batches — no in-flight dispatch is abandoned mid-device — its
  engine is dropped (the only references to its device buffers), and the
  cancellation is journaled; a deadline that expires while the job is
  STILL QUEUED cancels before any work and records no queue-wait/ttfv
  SLO sample (an expired wait is not a latency datum). `shutdown(
  drain=True)` stops admissions and completes every queued job before
  returning.

  **Journaled crash recovery.** When constructed with a `journal_path`,
  every accepted submission and every harvested `(tenant, subset, value)`
  is appended to a checksummed, fsync'd write-ahead journal
  (service/journal.py) BEFORE the service acts on it. A killed process
  restarts by constructing a new service on the same path: the journal
  replays (quarantining a torn tail record), `recovered_jobs()` lists the
  interrupted submissions, and re-submitting a scenario under its old
  `job_id` seeds the fresh engine's memo with every journaled value — the
  sweep completes training only what was never harvested, bit-identically
  to an uninterrupted run (same per-coalition rng-fold streams; the
  engine's batch composition never affects v(S)).

  **Device-seconds metering.** Every quantum bills its engine's
  device-meter delta (obs/devcost.py) to the owning tenant: fenced-
  sample-extrapolated measured seconds when the engine fences
  (MPLC_TPU_DEVICE_FENCE_RATE), XLA-cost-model seconds when fences are
  off, host span as the explicit last resort — the basis rides the
  `service.slice` span and the terminal `service.job` event. The meter
  is exported per tenant (`service.device_seconds{tenant=...}` on
  /metrics, `tenant_device_seconds` on /varz), drives the report's
  `cost_share` (span-seconds kept as `host_share`), and is JOURNALED
  with every job terminal so a kill→restart never loses billing.
  `submit(..., profile=True)` additionally captures a `jax.profiler`
  device trace of exactly that job's quanta into
  `MPLC_TPU_PROFILE_DIR/<job_id>` (best-effort; path on the terminal
  event).

  **The live contributivity tier.** `live_game(scenario, tenant=...)`
  registers a tenant's RESIDENT incremental game (mplc_tpu/live/:
  recorded rounds stay in-process, journaled, round-stamp invalidated),
  `append_round(tenant, deltas, weights)` feeds it, and
  `submit_live(tenant, method=..., prune=...)` runs "what is my Shapley
  value now" as a LOW-LATENCY job class on this same machinery —
  admission bound, tier-weighted quanta (default one tier above the
  batch default), overload shedding, deadlines
  (`MPLC_TPU_LIVE_QUERY_DEADLINE_SEC` default), journaled terminals —
  answered from reconstruction against banked programs with zero
  training batches. The resident game's engine is shared across queries
  and never released at job completion; per-tenant games appear on
  /varz (`live_games`) and the `live.rounds_resident` gauge.

Live telemetry: when `MPLC_TPU_METRICS_PORT` is set, constructing a
service starts the obs/export.py HTTP plane — /metrics (Prometheus,
incl. the per-tenant SLO histograms instrumented here: queue wait,
time-to-first-value, slice duration, deadline misses, retries),
/healthz (worker heartbeat age; 503 when a running job's quantum stalls
past STALL_HEALTHY_SEC) and /varz (the per-job state table via
`varz_view`). With it unset no thread or socket exists; `health_view()`
and `varz_view()` remain directly callable either way. Quarantines dump
the crash flight recorder (obs/flight.py) and reference the postmortem
file from the quarantine log line.

Deterministic testability: `MPLC_TPU_SERVICE_FAULT_PLAN` (faults.py)
addresses jobs by submission ordinal — `crash@job2:batch3` installs an
injected crash into job 2's private engine injector, `reject@job4` makes
admission refuse the 4th submission, `stall@job1:sec2` sleeps the
scheduler before job 1's next quantum (billed against job 1's own
deadline; with a single shared device, a stalled tenant's compute slot is
indistinguishable from slow compute for whoever is behind it in line).
A `chaos@rate0.05:seed7` entry extends the plan with randomized-but-
replayable injection: every submission independently draws (seeded by
plan seed x job ordinal, so the draw survives any worker interleaving)
one crash/transient/stall fault with the given probability — the load
harness's (scripts/load_gen.py) way of proving the isolation and
accounting machinery holds at thousands of jobs.
"""

from __future__ import annotations

import contextlib
import hmac
import itertools
import logging
import os
import threading
import time
from collections import deque

import numpy as np

from .. import constants, faults
from ..obs import devcost
from ..obs import export as obs_export
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .admission import AdmissionController, TierQueue
from .journal import SweepJournal
from .packer import CrossTenantPacker

logger = logging.getLogger("mplc_tpu")


def _live_residency_stats() -> dict:
    """The residency manager's /varz block (lazy import: the live tier
    is only loaded once a service actually touches it)."""
    from ..live import residency
    return residency.stats()


# /healthz stall rule: the service is unhealthy when a job is RUNNING and
# the worker heartbeat (beaten at every quantum start and every batch
# boundary) is older than this — a single device batch legitimately
# longer than the bound would false-flag, so it is generous. Idle
# services (no running job) are healthy at any heartbeat age.
STALL_HEALTHY_SEC = 30.0

_SERVICE_IDS = itertools.count(1)


class ServiceError(RuntimeError):
    """Base class for service-level failures."""


class ServiceClosed(ServiceError):
    """submit() after shutdown started."""


class ServiceOverloaded(ServiceError):
    """Backpressure: the bounded submission queue is full. Resubmit after
    draining — nothing about the request itself is wrong.

    `retry_after_sec` is the live backoff hint: the service's windowed
    queue-wait p50 (roughly one queue's worth of patience), or 0.0 when
    no job has ever been scheduled. Callers should sleep about that long
    before resubmitting instead of hammering `submit` in a tight loop —
    the load harness (scripts/load_gen.py) does exactly that.

    `cluster` (sharded fleet deployments, MPLC_TPU_FLEET_STATE_DIR) is
    the cross-shard queue view at rejection time — its `least_loaded`
    shard is the redirect hint; None outside a fleet."""

    cluster: "dict | None" = None

    def __init__(self, msg: str, retry_after_sec: float = 0.0):
        super().__init__(msg)
        self.retry_after_sec = float(retry_after_sec)


class ServiceRejected(ServiceError):
    """Admission control refused the job (fault-plan injected reject)."""


class ServiceAuthError(ServiceError):
    """The submit-path credential check failed: `MPLC_TPU_METRICS_TOKEN`
    is set (the service authenticates tenants) and the presented
    credential is neither the master/operator token nor the tenant's own
    HMAC credential (`obs.export.tenant_token(master, tenant)`). A
    SYNCHRONOUS submission error — nothing was accepted, journaled or
    quarantined; the caller's identity claim was simply wrong."""


class JobShed(ServiceError):
    """The overload governor terminated this still-queued job to protect
    the queue-wait SLO of higher-priority work (service/admission.py).
    A classified, journaled outcome — never a silent drop: the job's
    status is `"shed"`, it is counted in `service.jobs_shed` (separate
    from rejected/cancelled/quarantined), and `retry_after_sec` carries
    the same live backoff hint as `ServiceOverloaded`. Nothing about the
    request itself is wrong; resubmit later (or at a higher priority)."""

    def __init__(self, msg: str, retry_after_sec: float = 0.0):
        super().__init__(msg)
        self.retry_after_sec = float(retry_after_sec)


class JobQuarantined(ServiceError):
    """The job exhausted its retry budget (or failed permanently) and was
    quarantined; `__cause__` carries the terminal error."""


class JobCancelled(Exception):
    """Raised cooperatively at a batch/quantum boundary when a job's
    deadline expired. Plain Exception (not ServiceError): it unwinds
    through the engine's recovery ladder untouched (`is_transient` /
    `is_oom` are both False for it)."""


class SweepJob:
    """Handle for one submitted job. Thread-safe consumer surface:
    `stream()` yields `(subset, value)` incrementally as batches harvest,
    `result()` blocks for the final contributivity scores."""

    def __init__(self, service, job_id, tenant, scenario, method,
                 deadline_sec, ordinal, priority=0, profile=False):
        self.service = service
        self.job_id = job_id
        self.tenant = tenant
        self.scenario = scenario
        self.method = method
        self.deadline_sec = deadline_sec
        self.ordinal = ordinal  # 1-based submission ordinal (fault plan)
        self.priority = int(priority)  # tier: higher = more important
        # per-job device profiling (utils.profile_trace): when True and
        # MPLC_TPU_PROFILE_DIR is set, every quantum of THIS job runs
        # under a jax.profiler device trace into <dir>/<job_id>; the
        # trace path lands on the terminal service.job event
        self.profile = bool(profile)
        self.profile_path: "str | None" = None
        # metered device-seconds billed to this job (obs/devcost.py):
        # fenced-sample extrapolation when the engine fences, cost-model
        # (XLA flops / fleet peak) when fences are off, host span as the
        # explicit last resort — `device_basis` names the best basis seen
        self.device_seconds = 0.0
        self.device_basis: "str | None" = None
        # the job's resolved service-fault entry (explicit plan merged
        # with the chaos draw), snapshotted at submit so consumption
        # (stall fires once) is per-job state, never shared plan state
        self._fault_entry: "dict | None" = None
        self.status = "queued"
        self.engine = None
        self.subsets = None
        # live-query jobs (submit_live): {"game", "method", "prune", "kw"}
        # — the quantum answers from the tenant's RESIDENT LiveGame
        # instead of building a private sweep engine. `_live_billed`
        # carries the quantum's game-lock-scoped (device_sec, basis)
        # delta to the slice span / failure-billing paths (the generic
        # pre-quantum meter snapshot is skipped: the shared meter may be
        # mid-sibling-quantum at snapshot time)
        self._live_query: "dict | None" = None
        self._live_billed: "tuple | None" = None
        self._live_counts: "dict | None" = None
        self.live_result = None
        # the resolved QueryPlan when the caller asked for
        # method="auto": pinned at submit time for live queries (the
        # resident game's meter is available synchronously), at quantum
        # run time for batch jobs — either way it is journaled and lands
        # on the terminal service.job event so a replay runs the SAME
        # concrete method/kwargs
        self.plan = None
        self.attempts = 0
        self.recovered_values = 0
        self.packed_batches = 0
        self.scores = None
        # the completed job's full v(S) table (host-side floats), stashed
        # at completion so the engine's device state can be released
        self.values: "dict | None" = None
        self.error: "BaseException | None" = None
        self.submitted_at = time.monotonic()
        # SLO landmarks (per-tenant histograms + the report's slo row):
        # first scheduling quantum (queue wait) and first streamed value
        self.first_quantum_at: "float | None" = None
        self.first_value_at: "float | None" = None
        self.deadline_missed = False
        self._done = threading.Event()
        self._journal_cursor = 0    # items of charac_fct_values journaled
        self._cancel_raised = False
        self._slice_packed: dict = {}
        self._stream: list = []     # [(subset, value)] in harvest order
        self._stream_lock = threading.Condition()

    # -- consumer surface ------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: "float | None" = None):
        """Block for the job's contributivity scores; raises the job's
        terminal error (JobQuarantined / JobCancelled) on failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"job {self.job_id} not finished within {timeout} s "
                f"(status={self.status})")
        if self.error is not None:
            raise self.error
        return self.scores

    def stream(self, timeout: "float | None" = None):
        """Yield `(subset, value)` pairs as they are harvested, ending
        when the job reaches a terminal state. Values arrive in journal
        order; a consumer that starts late still sees every pair."""
        i = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._stream_lock:
                while i >= len(self._stream) and not self._done.is_set():
                    wait = (None if deadline is None
                            else max(deadline - time.monotonic(), 0.0))
                    if wait == 0.0:
                        raise TimeoutError(
                            f"job {self.job_id} stream stalled")
                    self._stream_lock.wait(wait)
                if i < len(self._stream):
                    item = self._stream[i]
                else:
                    return
            yield item
            i += 1

    # -- service-side helpers -------------------------------------------

    def _push_stream(self, items) -> None:
        items = list(items)
        with self._stream_lock:
            self._stream.extend(items)
            self._stream_lock.notify_all()
        if items and self.first_value_at is None:
            # time-to-first-value: submit -> the first v(S) a consumer
            # could observe (journal-recovered seeds count — the tenant
            # sees them just the same)
            self.first_value_at = time.monotonic()
            obs_metrics.histogram(
                "service.time_to_first_value_sec",
                tenant=self.tenant).observe(
                    self.first_value_at - self.submitted_at)

    def _slo_attrs(self) -> dict:
        """SLO fields attached to the terminal `service.job` event (the
        report's slo row reads them back out)."""
        return {
            "queue_wait_sec": (self.first_quantum_at - self.submitted_at
                               if self.first_quantum_at is not None
                               else None),
            "ttfv_sec": (self.first_value_at - self.submitted_at
                         if self.first_value_at is not None else None),
            "deadline_missed": self.deadline_missed,
        }

    def _finish(self) -> None:
        with self._stream_lock:
            self._stream_lock.notify_all()
        self._done.set()

    def _deadline_expired(self) -> bool:
        return (self.deadline_sec is not None
                and time.monotonic() - self.submitted_at > self.deadline_sec)


class _WorkerSlot:
    """One pool worker's scheduler-visible state: its thread, its own
    heartbeat (beaten at quantum starts and batch boundaries — a wedged
    worker flips only ITS liveness on /healthz), the job it is currently
    running, and the device slot it is pinned to."""

    __slots__ = ("index", "thread", "heartbeat", "running_job",
                 "device_slot", "device")

    def __init__(self, index: int, device_slot: int = 0, device=None):
        self.index = index
        self.thread = None
        self.heartbeat = time.monotonic()
        self.running_job = None
        self.device_slot = device_slot
        self.device = device

    def view(self, now: float, stall_sec: float) -> dict:
        age = now - self.heartbeat
        running = self.running_job
        alive = self.thread is None or self.thread.is_alive()
        return {
            "worker": self.index,
            "alive": alive,
            "heartbeat_age_sec": age,
            "running_job": running.job_id if running is not None else None,
            "stalled": running is not None and age > stall_sec,
            "device_slot": self.device_slot,
        }


# jax.profiler admits ONE trace at a time per process: quanta of
# profiled jobs serialize their captures through this lock; a quantum
# that can't get it (another profiled job's quantum is mid-capture on a
# sibling worker) simply runs unprofiled — profiling is best-effort
# observability, never a scheduling constraint
_PROFILE_LOCK = threading.Lock()
_profile_warned = False


class _QuantumProfiler:
    """Best-effort `jax.profiler` device trace of ONE scheduling quantum
    (utils.profile_trace's start/stop pair, serialized process-wide).
    NEVER raises into the quantum: a profiler failure is a warning and
    the quantum runs unprofiled — a job must not quarantine because
    observability hiccuped."""

    def __init__(self, job, path: str):
        self.job = job
        self.path = path
        self._active = False

    def __enter__(self) -> "_QuantumProfiler":
        global _profile_warned
        if not _PROFILE_LOCK.acquire(blocking=False):
            return self  # a sibling quantum owns the profiler
        try:
            import jax
            jax.profiler.start_trace(self.path)
            self._active = True
            self.job.profile_path = self.path
        except Exception as e:
            _PROFILE_LOCK.release()
            if not _profile_warned:
                _profile_warned = True
                logger.warning(
                    "service: jax.profiler trace for job %s failed to "
                    "start (%s); the job runs unprofiled",
                    self.job.job_id, e)
        return self

    def __exit__(self, *exc) -> bool:
        if self._active:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                logger.warning(
                    "service: jax.profiler stop_trace failed for job %s "
                    "(%s)", self.job.job_id, e)
            finally:
                self._active = False
                _PROFILE_LOCK.release()
        return False


class SweepService:
    """The long-lived multi-tenant sweep scheduler (module docstring)."""

    def __init__(self, journal_path=None, max_pending: "int | None" = None,
                 slice_coalitions: "int | None" = None, start: bool = True,
                 workers: "int | None" = None,
                 shed_p99_sec: "float | None" = None):
        self._lock = threading.Condition()
        self._queue = TierQueue()
        self._jobs: dict = {}
        self._ordinal = 0
        self._closed = False
        self._abandoned = False  # abandon(): SIGKILL-shaped worker stop
        self._running_job = None
        self._worker = None
        self._workers: list = []
        self._tl = threading.local()  # .worker: the slot running a quantum
        self._packer = CrossTenantPacker()
        self._plan = faults.service_fault_plan_from_env()
        self._max_pending = (max_pending if max_pending is not None
                             else constants._env_positive_int(
                                 constants.SERVICE_MAX_PENDING_ENV, 32))
        self._slice = (slice_coalitions if slice_coalitions is not None
                       else constants._env_positive_int(
                           constants.SERVICE_SLICE_ENV, 16))
        self._n_workers = (workers if workers is not None
                           else constants._env_positive_int(
                               constants.SERVICE_WORKERS_ENV, 1))
        self._priority_default = constants._env_nonneg_int(
            constants.SERVICE_PRIORITY_DEFAULT_ENV, 0)
        self._admission = AdmissionController(
            shed_p99_sec if shed_p99_sec is not None
            else constants._env_nonneg_float(
                constants.SERVICE_SHED_P99_ENV, 0.0))
        self._max_job_retries = constants._env_positive_int(
            constants.MAX_RETRIES_ENV, 3)
        # the live contributivity tier (mplc_tpu/live/): per-tenant
        # RESIDENT games + the default deadline for the low-latency
        # live-query job class
        self._live_games: dict = {}
        self._live_create_lock = threading.Lock()
        self._live_deadline = constants._env_nonneg_float(
            constants.LIVE_QUERY_DEADLINE_ENV, 0.0)
        self._heartbeat = time.monotonic()
        # fleet scale-out (parallel/fleet.py): when MPLC_TPU_FLEET_STATE_DIR
        # names a shared state dir, this process publishes its queue
        # depth / admission state there (rate-limited, at submits and
        # heartbeats) and reads the CLUSTER aggregate back into /healthz
        # and into ServiceOverloaded redirect hints — the cross-shard
        # queue view the single-process admission governor lacked. Unset
        # = single-process behavior, byte-identical.
        self._fleet_dir = os.environ.get(constants.FLEET_STATE_DIR_ENV) \
            or None
        self._fleet_shard = (os.environ.get(constants.FLEET_SHARD_ID_ENV)
                             or f"pid{os.getpid()}")
        self._fleet_pub_ts = 0.0
        # live telemetry plane: the /metrics//healthz//varz endpoints
        # exist ONLY when MPLC_TPU_METRICS_PORT is set (no thread, no
        # socket otherwise); health/varz providers register either way,
        # so an embedding process can poll them directly
        self._export = obs_export.maybe_start_from_env()
        self._provider_key = f"service{next(_SERVICE_IDS)}"
        # WeakMethod: a service dropped without shutdown() must not keep
        # reporting into /healthz //varz forever (shutdown unregisters
        # explicitly; the weakref covers the leak path)
        import weakref
        obs_export.register_health(self._provider_key,
                                   weakref.WeakMethod(self.health_view))
        obs_export.register_varz(self._provider_key,
                                 weakref.WeakMethod(self.varz_view))
        # streaming ingestion sink (POST /live/<tenant>/round): same
        # WeakMethod lifetime contract as the health/varz providers; the
        # route itself only exists when MPLC_TPU_LIVE_INGEST=1
        obs_export.register_live_ingest(
            self._provider_key, weakref.WeakMethod(self._ingest_live_round))

        # lifetime device-seconds metered per tenant (obs/devcost.py) —
        # fed by every quantum's meter delta AND by journal replay below
        # (terminal records carry the meter), so a restarted service's
        # billing continues where the killed one stopped
        self._tenant_device_seconds: dict = {}
        # journal replay BEFORE the append handle opens: a restart reads
        # history (quarantining a torn tail), then appends to it
        self._journal = None
        self._journal_broken = False
        # terminal jobs retained for handle lookups, FIFO-bounded so a
        # long-lived service's _jobs map can't grow without bound (the
        # caller's own handle keeps an evicted job alive)
        self._terminal_order: deque = deque()
        self._max_terminal_jobs = 256
        # lifetime terminal count: the _jobs map is FIFO-bounded, so
        # counting done entries there would cap /varz's scalar at the
        # retention bound instead of the true total
        self._terminal_seen = 0
        self._recovered: dict = {}
        if journal_path is not None:
            records, _torn = SweepJournal.replay(journal_path)
            for rec in records:
                self._replay_record(rec)
            # restore the /metrics billing counter by RAISING it to the
            # journal's per-tenant totals, never blind-incrementing: the
            # counter is process-global, so a service reconstructed in
            # the SAME process as the one that billed live (tests, an
            # embedding app restarting its service object) must not
            # double-count what both the live path and the journal saw.
            # A fresh process starts at zero and lands exactly on the
            # journaled totals.
            for tenant, total in self._tenant_device_seconds.items():
                c = obs_metrics.counter("service.device_seconds",
                                        tenant=tenant)
                if total > c.value:
                    c.inc(total - c.value)
            self._journal = SweepJournal(journal_path)

        if start:
            self._start_workers()

    def _start_workers(self) -> None:
        """Spin up the worker pool: `MPLC_TPU_SERVICE_WORKERS` threads,
        each pinned to a device slot (`index % local device count`) and
        carrying its own heartbeat. Device pinning is best-effort: with
        one local device (or no importable jax) every slot is slot 0 and
        no placement context is applied."""
        n_dev = 1
        devices = None
        try:
            import jax
            devices = jax.local_devices()
            n_dev = max(len(devices), 1)
        except Exception:  # pragma: no cover - lean process without jax
            pass
        for i in range(self._n_workers):
            w = _WorkerSlot(
                index=i, device_slot=i % n_dev,
                device=(devices[i % n_dev]
                        if devices is not None and n_dev > 1 else None))
            w.thread = threading.Thread(
                target=self._worker_loop, args=(w,), daemon=True,
                name=f"mplc-sweep-service-{i}")
            self._workers.append(w)
            w.thread.start()
        # back-compat alias: PR-9 callers (and shutdown's join loop)
        # treated `_worker` as "the threaded mode is on"
        self._worker = self._workers[0].thread if self._workers else None

    # -- recovery --------------------------------------------------------

    def _replay_record(self, rec: dict) -> None:
        kind = rec.get("type")
        job = rec.get("job")
        if kind == "submit":
            slot = self._recovered.setdefault(
                job, {"values": {}, "done": False, "quarantined": False,
                      "cancelled": False, "shed": False})
            # a resubmission after a previous restart re-journals the
            # submit record: MERGE (keep already-replayed values)
            slot.update(tenant=rec.get("tenant"), method=rec.get("method"),
                        partners_count=rec.get("partners_count"))
        elif kind == "value" and job in self._recovered:
            self._recovered[job]["values"][
                tuple(rec["subset"])] = rec["value"]
        elif kind == "done" and job in self._recovered:
            self._recovered[job]["done"] = True
        elif kind == "quarantine" and job in self._recovered:
            self._recovered[job]["quarantined"] = True
        elif kind == "cancel" and job in self._recovered:
            self._recovered[job]["cancelled"] = True
        elif kind == "shed" and job in self._recovered:
            self._recovered[job]["shed"] = True
        if kind in ("done", "quarantine", "cancel", "shed"):
            # terminal records carry the job's metered device-seconds:
            # restore the per-tenant meter so a kill→restart never
            # loses billing (the /metrics counter is raised AFTER the
            # whole replay — see __init__)
            ds = rec.get("device_seconds")
            if ds:
                tenant = (rec.get("tenant")
                          or (self._recovered.get(job) or {}).get("tenant")
                          or "?")
                self._tenant_device_seconds[tenant] = \
                    self._tenant_device_seconds.get(tenant, 0.0) + float(ds)

    # -- live telemetry providers ---------------------------------------

    def health_view(self) -> dict:
        """The /healthz provider: per-worker liveness/heartbeat ages,
        admission-governor state, queue depth and journal status.

        Each worker beats its OWN heartbeat at quantum starts and batch
        boundaries, so one wedged worker flips only its own `stalled`
        flag in the `workers` block. The service-level `healthy` flips
        False when any worker thread DIED, or when every slot currently
        running a job is stalled past STALL_HEALTHY_SEC (a single-worker
        service therefore keeps the PR-10 behavior: its only quantum
        wedging = unhealthy; in a pool, siblings still making progress
        keep the service up while the `workers` block names the wedged
        one). An idle service is healthy at any heartbeat age. The
        `admission` block surfaces overload BEFORE it becomes a 503:
        governor state (healthy|deferring|shedding), the live queue-wait
        p99 vs the shed threshold, and shed/reject accounting."""
        now = time.monotonic()
        with self._lock:
            running = self._running_job
            queue_depth = len(self._queue)
            pending = sum(1 for j in self._jobs.values() if not j.done)
            closed = self._closed
            workers = [w.view(now, STALL_HEALTHY_SEC)
                       for w in self._workers]
            queued_ages = [now - j.submitted_at
                           for j in self._queue.jobs()]
            admission = self._admission.view(queued_ages)
        # the inline slot (start=False / step() mode) keeps the PR-9
        # single-heartbeat semantics; it only matters when a quantum is
        # actually running there
        inline_age = now - self._heartbeat
        slots = list(workers)
        if not workers or running is not None:
            slots.append({
                "worker": "inline", "alive": True,
                "heartbeat_age_sec": inline_age,
                "running_job": (running.job_id
                                if running is not None else None),
                "stalled": (running is not None
                            and inline_age > STALL_HEALTHY_SEC),
                "device_slot": 0,
            })
        worker_alive = all(w["alive"] for w in workers) if workers else True
        busy = [s for s in slots if s["running_job"] is not None]
        stalled_busy = [s for s in busy if s["stalled"]]
        stalled = bool(stalled_busy)
        all_wedged = bool(busy) and len(stalled_busy) == len(busy)
        running_names = [s["running_job"] for s in busy]
        fleet_view = self._fleet_view()
        extra = {} if fleet_view is None else {"fleet": fleet_view}
        return {
            **extra,
            "healthy": worker_alive and not all_wedged,
            "worker_alive": worker_alive,
            "workers": slots,
            "worker_heartbeat_age_sec": min(
                (s["heartbeat_age_sec"] for s in slots), default=inline_age),
            "stalled": stalled,
            "running_job": running_names[0] if running_names else None,
            "running_jobs": running_names,
            "queue_depth": queue_depth,
            "jobs_pending": pending,
            "closed": closed,
            "admission": admission,
            "journal": ("disabled" if self._journal is None
                        else "broken" if self._journal_broken else "ok"),
        }

    # /varz job-table bound: every non-terminal job is always listed, but
    # only this many of the MOST RECENT terminal jobs — a load-generator
    # run submitting thousands of jobs must not balloon the endpoint
    # response (the full terminal count is retained as a scalar)
    VARZ_TERMINAL_JOBS = 100

    def varz_view(self) -> dict:
        """The /varz provider: the engine-state snapshot — a per-job
        status table (all live jobs + the `VARZ_TERMINAL_JOBS` most
        recent terminal ones; `jobs_total` / `terminal_jobs_total` keep
        the full counts) plus the scheduler's admission/queue knobs."""
        with self._lock:
            recent_terminal = set(
                list(self._terminal_order)[-self.VARZ_TERMINAL_JOBS:])
            jobs = {
                job_id: {
                    "tenant": j.tenant, "method": j.method,
                    "status": j.status, "attempts": j.attempts,
                    "ordinal": j.ordinal, "priority": j.priority,
                    "values_streamed": len(j._stream),
                    "packed_batches": j.packed_batches,
                    "recovered_values": j.recovered_values,
                    "deadline_sec": j.deadline_sec,
                    "age_sec": time.monotonic() - j.submitted_at,
                    "device_seconds": round(j.device_seconds, 6),
                    "device_basis": j.device_basis,
                } for job_id, j in self._jobs.items()
                if not j.done or job_id in recent_terminal}
            listed_terminal = sum(1 for row in jobs.values()
                                  if row["status"] not in ("queued",
                                                           "running"))
            return {
                "jobs": jobs,
                # lifetime totals (the _jobs map itself is FIFO-bounded
                # at 256 terminals, so these come from the monotone
                # counter, not a scan of what happens to be retained)
                "jobs_total": self._terminal_seen + sum(
                    1 for j in self._jobs.values() if not j.done),
                "terminal_jobs_total": self._terminal_seen,
                "terminal_jobs_truncated": max(
                    self._terminal_seen - listed_terminal, 0),
                "queue_depth": len(self._queue),
                "max_pending": self._max_pending,
                "workers": self._n_workers,
                "slice_coalitions": self._slice,
                "admission": self._admission.view(),
                "closed": self._closed,
                "recovered_jobs": len(self._recovered),
                # the live tier's per-tenant resident games: rounds
                # resident, round-stamp, query counts (the dashboard's
                # rounds-resident gauge mirrors live.rounds_resident
                # on /metrics)
                "live_games": {t: g.describe()
                               for t, g in sorted(self._live_games.items())},
                # the process-wide residency manager's state: resident/
                # evicted counts, lifetime evictions/restores, last
                # WAL-restore latency (live/residency.py)
                "live_residency": _live_residency_stats(),
                # lifetime metered device-seconds per tenant (restored
                # from the journal on restart — the billing meter)
                "tenant_device_seconds": {
                    t: round(v, 6)
                    for t, v in sorted(
                        self._tenant_device_seconds.items())},
            }

    def recovered_jobs(self) -> list:
        """Descriptors of journaled submissions from previous service
        lives: `[{job_id, tenant, method, values, done, ...}]`. Resubmit
        each unfinished one with its old `job_id` to complete it — the
        engine memo is seeded from the journaled values, so only
        never-harvested coalitions train."""
        return [{"job_id": jid, "tenant": r.get("tenant"),
                 "method": r.get("method"), "values": len(r["values"]),
                 "done": r["done"], "quarantined": r["quarantined"],
                 "cancelled": r["cancelled"],
                 "shed": r.get("shed", False)}
                for jid, r in self._recovered.items()]

    def adopt_recovered(self, job_id: str, tenant: "str | None" = None,
                        method: "str | None" = None,
                        partners_count: "int | None" = None,
                        values: "dict | None" = None) -> None:
        """Install another shard's journaled partial job into THIS
        service's recovered-jobs table — the fleet router's failover
        path. The router replays a dead shard's WAL
        (`SweepJournal.replay`), hands each incomplete job's harvested
        `{subset_tuple: value}` map here, then resubmits under the old
        `job_id`: `_build_engine` seeds the fresh engine's memo from
        these values exactly as it would from this shard's own journal,
        so the continuation is bit-identical to a solo fault-free run
        and only never-harvested coalitions train. Refuses a `job_id`
        already known to this service (live, or recovered with a
        DIFFERENT seed — adopting over either would mix two games' v(S)
        tables); re-adopting the exact same seed is idempotent, so a
        routing retry that already adopted here (then hit backpressure)
        is a no-op rather than an error."""
        norm = {tuple(s): float(v) for s, v in (values or {}).items()}
        pc = int(partners_count) if partners_count is not None else None
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is shut down")
            if job_id in self._jobs:
                raise ValueError(
                    f"job id {job_id!r} is already live on this service "
                    "— cannot adopt a foreign journal's values for it")
            if job_id in self._recovered:
                slot = self._recovered[job_id]
                if (not slot["done"] and slot["values"] == norm
                        and (pc is None
                             or slot.get("partners_count") is None
                             or pc == slot["partners_count"])):
                    return  # identical seed: idempotent re-adoption
                raise ValueError(
                    f"job id {job_id!r} already has recovered state on "
                    "this service that differs from the adoption "
                    "payload — refusing to overwrite it with a foreign "
                    "journal's")
            self._recovered[job_id] = {
                "values": norm,
                "done": False, "quarantined": False, "cancelled": False,
                "shed": False, "tenant": tenant, "method": method,
                "partners_count": pc}

    # -- submission ------------------------------------------------------

    def _check_credential(self, tenant: str,
                          credential: "str | None") -> None:
        """Authenticate a submit-path tenant claim (PR-12/PR-18 scheme):
        with `MPLC_TPU_METRICS_TOKEN` set, a presented credential must be
        the master/operator token or `tenant_token(master, tenant)` —
        anything else raises `ServiceAuthError` synchronously (an auth
        error is a caller mistake, never a quarantine). `credential=None`
        stays the trusted in-process embedder path (the process that can
        call this method could also read the master token from its own
        environment); the HTTP routed-submit surface REQUIRES the
        credential, so the trust boundary authenticates. With no master
        token configured there is no credential scheme to check against
        and every claim passes, unchanged."""
        if credential is None:
            return
        master = os.environ.get(constants.METRICS_TOKEN_ENV)
        if not master:
            return
        cred = str(credential).encode("utf-8", "surrogatepass")
        ok = hmac.compare_digest(cred, master.encode())
        if not ok:
            expect = obs_export.tenant_token(master, tenant)
            ok = hmac.compare_digest(cred, expect.encode())
        if not ok:
            obs_metrics.counter("service.auth_rejected").inc()
            obs_trace.event("service.auth_reject", tenant=tenant)
            raise ServiceAuthError(
                f"credential does not authenticate tenant {tenant!r} "
                "(expected the master token or tenant_token(master, "
                "tenant))")

    def submit(self, scenario, method: str = "Shapley values",
               tenant: str = "tenant0",
               deadline_sec: "float | None" = None,
               job_id: "str | None" = None,
               priority: "int | None" = None,
               profile: bool = False,
               credential: "str | None" = None,
               _live: "dict | None" = None) -> SweepJob:
        """Accept a Scenario+method job onto the bounded queue.

        `priority` is the job's integer tier (default
        `MPLC_TPU_SERVICE_PRIORITY_DEFAULT`, 0; higher = more
        important): the scheduler weights quanta by `tier + 1` and the
        overload governor defers/sheds the lowest tier first.

        `profile=True` captures a `jax.profiler` device trace of exactly
        this job's quanta into `MPLC_TPU_PROFILE_DIR/<job_id>` (a no-op
        when the dir knob is unset; best-effort — a profiler failure
        degrades to a warning, never a job fault). The trace path is
        recorded on the job's terminal `service.job` event.

        `credential` authenticates the `tenant` claim when
        `MPLC_TPU_METRICS_TOKEN` is set (master token or
        `tenant_token(master, tenant)`; a mismatch raises
        `ServiceAuthError` synchronously). None = the trusted in-process
        caller, unchanged — the HTTP routed-submit surface is where the
        credential is mandatory.

        Raises `ServiceClosed` after shutdown, `ServiceOverloaded` when
        the queue is at `MPLC_TPU_SERVICE_MAX_PENDING` (backpressure —
        its `retry_after_sec` is the live queue-wait p50 backoff hint),
        `ServiceRejected` on a fault-plan injected admission reject,
        `ServiceAuthError` on a bad credential. The accepted submission
        is journaled before this returns."""
        self._check_credential(tenant, credential)
        if _live is not None:
            from ..live import LIVE_METHODS
            if _live["method"] not in LIVE_METHODS:
                raise ValueError(
                    f"unknown live query method {_live['method']!r} "
                    f"(expected one of {LIVE_METHODS})")
        elif method not in constants.CONTRIBUTIVITY_METHODS:
            # validated synchronously: the dispatcher would only log a
            # warning for an unknown name, and a job that "completes"
            # with no scores is worse than a clean submit-time error
            raise ValueError(
                f"unknown contributivity method {method!r} (expected one "
                f"of {constants.CONTRIBUTIVITY_METHODS})")
        if priority is None:
            priority = self._priority_default
        elif int(priority) < 0:
            raise ValueError(
                f"priority must be a non-negative tier, got {priority!r}")
        # cross-shard redirect data is read OUTSIDE the lock (the fleet
        # state dir is typically a shared/network filesystem — per-file
        # reads under the service-wide lock would stall every worker
        # heartbeat exactly when the service is saturated), gated on an
        # unlocked approximate fullness pre-check so the happy path
        # never touches the dir. A race (queue drains between the
        # pre-check and the locked check) only costs the hint, never
        # correctness.
        fleet_view = None
        if self._fleet_dir is not None:
            try:
                approx_pending = sum(1 for j in list(self._jobs.values())
                                     if not j.done)
            except RuntimeError:   # dict mutated mid-iteration
                approx_pending = self._max_pending
            if approx_pending >= self._max_pending:
                fleet_view = self._fleet_view()
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is shut down")
            self._ordinal += 1
            ordinal = self._ordinal
            # the job's resolved fault entry: the explicit per-ordinal
            # plan entry merged with the chaos draw (both deterministic
            # in the submission ordinal)
            entry = faults.merge_service_entries(
                self._plan.get(ordinal),
                faults.chaos_entry(self._plan.get("chaos"), ordinal))
            if entry is not None and entry.get("reject"):
                obs_metrics.counter("service.jobs_rejected").inc()
                self._admission.note_reject()
                obs_trace.event("service.reject", tenant=tenant,
                                ordinal=ordinal, reason="fault_plan")
                raise ServiceRejected(
                    f"admission control rejected submission #{ordinal} "
                    f"({faults.SERVICE_FAULT_PLAN_ENV} reject entry)")
            pending = sum(1 for j in self._jobs.values() if not j.done)
            if pending >= self._max_pending:
                obs_metrics.counter("service.jobs_rejected").inc()
                self._admission.note_reject()
                obs_trace.event("service.reject", tenant=tenant,
                                ordinal=ordinal, reason="backpressure")
                hint = self._admission.retry_after_sec()
                # cross-shard redirect hint: in a sharded fleet
                # deployment a full local queue is not a full CLUSTER —
                # name the least-loaded live sibling so a router can
                # resubmit there instead of backing off (view read
                # before the lock; None when the pre-check raced)
                redirect = ""
                if fleet_view is not None:
                    least = fleet_view.get("least_loaded")
                    if least and least != self._fleet_shard:
                        redirect = (f"; fleet shard {least!r} has the "
                                    "shallowest queue (cluster depth "
                                    f"{fleet_view['cluster_queue_depth']})")
                err = ServiceOverloaded(
                    f"submission queue is full ({pending} pending >= "
                    f"{constants.SERVICE_MAX_PENDING_ENV}="
                    f"{self._max_pending}); resubmit after jobs drain "
                    f"(retry_after_sec={hint:.3f}){redirect}",
                    retry_after_sec=hint)
                err.cluster = fleet_view
                raise err
            if job_id is None:
                job_id = f"job{ordinal}"
            if job_id in self._jobs:
                raise ValueError(f"job id {job_id!r} already submitted "
                                 "to this service")
            job = SweepJob(self, job_id, tenant, scenario,
                           (f"live:{_live['method']}" if _live is not None
                            else method),
                           deadline_sec, ordinal, priority=priority,
                           profile=profile)
            job._fault_entry = entry
            job._live_query = _live
            if _live is not None and _live.get("plan") is not None:
                job.plan = _live["plan"]
            if self._journal is not None:
                # journal BEFORE registering: an un-journalable
                # submission must fail synchronously (the caller is owed
                # the durability contract), never become a phantom job
                # that occupies a MAX_PENDING slot forever
                if self._journal_broken:
                    raise ServiceError(
                        "the service WAL is broken (an earlier append "
                        "failed); refusing new submissions whose "
                        "durability cannot be honored — in-flight jobs "
                        "continue without recovery coverage")
                try:
                    self._journal.append({
                        "type": "submit", "job": job_id, "tenant": tenant,
                        "method": job.method, "priority": int(priority),
                        "partners_count": int(scenario.partners_count),
                        # a submit-time plan (live method="auto") rides
                        # the submit record: replay re-runs the SAME
                        # concrete method, never a re-plan under
                        # different meter state
                        **({"plan": job.plan.describe()}
                           if job.plan is not None else {})})
                except OSError as e:
                    raise ServiceError(
                        f"could not journal submission {job_id!r}: "
                        f"{e}") from e
            self._jobs[job_id] = job
            obs_metrics.counter("service.jobs_accepted").inc()
            obs_trace.event("service.submit", tenant=tenant, job=job_id,
                            method=job.method, ordinal=ordinal,
                            priority=int(priority),
                            **({"planned": job.plan.method,
                                "plan_reason": job.plan.reason}
                               if job.plan is not None else {}))
            self._queue.push(job)
            self._lock.notify_all()
        # the accepted submission moved the queue depth: let the fleet's
        # sibling shards (and their overload hints) see it promptly
        self._publish_fleet_state(force=True)
        return job

    # -- the live contributivity tier ------------------------------------

    def live_game(self, scenario, tenant: str = "tenant0",
                  journal_path=None, **kw):
        """Create (or return) the tenant's RESIDENT live game
        (mplc_tpu/live/): the recorded round history stays in this
        process across queries, so `submit_live` answers without a
        sweep. One game per tenant; a second call returns the existing
        game (the scenario/journal arguments of the first call win)."""
        from ..live import LiveGame
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is shut down")
            game = self._live_games.get(tenant)
        if game is not None:
            return game
        # creation serialized OUTSIDE the scheduler lock (engine/data
        # construction can take seconds and must not stall every quantum
        # pick) but under its own lock: two racing callers must not BOTH
        # construct — the loser would leak an open journal handle and
        # append a duplicate live_init record to the same WAL
        with self._live_create_lock:
            with self._lock:
                game = self._live_games.get(tenant)
            if game is None:
                game = LiveGame(scenario, tenant=tenant,
                                journal_path=journal_path, **kw)
                with self._lock:
                    self._live_games[tenant] = game
        return game

    def append_round(self, tenant: str, deltas, weights) -> int:
        """Append one aggregation round to the tenant's resident game
        (LiveGame.append_round — journaled, round-stamp invalidation).
        Returns the game's round-stamp after the append."""
        game = self._live_games.get(tenant)
        if game is None:
            raise ServiceError(
                f"no live game for tenant {tenant!r} — call live_game() "
                "first")
        return game.append_round(deltas, weights)

    def _ingest_live_round(self, tenant: str, doc: dict) -> dict:
        """The telemetry server's streaming-ingestion sink
        (`POST /live/<tenant>/round`, obs/export.py, gated on
        `MPLC_TPU_LIVE_INGEST=1`): decode one wire round — `{"deltas":
        [[shape, dtype, flat-values], ...], "weights": [P floats]}`, the
        exact triples the WAL journals for `live_round` records — and
        feed the tenant's resident game, so round arrival needs no
        in-process call. Error contract (mapped to HTTP by the handler):
        KeyError = unknown tenant (404), ValueError = malformed round
        (400), `ServiceAuthError` = a wire credential that does not
        authenticate the tenant (403); `LiveGameFull`/
        `LiveResidencyFull` propagate with their `retry_after_sec`
        backoff hint (429 + Retry-After)."""
        # a credential riding the wire document authenticates the tenant
        # claim exactly as on submit (the HTTP handler ALSO checks its
        # path-bound bearer token — this covers in-process dispatchers)
        self._check_credential(tenant, doc.get("credential"))
        game = self._live_games.get(tenant)
        if game is None:
            raise KeyError(f"no live game for tenant {tenant!r}")
        from ..live.game import _decode_tree
        try:
            deltas = _decode_tree(doc["deltas"], game._treedef)
            weights = np.asarray(doc["weights"], np.float32)
        except Exception as e:
            raise ValueError(f"malformed live_round document ({e}); "
                             'expected {"deltas": [[shape, dtype, '
                             'flat-values], ...], "weights": [P floats]}')
        stamp = game.append_round(deltas, weights)
        obs_metrics.counter("live.rounds_ingested").inc()
        obs_trace.event("live.ingest", tenant=game.tenant, stamp=stamp,
                        rounds=game.rounds_resident)
        return {"tenant": game.tenant, "stamp": stamp,
                "rounds_resident": game.rounds_resident}

    def submit_live(self, tenant: str, method: str = "GTG-Shapley",
                    deadline_sec: "float | None" = None,
                    job_id: "str | None" = None,
                    priority: "int | None" = None,
                    prune: "float | None" = None,
                    accuracy_target: "float | None" = None,
                    credential: "str | None" = None,
                    **method_kw) -> SweepJob:
        """Submit a low-latency live contributivity query against the
        tenant's resident game. Rides the EXISTING admission/priority/
        SLO machinery — bounded queue, tier-weighted quanta, overload
        shedding, deadlines, journaled terminals — as its own job class:
        by default one priority tier ABOVE the batch default (live
        queries are the latency-sensitive traffic the governor protects)
        with `MPLC_TPU_LIVE_QUERY_DEADLINE_SEC` as the default deadline
        (0/unset = none; an explicit `deadline_sec` wins). `method` is
        "exact" | "hierarchical" | "GTG-Shapley" | "SVARM" | "auto";
        `prune` is the DPVS threshold tau (None = the env default). The
        answer is `job.result()` (the scores) with the full
        `LiveQueryResult` on `job.live_result`.

        `method="auto"` resolves HERE, synchronously: the adaptive
        planner (contrib/planner.py) routes
        `(partners, accuracy_target, deadline_sec)` to a concrete
        estimator using the resident game's measured per-eval cost, the
        resolved QueryPlan is pinned into the live spec AND the journal's
        submit record (a replay runs the same concrete query, never a
        re-plan), and the plan's prune tau wins when the caller passed
        none — even tau=0 (unpruned) is the plan's decision. The plan is
        admission-aware: the queue's measured p50 wait is subtracted
        from the deadline before routing (floored at a tenth of the
        SLO), so the chosen estimator fits what REMAINS of the tier's
        SLO after queueing, not the wall-clock deadline the job itself
        is still held to.

        `credential` authenticates the tenant claim exactly as in
        `submit` — checked BEFORE the planner runs (an unauthenticated
        caller must not even spend the planning work)."""
        self._check_credential(tenant, credential)
        game = self._live_games.get(tenant)
        if game is None:
            raise ServiceError(
                f"no live game for tenant {tenant!r} — call live_game() "
                "first")
        # the planner must see the EFFECTIVE deadline, so the env
        # default resolves before the auto branch (explicit wins, as
        # documented)
        if deadline_sec is None and self._live_deadline > 0:
            deadline_sec = self._live_deadline
        plan = None
        if method == "auto":
            from ..contrib.planner import (estimate_eval_seconds,
                                           plan_query)
            eval_sec, basis = estimate_eval_seconds(game.engine)
            # admission-aware per-tier SLO: a queued job spends the
            # queue's current p50 wait before any compute runs, so the
            # planner routes against the COMPUTE budget that remains of
            # the deadline (floored at a tenth — a saturated queue must
            # degrade the method choice, not zero the budget). The job's
            # own deadline stays the full SLO.
            plan_deadline = deadline_sec
            if deadline_sec is not None:
                wait = self._admission.retry_after_sec()
                plan_deadline = max(float(deadline_sec) - wait,
                                    float(deadline_sec) * 0.1)
            plan = plan_query(game.engine.partners_count,
                              accuracy_target, plan_deadline,
                              eval_sec=eval_sec, cost_basis=basis,
                              live=True)
            method = plan.method
            if prune is None:
                prune = plan.prune_tau
            method_kw = {**plan.method_kw, **method_kw}
        # validate what the quantum would deterministically reject
        # SYNCHRONOUSLY (same rule as submit()'s method check): a job
        # that can only ever ValueError must not burn the retry budget,
        # quarantine and dump a postmortem for a caller mistake
        from ..live import MAX_EXACT_PARTNERS
        if (method in ("exact", "Shapley values")
                and game.engine.partners_count > MAX_EXACT_PARTNERS):
            raise ValueError(
                f"live exact queries are limited to {MAX_EXACT_PARTNERS} "
                f"partners (this game has {game.engine.partners_count}) "
                "— use hierarchical, GTG-Shapley or SVARM")
        if prune is not None and not 0.0 <= float(prune) <= 1.0:
            raise ValueError(
                f"prune tau must be in [0, 1], got {prune}")
        if priority is None:
            priority = self._priority_default + 1
        return self.submit(game.scenario, tenant=tenant,
                           deadline_sec=deadline_sec, job_id=job_id,
                           priority=priority,
                           _live={"game": game, "method": method,
                                  "prune": prune, "kw": method_kw,
                                  "plan": plan})

    # -- scheduling loop -------------------------------------------------

    def _pick_locked(self) -> tuple:
        """One admission decision + queue pop, caller holding the lock:
        evaluate the overload governor on the live queue-wait signal,
        REMOVE any shed victims from the queue (their terminal
        bookkeeping — journal fsync, metrics, events — happens in
        `_shed_job` AFTER the caller releases the lock: shedding exists
        to recover latency, so it must not stall every worker and
        submit() behind per-victim fsyncs), then pop the next job
        (lowest tier deferred while the governor is unhealthy). Returns
        `(victims, job)`; job is None when the queue is empty."""
        if not len(self._queue):
            return [], None
        now = time.monotonic()
        state = self._admission.evaluate(
            [now - j.submitted_at for j in self._queue.jobs()])
        victims = []
        if state == AdmissionController.SHEDDING:
            victims = self._queue.shed_candidates(
                self._admission.shed_quota(len(self._queue),
                                           self._max_pending))
            self._admission.note_shed(len(victims))
        return victims, self._queue.pop(
            defer_lowest=state != AdmissionController.HEALTHY)

    def _shed_job(self, job: SweepJob) -> None:
        """One victim's classified, journaled `JobShed` terminal —
        never a silent drop. Runs WITHOUT the scheduler lock held."""
        hint = self._admission.retry_after_sec()
        p99 = self._admission._last_p99
        obs_trace.event("service.shed", tenant=job.tenant,
                        job=job.job_id, priority=job.priority,
                        queue_wait_p99_sec=p99,
                        retry_after_sec=hint)
        logger.warning(
            "service: SHED job %s (tier %d) — queue-wait p99 %.2fs "
            "over %s=%.2fs; retry after ~%.2fs", job.job_id,
            job.priority, p99 if p99 is not None else float("nan"),
            constants.SERVICE_SHED_P99_ENV,
            self._admission.shed_p99_sec, hint)
        self._terminal(job, "shed", JobShed(
            f"job {job.job_id} shed by overload admission control "
            f"(queue-wait p99 {p99:.3f}s > "
            f"{constants.SERVICE_SHED_P99_ENV}="
            f"{self._admission.shed_p99_sec}s); resubmit in "
            f"~{hint:.3f}s or at a higher priority",
            retry_after_sec=hint))

    def _shed_all(self, victims) -> None:
        if not victims:
            return
        for job in victims:
            self._shed_job(job)
        # terminal states changed outside the lock: wake drain()/waiters
        with self._lock:
            self._lock.notify_all()

    def _worker_loop(self, worker: "_WorkerSlot") -> None:
        while True:
            with self._lock:
                if self._abandoned:
                    # abandon(): stop at the quantum boundary, leaving
                    # queue/jobs/journal exactly as a SIGKILL would
                    return
                victims, job = self._pick_locked()
                while job is None and not victims and not self._closed:
                    # in a fleet deployment the idle wait is BOUNDED so an
                    # idle shard keeps publishing its (empty) queue state —
                    # an idle sibling that goes stale is excluded from
                    # least_loaded exactly when it is the best redirect
                    # target. Non-fleet services keep the untimed wait.
                    timed_out = not self._lock.wait(
                        timeout=10.0 if self._fleet_dir else None)
                    victims, job = self._pick_locked()
                    if timed_out and job is None and not victims:
                        break
                if job is not None:
                    worker.running_job = job
            self._shed_all(victims)
            if job is None:
                if self._closed:
                    return  # closed and drained
                # idle heartbeat: publish outside the lock (no-op
                # without MPLC_TPU_FLEET_STATE_DIR), then re-check
                self._publish_fleet_state()
                continue  # everything poppable was shed; re-check
            alive = False
            try:
                alive = self._run_quantum(job, worker=worker)
            finally:
                # clear running AND re-queue under ONE lock hold: a
                # drain() between the two would otherwise see an idle
                # service with a live job in neither place
                with self._lock:
                    worker.running_job = None
                    if alive and not job.done:
                        self._queue.push(job)  # round-robin re-queue
                    self._lock.notify_all()

    def step(self) -> bool:
        """Process ONE scheduling quantum inline (start=False mode — the
        deterministic harness the crash-recovery and chaos-smoke tests
        drive). Returns True while work remains."""
        with self._lock:
            victims, job = self._pick_locked()
            if job is not None:
                self._running_job = job
        self._shed_all(victims)
        if job is None:
            with self._lock:
                return bool(len(self._queue))
        alive = False
        try:
            alive = self._run_quantum(job)
        finally:
            with self._lock:
                self._running_job = None
                if alive and not job.done:
                    self._queue.push(job)
        with self._lock:
            return bool(len(self._queue))

    def run_until_idle(self) -> None:
        """Drain the queue inline (start=False mode)."""
        while self.step():
            pass

    def drain(self, timeout: "float | None" = None) -> None:
        """Block until every accepted job reached a terminal state."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if not self._workers:
            self.run_until_idle()
            return
        with self._lock:
            while (len(self._queue)
                   or any(w.running_job is not None for w in self._workers)
                   or self._running_job is not None):
                wait = (None if deadline is None
                        else max(deadline - time.monotonic(), 0.0))
                if wait == 0.0:
                    raise TimeoutError("service did not drain in time")
                self._lock.wait(wait)

    def abandon(self, timeout: "float | None" = 5.0) -> None:
        """Chaos/test hook: die like a SIGKILL, minus the threads. Stops
        the worker pool at the next quantum boundary WITHOUT draining,
        cancelling, publishing or closing the journal — queued and
        running jobs stay non-terminal and the WAL on disk is exactly
        what a process death would leave, which is what a fleet router's
        failover replays. The currently-running quantum cannot be
        preempted (it finishes, journaling its harvest — deterministic,
        so a survivor's re-run of it is bit-identical); `timeout` bounds
        the per-thread join. Idempotent; a no-op for inline
        (start=False) services, which have no threads to stop."""
        with self._lock:
            self._abandoned = True
            self._closed = True
            self._lock.notify_all()
        for w in self._workers:
            if w.thread is not None \
                    and w.thread is not threading.current_thread():
                w.thread.join(timeout)
        self._workers = []
        self._worker = None

    def shutdown(self, drain: bool = True,
                 timeout: "float | None" = None) -> None:
        """Stop accepting submissions; with `drain` (the default) finish
        every queued job first, otherwise cancel whatever never started.
        Idempotent; closes the journal last. After `abandon()` the
        service is already dead: shutdown only releases resources —
        no draining, no cancel records, no state publishing (the corpse
        must not journal or heartbeat post-mortem)."""
        with self._lock:
            abandoned = self._abandoned
            self._closed = True
            if not drain and not abandoned:
                while len(self._queue):
                    job = self._queue.pop()
                    self._terminal(job, "cancelled",
                                   JobCancelled("service shutdown"))
            self._lock.notify_all()
        if not abandoned:
            # force-publish the `closed: true` state BEFORE draining:
            # without this a cleanly shut-down shard keeps its last
            # (healthy, queue_depth 0) state file for up to the
            # staleness bound and the cluster view recommends a corpse
            # as "least loaded" — exactly the redirect a router must
            # never follow
            self._publish_fleet_state(force=True)
            if drain:
                self.drain(timeout)
        for w in self._workers:
            if w.thread is not None:
                w.thread.join(timeout)
        self._workers = []
        self._worker = None
        if self._journal is not None:
            self._journal.close()
        for game in self._live_games.values():
            game.close()
        obs_export.unregister(self._provider_key)

    def __enter__(self) -> "SweepService":
        return self

    def __exit__(self, *exc) -> bool:
        self.shutdown(drain=exc == (None, None, None))
        return False

    # -- one scheduling quantum ------------------------------------------

    def _beat(self, worker: "_WorkerSlot | None" = None) -> None:
        """Advance the current scheduling slot's heartbeat: the worker's
        own when a pool worker is running the quantum, the service-level
        one in inline (start=False / step()) mode."""
        now = time.monotonic()
        if worker is None:
            worker = getattr(self._tl, "worker", None)
        if worker is not None:
            worker.heartbeat = now
        else:
            self._heartbeat = now
        self._publish_fleet_state()

    def _publish_fleet_state(self, force: bool = False) -> None:
        """Mirror this shard's queue/admission state into the shared
        fleet state dir (no-op without MPLC_TPU_FLEET_STATE_DIR).
        Rate-limited so the per-batch heartbeat path never turns into a
        per-batch fsync; the snapshot is taken under the lock, the file
        write happens outside it. Never raises."""
        if not self._fleet_dir:
            return
        now = time.monotonic()
        if not force and now - self._fleet_pub_ts < 0.5:
            return
        self._fleet_pub_ts = now
        with self._lock:
            payload = {
                "queue_depth": len(self._queue),
                "jobs_pending": sum(1 for j in self._jobs.values()
                                    if not j.done),
                "max_pending": self._max_pending,
                "workers": max(len(self._workers), 1),
                "admission_state": self._admission.state,
                "closed": self._closed,
                # where a fleet router reaches this shard's HTTP surface
                # (None when no telemetry server is up): the published
                # state dir doubles as the router's service discovery
                "port": obs_export.active_port(),
                # where this shard's WAL lives (None when unjournaled):
                # a router performing failover replays the dead shard's
                # journal from here to resubmit its incomplete jobs
                "journal_path": (self._journal.path
                                 if self._journal is not None else None),
            }
        # the full metrics snapshot rides along (shared log2 buckets):
        # this is what makes the published state dir a SERVERLESS fleet
        # metrics source — obs/fleet_view.FleetCollector merges these
        # per-shard snapshots into cluster-true per-tenant SLO quantiles
        # without any shard exposing an HTTP port
        from ..obs import metrics as obs_metrics
        payload["metrics"] = obs_metrics.snapshot()
        from ..parallel import fleet
        fleet.publish_shard_state(self._fleet_dir, self._fleet_shard,
                                  payload)

    def _fleet_view(self) -> "dict | None":
        """The cross-shard cluster aggregate (None without a state dir):
        per-shard queue depths, cluster totals, and the least-loaded
        live shard — what /healthz exposes and overload hints cite."""
        if not self._fleet_dir:
            return None
        from ..parallel import fleet
        view = fleet.cluster_view(self._fleet_dir)
        view["shard_id"] = self._fleet_shard
        return view

    @staticmethod
    def _device_ctx(worker: "_WorkerSlot | None"):
        """The worker's device-slot pin: uncommitted computation inside
        its quanta defaults onto the pinned device when the host has more
        than one (explicitly-sharded arrays keep their shardings). A
        single-device host — and the inline mode — runs unpinned."""
        import contextlib
        if worker is None or worker.device is None:
            return contextlib.nullcontext()
        try:
            import jax
            return jax.default_device(worker.device)
        except Exception:  # pragma: no cover - jax without the API
            return contextlib.nullcontext()

    def _run_quantum(self, job: SweepJob,
                     worker: "_WorkerSlot | None" = None) -> bool:
        """Run one slice of `job`. Returns True when the job should be
        re-queued (work remains), False on any terminal state. EVERY
        failure is contained here: nothing a job does may unwind into
        the scheduler loop (per-tenant isolation)."""
        self._beat(worker)
        self._tl.worker = worker
        expired = job._deadline_expired()
        if job.first_quantum_at is None:
            if expired:
                # expired while STILL QUEUED: cancel before any work —
                # and before the queue-wait observation below, so an
                # expired wait never lands in the SLO histograms (it is
                # a deadline miss, not a latency datum) and ttfv stays
                # unset
                self._note_deadline_miss(job)
                self._terminal(job, "cancelled", JobCancelled(
                    f"job {job.job_id} exceeded deadline_sec="
                    f"{job.deadline_sec} while still queued"))
                return False
            # queue wait: submit -> the scheduler first picks the job up
            # (the injected stall below bills against the job's SLICE
            # time, like any slow quantum, not its queue wait); the same
            # sample feeds the admission governor's sliding window
            job.first_quantum_at = time.monotonic()
            wait = job.first_quantum_at - job.submitted_at
            obs_metrics.histogram(
                "service.queue_wait_sec",
                tenant=job.tenant).observe(wait)
            with self._lock:
                self._admission.observe_queue_wait(wait)
        entry = job._fault_entry
        if entry is not None and entry.get("stall_sec"):
            sec, entry["stall_sec"] = entry["stall_sec"], 0.0
            obs_trace.event("service.stall", tenant=job.tenant,
                            job=job.job_id, seconds=sec)
            logger.warning("service: injected stall of %.2f s before %s",
                           sec, job.job_id)
            time.sleep(sec)
            # the stall billed against the job's own deadline
            expired = expired or job._deadline_expired()
        if expired:
            self._note_deadline_miss(job)
            self._terminal(job, "cancelled", JobCancelled(
                f"job {job.job_id} exceeded deadline_sec="
                f"{job.deadline_sec} before its quantum"))
            return False
        job.status = "running"
        span = obs_trace.start_span("service.slice", tenant=job.tenant,
                                    job=job.job_id)
        try:
            with self._device_ctx(worker), self._profile_ctx(job):
                return self._run_quantum_body(job, span)
        finally:
            self._tl.worker = None

    def _profile_ctx(self, job: SweepJob):
        """The per-job device-trace context (submit's `profile=True`
        flag x `MPLC_TPU_PROFILE_DIR`): captures exactly this job's
        quanta — sibling tenants' quanta on other workers never enter
        the trace."""
        if not job.profile:
            return contextlib.nullcontext()
        profile_dir = os.environ.get("MPLC_TPU_PROFILE_DIR")
        if not profile_dir:
            return contextlib.nullcontext()
        return _QuantumProfiler(job, os.path.join(profile_dir, job.job_id))

    def _run_quantum_body(self, job: SweepJob, span) -> bool:
        meter_before = None
        try:
            if job.engine is None:
                if job._live_query is not None:
                    self._attach_live_engine(job)
                else:
                    self._build_engine(job)
            eng = job.engine
            meter = getattr(eng, "device_meter", None)
            # live quanta snapshot/bill inside the GAME lock instead —
            # the resident engine's meter is shared with sibling quanta
            meter_before = (meter.snapshot()
                            if meter is not None
                            and job._live_query is None else None)
            b0, e0 = eng._batch_ordinal, eng.epochs_trained
            s0, p0 = eng.samples_trained, job.packed_batches
            c0 = len(eng.charac_fct_values)
            if job._live_query is not None:
                finished = self._run_live_quantum(job)
            elif job.method == "Shapley values":
                finished = self._run_exact_slice(job)
            else:
                finished = self._run_method_quantum(job)
            if job._live_query is not None:
                dev_sec, dev_basis = job._live_billed or (0.0, None)
                job._live_billed = None
            else:
                dev_sec, dev_basis = self._meter_quantum(job, meter_before)
            meter_before = None  # billed; the except paths must not re-bill
            if job._live_query is not None:
                # counters snapshotted under the GAME lock (sibling
                # quanta share the resident engine; unlocked deltas
                # would report their work too); coalitions = this
                # query's reconstruction evaluations
                counts = job._live_counts or {}
                job._live_counts = None
                span.attrs.update(
                    **counts, packed_batches=job.packed_batches - p0,
                    device_sec=dev_sec, device_basis=dev_basis)
            else:
                span.attrs.update(
                    batches=eng._batch_ordinal - b0,
                    coalitions=len(eng.charac_fct_values) - c0,
                    epochs=eng.epochs_trained - e0,
                    samples=eng.samples_trained - s0,
                    packed_batches=job.packed_batches - p0,
                    device_sec=dev_sec, device_basis=dev_basis)
            span.end()
            obs_metrics.histogram(
                "service.slice_sec", tenant=job.tenant).observe(
                    span.duration)
            if finished:
                self._complete(job)
                return False
            return True
        except JobCancelled as e:
            span.cancel()
            self._bill_failed_quantum(job, meter_before, span, "cancelled")
            self._journal_new_values(job)  # keep what the drain harvested
            self._terminal(job, "cancelled", e)
            return False
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — the isolation boundary
            span.cancel()
            # bill + preserve whatever the failed attempt harvested
            # before the fault: the journal (and the engine memo) make
            # the retry a bit-identical continuation, not a restart —
            # and the tenant pays for the device time its fault consumed
            self._bill_failed_quantum(job, meter_before, span, "fault")
            try:
                self._journal_new_values(job)
            except Exception:
                logger.exception(
                    "service: journaling after a fault failed for %s",
                    job.job_id)
            return self._fail_attempt(job, e)

    def _bill_failed_quantum(self, job: SweepJob, before: "dict | None",
                             span, outcome: str) -> None:
        """Billing for a quantum that did NOT complete (deadline cancel,
        fault): the tenant pays for the device time its quantum
        consumed, and — because the `service.slice` span was CANCELLED,
        never emitted — a replacement slice EVENT carries the billed
        delta into the trace stream. Without it the report's per-tenant
        device_seconds/cost_share would silently disagree with the
        /metrics counter and the journal for exactly the tenants whose
        faults consumed device time. Live quanta were already billed
        inside the game lock (`_run_live_quantum`'s finally) — their
        stashed delta feeds the replacement event here instead of a
        second metering pass."""
        if job._live_query is not None:
            dsec, dbasis = job._live_billed or (0.0, None)
            job._live_billed = None
        else:
            dsec, dbasis = self._meter_quantum(job, before)
        if dsec:
            obs_trace.event(
                "service.slice", dur=span.duration or 0.0,
                tenant=job.tenant, job=job.job_id,
                device_sec=dsec, device_basis=dbasis, outcome=outcome)

    def _meter_quantum(self, job: SweepJob,
                       before: "dict | None") -> "tuple[float, str | None]":
        """Bill the quantum's device-seconds delta (obs/devcost.py) to
        the job and its tenant: the `service.device_seconds{tenant=...}`
        counter, the scheduler's lifetime per-tenant map (/varz), and
        the job's own total (journaled at terminal). Returns the
        (seconds, basis) pair the `service.slice` span records."""
        eng = job.engine
        meter = getattr(eng, "device_meter", None) if eng is not None \
            else None
        if meter is None or before is None:
            return 0.0, None
        delta = devcost.meter_delta(before, meter.snapshot())
        sec, basis = devcost.estimate_device_seconds(
            delta, devcost.fleet_peak_flops())
        if sec > 0:
            job.device_seconds += sec
            job.device_basis = devcost.merge_basis(job.device_basis, basis)
            obs_metrics.counter("service.device_seconds",
                                tenant=job.tenant).inc(sec)
            with self._lock:
                self._tenant_device_seconds[job.tenant] = \
                    self._tenant_device_seconds.get(job.tenant, 0.0) + sec
        return sec, (basis if sec > 0 else None)

    def _fail_attempt(self, job: SweepJob, err: BaseException) -> bool:
        """Attempt-level retry/quarantine policy. Retryable failures
        (transient-classified, OOM that escaped the engine's own ladder,
        injected crash) re-queue the job up to MPLC_TPU_MAX_RETRIES
        attempts; permanent ones (LadderExhaustedError, genuine bugs)
        quarantine immediately — a poison job must never retry forever."""
        job.attempts += 1
        retryable = (faults.is_transient(err) or faults.is_oom(err)
                     or isinstance(err, faults.InjectedCrash))
        requeued = retryable and job.attempts <= self._max_job_retries
        # `requeued` distinguishes a retry from the quarantining final
        # attempt, so the report's slo row counts exactly what the live
        # service.job_retries counter counts
        obs_trace.event("service.job_fault", tenant=job.tenant,
                        job=job.job_id, attempt=job.attempts,
                        retryable=retryable, requeued=requeued,
                        error=str(err)[:200])
        if requeued:
            obs_metrics.counter("service.job_retries",
                                tenant=job.tenant).inc()
            logger.warning(
                "service: job %s attempt %d failed (%s) — re-queueing "
                "(its harvested values persist; the continuation is "
                "bit-identical)", job.job_id, job.attempts, err)
            return True
        kind = ("retry budget exhausted" if retryable
                else "permanent failure")
        # postmortem BEFORE the terminal bookkeeping: the flight ring
        # still holds the failing attempt's spans (engine.dispatch /
        # engine.fault / service.job_fault of the batch that died)
        postmortem = obs_flight.dump("job_quarantined", extra={
            "job": job.job_id, "tenant": job.tenant,
            "attempts": job.attempts, "kind": kind,
            "error": str(err)[:500]})
        logger.error(
            "service: quarantining job %s after %s: %s%s",
            job.job_id, kind, err,
            f" — postmortem flight record: {postmortem}"
            if postmortem else "")
        q = JobQuarantined(
            f"job {job.job_id} quarantined ({kind}, "
            f"{job.attempts} attempt(s)): {err}")
        # __cause__ accepts any BaseException — the injected-crash case
        # must not be the one place the root cause is lost
        q.__cause__ = err
        self._terminal(job, "quarantined", q)
        return False

    # -- engine lifecycle ------------------------------------------------

    def _build_engine(self, job: SweepJob) -> None:
        from ..contrib.bank import ProgramBank
        from ..contrib.engine import CharacteristicEngine
        from ..contrib.shapley import powerset_order

        eng = CharacteristicEngine(job.scenario)
        if eng.program_bank is not None:
            # shape-scoped keys: same (slots, width) bucket => same banked
            # program regardless of which tenant's game it serves
            eng.program_bank = ProgramBank(eng, shared=True)
        entry = job._fault_entry
        if entry is not None and entry.get("batch"):
            # install the job's injected batch faults (explicit plan
            # merged with the chaos draw) into ITS engine's private
            # injector: FaultInjector's fire-once/retry-keeps-ordinal
            # semantics apply per tenant, exactly as solo
            eng._faults = faults.FaultInjector(
                {k: list(v) for k, v in entry["batch"].items()})

        def on_batch(done_in_group, remaining, slot_count,
                     _job=job) -> None:
            self._on_batch(_job, slot_count)

        eng.progress = on_batch
        job.engine = eng
        job.subsets = powerset_order(eng.partners_count)

        rec = self._recovered.get(job.job_id)
        if rec and rec["values"]:
            # the journaled submission is the authority on which GAME the
            # job_id names: seeding a different scenario's engine from it
            # would silently mix two games' v(S) tables
            jp = rec.get("partners_count")
            if jp is not None and int(jp) != eng.partners_count:
                raise ValueError(
                    f"journaled job {job.job_id!r} was submitted with "
                    f"{jp} partners but the resubmitted scenario has "
                    f"{eng.partners_count} — refusing to seed v(S) from "
                    "a different game's journal (resubmit the original "
                    "scenario, or use a fresh job_id)")
            # seed the fresh engine's memo from the journal: replay in
            # journal (= harvest) order reproduces the increment
            # bookkeeping of the original run, and the journaled floats
            # round-trip exactly — the continuation is bit-identical
            for subset, value in rec["values"].items():
                if subset and subset not in eng.charac_fct_values:
                    eng._store(subset, float(value))
            job.recovered_values = len(rec["values"])
            job._journal_cursor = len(eng.charac_fct_values)
            job._push_stream([(s, v) for s, v in rec["values"].items()])
            obs_metrics.counter("service.jobs_recovered").inc()
            obs_trace.event("service.recover", tenant=job.tenant,
                            job=job.job_id, values=job.recovered_values)
            # the seeded table now lives in the engine memo; a duplicate
            # job_id can't be resubmitted in this service life, so free
            # the replayed copy (a restart on a long journal must not pin
            # every historical job's 2^P-entry table twice). Entries for
            # jobs never resubmitted keep theirs until process exit —
            # WAL compaction is future work.
            rec["values"] = {}
        else:
            # the engine pre-seeds v(empty)=0; never journal it
            job._journal_cursor = len(eng.charac_fct_values)

    def _on_batch(self, job: SweepJob, slot_count) -> None:
        """The engine's per-batch progress hook: journal what the batch
        harvested, count cross-tenant packed batches, and enforce the
        deadline cooperatively — raising BETWEEN batches, never inside a
        dispatch."""
        self._beat()  # the running worker's own heartbeat (thread-local)
        self._journal_new_values(job)
        if job._slice_packed.get(slot_count):
            job.packed_batches += 1
            obs_metrics.counter("service.cross_tenant_packed_batches").inc()
        if job._deadline_expired() and not job._cancel_raised:
            # raise ONCE: the engine's exception-unwind drain re-enters
            # this hook for the in-flight batch, and a second raise there
            # would abort the drain's bookkeeping
            job._cancel_raised = True
            self._note_deadline_miss(job)
            raise JobCancelled(
                f"job {job.job_id} exceeded deadline_sec="
                f"{job.deadline_sec} (cancelled at a batch boundary)")

    def _journal_safe(self, *recs) -> None:
        """Async-path WAL appends (harvest values, terminal states): a
        journal write failure here (disk full, dead volume) must DEGRADE
        the service — recovery coverage stops, loudly — never unwind into
        the scheduler loop and kill the worker with jobs still blocked on
        their handles. (submit() is the synchronous path and propagates
        instead: an unacknowledged durability contract is the caller's to
        handle.)"""
        if self._journal is None or self._journal_broken:
            return
        try:
            self._journal.append_many(list(recs))
        except OSError as e:
            self._journal_broken = True
            obs_trace.event("service.journal_broken", error=str(e)[:200])
            logger.error(
                "service: WAL append failed (%s) — journaling DISABLED; "
                "crash recovery no longer covers work from this point on",
                e)

    def _journal_new_values(self, job: SweepJob) -> None:
        """Append every not-yet-journaled `(tenant, subset, value)` to the
        WAL — one fsync for the whole batch — and the tenant's stream, in
        memo insertion (= harvest) order."""
        eng = job.engine
        if eng is None:
            return
        items = list(eng.charac_fct_values.items())
        fresh = items[job._journal_cursor:]
        if not fresh:
            return
        job._journal_cursor = len(items)
        self._journal_safe(*[
            {"type": "value", "job": job.job_id, "tenant": job.tenant,
             "subset": list(subset), "value": float(value)}
            for subset, value in fresh])
        job._push_stream(fresh)

    def _attach_live_engine(self, job: SweepJob) -> None:
        """Point a live-query job at its tenant's RESIDENT game engine
        (shared across queries — never rebuilt, never released). The
        journal cursor is parked at the engine's current memo size: live
        answers are reconstruction-derived and journaled by the game's
        OWN WAL, so the service WAL must not re-journal the shared
        engine's exact memo under this job."""
        eng = job._live_query["game"].engine
        job.engine = eng
        job.subsets = None
        job._journal_cursor = len(eng.charac_fct_values)

    def _run_live_quantum(self, job: SweepJob) -> bool:
        """One live query runs as ONE quantum (like the estimator
        methods): the resident game answers from reconstruction — zero
        training batches — while the heartbeat and cooperative deadline
        ride the shared engine's per-batch progress hook for the
        quantum's duration.

        The whole quantum body holds the GAME's lock: the engine, its
        progress hook, its device meter and the evaluator memo are all
        shared with every other quantum of this tenant, so a sibling
        worker's live quantum must not interleave — it would clobber
        this quantum's hook (driving the wrong job's heartbeat/deadline)
        and its device work would land inside both quanta's meter
        windows (double-billed device-seconds). The meter snapshot is
        therefore taken INSIDE the lock (the generic quantum pre-snapshot
        is skipped for live jobs) and the billed delta is stashed on the
        job for the slice span / failure-billing paths."""
        spec = job._live_query
        game = spec["game"]
        eng = job.engine

        def on_batch(done_in_group, remaining, slot_count,
                     _job=job) -> None:
            self._on_batch(_job, slot_count)

        with game._lock:
            meter = getattr(eng, "device_meter", None)
            before = meter.snapshot() if meter is not None else None
            # batch/epoch/sample accounting snapshotted INSIDE the lock
            # too: the shared engine's counters advance under sibling
            # quanta, and this quantum's slice span must report only its
            # own work (same rule as the meter)
            b0, e0, s0 = (eng._batch_ordinal, eng.epochs_trained,
                          eng.samples_trained)
            prev = eng.progress
            eng.progress = on_batch
            try:
                result = game.query(method=spec["method"],
                                    prune=spec.get("prune"),
                                    **(spec.get("kw") or {}))
            finally:
                eng.progress = prev
                # bill inside the lock: the window contains exactly this
                # quantum's device work (a faulted/cancelled query pays
                # for what it consumed, like any quantum)
                job._live_billed = self._meter_quantum(job, before)
                job._live_counts = {
                    "batches": eng._batch_ordinal - b0,
                    "epochs": eng.epochs_trained - e0,
                    "samples": eng.samples_trained - s0,
                }
            job._live_counts["coalitions"] = result.evaluations
            # the completed query's v(S) table, snapshotted while appends
            # are still excluded — _complete must not touch the shared
            # evaluator outside the lock (a racing append_round would
            # reset_recorded under it)
            job.values = dict(game._evaluator().values)
        job.scores = np.asarray(result.scores)
        job.live_result = result
        # a submit-time plan (method="auto") rides the result handle:
        # the game saw only the concrete method, so the plan attaches
        # here for `job.live_result.describe()` consumers
        if spec.get("plan") is not None and result.plan is None:
            result.plan = spec["plan"]
        # stream the answer as one terminal item so stream() consumers
        # (and the ttfv SLO histogram) see live answers like batch values
        job._push_stream([(("live", spec["method"]),
                           [float(x) for x in result.scores])])
        return True

    # -- the two execution shapes ---------------------------------------

    def _run_exact_slice(self, job: SweepJob) -> bool:
        """One slice of an exact-Shapley sweep: evaluate the next
        `MPLC_TPU_SERVICE_SLICE` missing coalitions. Returns True when
        the sweep's table is complete."""
        eng = job.engine
        missing = [s for s in job.subsets
                   if s not in eng.charac_fct_values]
        if missing:
            chunk = missing[:self._slice]
            # the chunk is all-missing, so sweep_plan == the buckets
            # evaluate() will actually dispatch
            job._slice_packed = self._packer.observe_plan(
                job.tenant, eng, eng.sweep_plan(chunk))
            eng.evaluate(chunk)
            self._journal_new_values(job)
        return len(missing) <= self._slice

    def _run_method_quantum(self, job: SweepJob) -> bool:
        """Estimator methods (TMCS, GTG-Shapley, ...) drive the engine
        from their own host loop, so they run as ONE quantum: the worker
        is theirs for the method's duration, but per-batch journaling,
        deadline cancellation and fault isolation all still apply through
        the engine hooks."""
        from ..contrib.contributivity import Contributivity

        eng = job.engine
        job._slice_packed = self._packer.observe_plan(
            job.tenant, eng, eng.sweep_plan(job.subsets))
        job.scenario._charac_engine = eng
        contrib = Contributivity(job.scenario)
        # method="auto" resolves at run time (the engine's meter/bank
        # cost truth exists only once the job's engine is built); the
        # job's deadline is the planner's budget. The resolved plan is
        # journaled so the WAL replay knows the concrete query, and
        # lands on the terminal service.job event.
        contrib.compute_contributivity(job.method,
                                       deadline_sec=job.deadline_sec)
        plan = getattr(contrib, "plan", None)
        if plan is not None:
            job.plan = plan
            self._journal_safe({"type": "plan", "job": job.job_id,
                                "tenant": job.tenant,
                                "plan": plan.describe()})
        self._journal_new_values(job)
        job.scores = np.asarray(contrib.contributivity_scores)
        return True

    # -- terminal states -------------------------------------------------

    def _note_deadline_miss(self, job: SweepJob) -> None:
        if not job.deadline_missed:
            job.deadline_missed = True
            obs_metrics.counter("service.deadline_misses",
                                tenant=job.tenant).inc()

    def _release_engine_data(self, job: SweepJob) -> None:
        """Drop the completed job's device-resident state (stacked data,
        eval sets, pipelines, bank view) while KEEPING the engine object
        and its host-side v(S)/counters for the handle's consumers — a
        long-lived service completing many jobs must not accumulate one
        game's device arrays per job."""
        eng = job.engine
        if eng is None:
            return
        if job._live_query is not None:
            # the engine belongs to the tenant's RESIDENT live game —
            # shared across queries; drop only this handle's reference
            job.engine = None
            return
        eng.progress = None
        for attr in ("stacked", "val", "test", "_cpu_data", "multi_pipe",
                     "single_pipe", "_pipe2d", "program_bank"):
            setattr(eng, attr, None)
        eng._slot_pipes = {}
        eng._singles_pipes = {}

    def _retire(self, job: SweepJob) -> None:
        """FIFO-bound the terminal-job registry: handles returned to
        callers stay alive through their own reference, but the service's
        _jobs map (and its job-id dedupe window) is bounded."""
        with self._lock:
            self._terminal_seen += 1
            self._terminal_order.append(job.job_id)
            while len(self._terminal_order) > self._max_terminal_jobs:
                old = self._terminal_order.popleft()
                j = self._jobs.get(old)
                if j is not None and j.done:
                    self._jobs.pop(old, None)

    def _complete(self, job: SweepJob) -> None:
        if job.scores is None:
            from ..contrib.shapley import shapley_from_characteristic
            job.scores = shapley_from_characteristic(
                job.engine.partners_count, job.engine.charac_fct_values)
        if job._live_query is None:
            job.values = dict(job.engine.charac_fct_values)
        # live jobs: `job.values` was snapshotted from the game's
        # evaluator UNDER the game lock in _run_live_quantum — touching
        # the shared evaluator here would race a sibling append_round's
        # reset_recorded (and a concurrent query's memo inserts)
        job.status = "completed"
        # the terminal record carries the job's metered device-seconds:
        # replay restores per-tenant billing across restarts
        self._journal_safe({"type": "done", "job": job.job_id,
                            "tenant": job.tenant,
                            "device_seconds": job.device_seconds,
                            "device_basis": job.device_basis})
        obs_metrics.counter("service.jobs_completed").inc()
        obs_metrics.histogram("service.job_attempts",
                              tenant=job.tenant).observe(job.attempts)
        obs_trace.event(
            "service.job", tenant=job.tenant, job=job.job_id,
            status="completed", attempts=job.attempts,
            recovered=job.recovered_values > 0,
            packed_batches=job.packed_batches,
            seconds=time.monotonic() - job.submitted_at,
            device_seconds=job.device_seconds,
            device_basis=job.device_basis,
            **({"profile_path": job.profile_path}
               if job.profile_path else {}),
            **({"planned": job.plan.method,
                "plan_reason": job.plan.reason,
                "plan": job.plan.describe()}
               if job.plan is not None else {}),
            **job._slo_attrs())
        self._release_engine_data(job)
        self._retire(job)
        job._finish()

    def _terminal(self, job: SweepJob, status: str,
                  err: BaseException) -> None:
        job.status = status
        job.error = err
        # the engine holds the only references to the job's device
        # buffers (stacked data, eval sets, any banked-state leftovers):
        # dropping it here is what "cancelled without leaking device
        # buffers" means
        job.engine = None
        kind = {"cancelled": "cancel", "quarantined": "quarantine",
                "shed": "shed"}[status]
        self._journal_safe({"type": kind, "job": job.job_id,
                            "tenant": job.tenant,
                            "device_seconds": job.device_seconds,
                            "device_basis": job.device_basis,
                            "error": str(err)[:500]})
        counter = {"cancelled": "service.jobs_cancelled",
                   "quarantined": "service.jobs_quarantined",
                   "shed": "service.jobs_shed"}[status]
        obs_metrics.counter(counter).inc()
        obs_metrics.histogram("service.job_attempts",
                              tenant=job.tenant).observe(job.attempts)
        obs_trace.event(
            "service.job", tenant=job.tenant, job=job.job_id,
            status=status, attempts=job.attempts,
            recovered=job.recovered_values > 0,
            packed_batches=job.packed_batches,
            seconds=time.monotonic() - job.submitted_at,
            device_seconds=job.device_seconds,
            device_basis=job.device_basis,
            **({"profile_path": job.profile_path}
               if job.profile_path else {}),
            **({"planned": job.plan.method,
                "plan_reason": job.plan.reason}
               if job.plan is not None else {}),
            error=str(err)[:200], **job._slo_attrs())
        self._retire(job)
        job._finish()
