"""Sweep-as-a-service: fault-isolated multi-tenant sweep scheduling with
a device-pinned worker pool and journaled crash recovery (scheduler.py),
SLO-driven admission control, priority tiers and load shedding
(admission.py), cross-tenant program packing bookkeeping (packer.py),
the checksummed write-ahead journal (journal.py) and the fleet router —
the redirect-acting, tenant-sticky front over N service shards with
shard failover (router.py)."""

from .admission import AdmissionController, TierQueue
from .journal import JournalCorruptError, SweepJournal
from .packer import CrossTenantPacker
from .router import FleetRouter, RoutedJobFailed
from .scheduler import (JobCancelled, JobQuarantined, JobShed,
                        ServiceAuthError, ServiceClosed, ServiceError,
                        ServiceOverloaded, ServiceRejected, SweepJob,
                        SweepService)

__all__ = [
    "AdmissionController",
    "CrossTenantPacker",
    "FleetRouter",
    "JobCancelled",
    "JobQuarantined",
    "JobShed",
    "JournalCorruptError",
    "RoutedJobFailed",
    "ServiceAuthError",
    "ServiceClosed",
    "ServiceError",
    "ServiceOverloaded",
    "ServiceRejected",
    "SweepJob",
    "SweepJournal",
    "SweepService",
    "TierQueue",
]
