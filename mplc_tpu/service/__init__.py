"""Sweep-as-a-service: fault-isolated multi-tenant sweep scheduling with
a device-pinned worker pool and journaled crash recovery (scheduler.py),
SLO-driven admission control, priority tiers and load shedding
(admission.py), cross-tenant program packing bookkeeping (packer.py) and
the checksummed write-ahead journal (journal.py)."""

from .admission import AdmissionController, TierQueue
from .journal import JournalCorruptError, SweepJournal
from .packer import CrossTenantPacker
from .scheduler import (JobCancelled, JobQuarantined, JobShed,
                        ServiceClosed, ServiceError, ServiceOverloaded,
                        ServiceRejected, SweepJob, SweepService)

__all__ = [
    "AdmissionController",
    "CrossTenantPacker",
    "JobCancelled",
    "JobQuarantined",
    "JobShed",
    "JournalCorruptError",
    "ServiceClosed",
    "ServiceError",
    "ServiceOverloaded",
    "ServiceRejected",
    "SweepJob",
    "SweepJournal",
    "SweepService",
    "TierQueue",
]
