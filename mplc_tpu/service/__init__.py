"""Sweep-as-a-service: fault-isolated multi-tenant sweep scheduling with
journaled crash recovery (scheduler.py), cross-tenant program packing
bookkeeping (packer.py) and the checksummed write-ahead journal
(journal.py)."""

from .journal import JournalCorruptError, SweepJournal
from .packer import CrossTenantPacker
from .scheduler import (JobCancelled, JobQuarantined, ServiceClosed,
                        ServiceError, ServiceOverloaded, ServiceRejected,
                        SweepJob, SweepService)

__all__ = [
    "CrossTenantPacker",
    "JobCancelled",
    "JobQuarantined",
    "JournalCorruptError",
    "ServiceClosed",
    "ServiceError",
    "ServiceOverloaded",
    "ServiceRejected",
    "SweepJob",
    "SweepJournal",
    "SweepService",
]
