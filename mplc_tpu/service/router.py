"""Fleet router — the redirect-ACTING, tenant-sticky front over N
`SweepService` shards.

The fleet plane (parallel/fleet.py) gives every shard a published state
file and every `ServiceOverloaded` a least-loaded redirect hint, but
nothing in the tree ACTS on them: each admission governor sheds only its
own queue, a client that hits backpressure is on its own, and a dead
shard strands its journaled jobs until an operator replays the WAL by
hand. This module is the missing client-facing half:

  **Redirects, acted on.** `FleetRouter.submit` resubmits on
  `ServiceOverloaded`/`JobShed`, following the error's cluster redirect
  hint (`least_loaded`) with capped exponential backoff that honors the
  error's own `retry_after_sec`. The loop is bounded by a per-job
  routing budget (`MPLC_TPU_ROUTER_BUDGET`, default 8 total attempts)
  after which the failure surfaces as a classified `RoutedJobFailed` —
  never a silent drop, never an unbounded retry storm.

  **Tenant stickiness.** A tenant's resident `LiveGame` and banked
  programs live on ONE shard; routing its next job elsewhere forfeits
  the residency the live tier paid for. The router therefore pins each
  tenant to the shard that last accepted its work and keeps routing
  there, breaking the pin only on shard death or on
  `MPLC_TPU_ROUTER_REPIN_OVERLOADS` CONSECUTIVE overloads from the
  pinned shard — a deliberate, journaled re-pin (`router.repin`), since
  a re-pin costs the tenant a WAL restore on the new shard.

  **Cluster-wide shed coordination.** Each shard's published state
  carries its admission-governor state. The router stops OFFERING new
  work to deferring/shedding shards while any healthy shard remains, so
  per-shard load shedding becomes fleet-level graceful degradation: the
  governor that would have shed never sees the work. When every shard
  is unhealthy the router degrades to least-loaded among the living —
  refusing all work would turn an overload into an outage.

  **Failover.** A shard whose published heartbeat goes stale
  (`cluster_view` staleness bound), whose `/healthz` flips 503/
  unreachable, or which a chaos plan kills is drained from the routing
  table. Its journaled incomplete jobs are resubmitted to surviving
  shards through the EXISTING recovered-jobs/WAL-seeding path: the
  router replays the dead shard's journal (`SweepJournal.replay`),
  hands each job's harvested `{subset: value}` map to the survivor via
  `SweepService.adopt_recovered`, and resubmits under the old job id —
  `_build_engine` seeds the fresh engine's memo from those values, so a
  failed-over job's completed v(S) table is BIT-IDENTICAL to a solo
  fault-free run (the PR-11 overload invariant, now under shard-kill
  chaos) and only never-harvested coalitions train again.

Two shard flavors share one routing core:

  `InProcShard` — wraps a `SweepService` in this process (the
  deterministic test/bench harness; inline `start=False` services are
  advanced by `FleetRouter.pump`). A killed in-proc shard is ABANDONED,
  not shut down: its journal file stays exactly as a SIGKILL would
  leave it, which is what failover replays.

  `HttpShard` — a peer process discovered through the shared fleet
  state dir (each shard publishes its telemetry port and journal path
  in its state file). Submission goes over `POST /router/submit` on the
  peer's telemetry server (`ShardServer` + obs/export.py, gated on
  `MPLC_TPU_ROUTER_SERVE=1`); results are polled via
  `GET /router/job?id=`. When `MPLC_TPU_METRICS_TOKEN` is set the wire
  REQUIRES the per-tenant HMAC credential — the in-process embedder is
  trusted, the network is not.

Chaos: `MPLC_TPU_ROUTER_FAULT_PLAN` (faults.py) kills shards on a
schedule — `shardkill@shard1:sec5` — so the failover path is a routine,
deterministically exercised code path rather than an emergency one.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import urllib.error
import urllib.request
import weakref
from urllib.parse import quote as _urlquote

from .. import constants, faults
from ..obs import export as obs_export
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .journal import SweepJournal
from .scheduler import (JobShed, ServiceAuthError, ServiceClosed,
                        ServiceError, ServiceOverloaded)

logger = logging.getLogger("mplc_tpu")

# capped exponential backoff: attempt k sleeps
# max(retry_after hint, min(base * 2^(k-1), base * _BACKOFF_CAP_MULT)) —
# the cap bounds the router's OWN exponential term; a shard's explicit
# retry_after_sec hint is always honored in full
_BACKOFF_CAP_MULT = 32.0
# liveness probes (healthz / cluster view) are rate-limited per shard so
# a tight routing loop never turns into a tight HTTP/stat loop
_PROBE_INTERVAL_SEC = 0.5
_HTTP_TIMEOUT_SEC = 10.0
# terminal routed jobs are archived as small summaries (shard/status/
# attempts) rather than kept as full records — a long-lived router must
# not leak one req+handle per job forever. /varz shows the most recent:
_DONE_JOBS_KEEP = 256


class RoutedJobFailed(ServiceError):
    """The routing budget ran out (or no live shard remained) before any
    shard accepted the job. A CLASSIFIED terminal outcome — counted in
    `router.budget_exhausted`, journaled on the `router.exhausted`
    event, `__cause__` carrying the last shard error — never a silent
    drop. Nothing about the job itself is wrong; resubmit when the
    cluster has capacity."""

    def __init__(self, msg: str, tenant: "str | None" = None,
                 job_id: "str | None" = None, attempts: int = 0):
        super().__init__(msg)
        self.tenant = tenant
        self.job_id = job_id
        self.attempts = int(attempts)


class ShardUnavailable(ServiceError):
    """Internal routing signal: the targeted shard is dead/unreachable
    at submit time (connection refused, closed service, killed handle).
    Routing treats it like a failed attempt and excludes the shard; it
    never escapes `FleetRouter.submit`."""


# ---------------------------------------------------------------------------
# shard handles
# ---------------------------------------------------------------------------

class InProcShard:
    """One in-process `SweepService` behind the router — the
    deterministic harness the chaos tests and BENCH_CONFIG=11 drive.
    `kill()` ABANDONS the service (no shutdown, no journal close): the
    WAL on disk is exactly what a SIGKILL would leave, which is what
    failover replays. A threaded (start=True) service additionally has
    its worker pool stopped at the next quantum boundary
    (`SweepService.abandon`) — a "dead" shard must not keep executing
    the jobs failover resubmits to survivors."""

    kind = "inproc"

    def __init__(self, shard_id: str, service):
        self.shard_id = str(shard_id)
        self.service = service
        self.dead = False
        self._drained = False  # failover ran for this shard

    @property
    def journal_path(self) -> "str | None":
        j = self.service._journal
        return j.path if j is not None else None

    def admission_state(self) -> str:
        return self.service._admission.state

    def queue_depth(self) -> int:
        return len(self.service._queue)

    def closed(self) -> bool:
        return bool(self.service._closed)

    def submit(self, req: dict, recover: "dict | None" = None):
        if self.dead:
            raise ShardUnavailable(
                f"shard {self.shard_id!r} is dead")
        if recover is not None:
            self._adopt(recover, req)
        return self.service.submit(
            req["scenario"], method=req["method"], tenant=req["tenant"],
            deadline_sec=req.get("deadline_sec"), job_id=req["job_id"],
            priority=req.get("priority"),
            credential=req.get("credential"))

    def _adopt(self, recover: dict, req: dict) -> None:
        # re-adoption of the SAME seed on a routing retry is idempotent
        # inside adopt_recovered; a differing seed raises — a real bug,
        # never swallowed (it would silently break bit-identity)
        self.service.adopt_recovered(
            req["job_id"], tenant=req["tenant"], method=req["method"],
            partners_count=recover.get("partners_count"),
            values=recover.get("values") or {})

    def job_status(self, job_id: str) -> dict:
        job = self.service._jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        return _job_doc(job)

    def kill(self) -> None:
        self.dead = True
        if self.service._workers:
            # a threaded (start=True) service's workers would otherwise
            # keep executing the very jobs failover is about to resubmit
            # to survivors — duplicate execution, double device-second
            # metering. abandon() stops them at the next quantum
            # boundary without draining, cancelling or closing the
            # journal, so the WAL stays SIGKILL-shaped for the replay.
            self.service.abandon()

    def pump(self) -> bool:
        """Advance an inline (start=False) service one scheduling
        quantum; True while it has work. Threaded services drain
        themselves — pumping them would run quanta on the router
        thread."""
        if self.dead or self.service._workers:
            return False
        try:
            return self.service.step()
        except Exception:  # a shard's crash is its own; the router routes
            logger.exception("router: in-proc shard %s pump failed",
                             self.shard_id)
            return False

    def describe(self) -> dict:
        return {"kind": self.kind, "dead": self.dead,
                "closed": self.closed(),
                "admission_state": self.admission_state(),
                "queue_depth": self.queue_depth(),
                "journal_path": self.journal_path}


class HttpShard:
    """A peer shard process reached over its telemetry server's routed
    surface (`POST /router/submit`, `GET /router/job` — ShardServer,
    gated on MPLC_TPU_ROUTER_SERVE=1). Discovered through the fleet
    state dir: the shard's published state carries its `port` and
    `journal_path`, refreshed on every cluster-view poll."""

    kind = "http"

    def __init__(self, shard_id: str, port: "int | None" = None,
                 host: str = "127.0.0.1",
                 journal_path: "str | None" = None,
                 credential: "str | None" = None):
        self.shard_id = str(shard_id)
        self.host = host
        self.port = port
        self.journal_path = journal_path
        self.dead = False
        self._drained = False
        self._admission_state = "healthy"
        self._queue_depth = 0
        self._closed = False
        self._last_probe = 0.0
        # operator bearer for the polling GET (the submit credential
        # rides each request body)
        self._credential = credential

    def update_from_state(self, row: dict) -> None:
        """Fold one published cluster_view row into the handle."""
        if row.get("port") is not None:
            self.port = int(row["port"])
        if row.get("journal_path"):
            self.journal_path = row["journal_path"]
        self._admission_state = row.get("admission_state") or "healthy"
        self._queue_depth = int(row.get("queue_depth") or 0)
        self._closed = bool(row.get("closed"))

    def admission_state(self) -> str:
        return self._admission_state

    def queue_depth(self) -> int:
        return self._queue_depth

    def closed(self) -> bool:
        return self._closed

    # -- wire ------------------------------------------------------------

    def _url(self, path: str) -> str:
        if self.port is None:
            raise ShardUnavailable(
                f"shard {self.shard_id!r} has not published a port")
        return f"http://{self.host}:{self.port}{path}"

    def _request(self, path: str, body: "dict | None" = None) -> dict:
        req = urllib.request.Request(
            self._url(path),
            data=(json.dumps(body).encode() if body is not None
                  else None),
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {self._credential}"}
                        if self._credential else {})},
            method="POST" if body is not None else "GET")
        try:
            with urllib.request.urlopen(
                    req, timeout=_HTTP_TIMEOUT_SEC) as resp:
                return json.loads(resp.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            raise self._classify(e) from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ShardUnavailable(
                f"shard {self.shard_id!r} unreachable: {e}") from e

    def _classify(self, e: "urllib.error.HTTPError") -> ServiceError:
        try:
            doc = json.loads(e.read().decode() or "{}")
        except Exception:
            doc = {}
        msg = doc.get("error") or f"HTTP {e.code}"
        if e.code == 429:
            err = (JobShed if doc.get("kind") == "shed"
                   else ServiceOverloaded)(
                msg, retry_after_sec=float(
                    doc.get("retry_after_sec") or 0.0))
            err.cluster = doc.get("cluster")
            return err
        if e.code in (401, 403):
            return ServiceAuthError(msg)
        if e.code == 503:
            return ShardUnavailable(msg)
        return ServiceError(msg)

    def submit(self, req: dict, recover: "dict | None" = None):
        body = {"spec": req.get("spec"), "method": req["method"],
                "tenant": req["tenant"], "job_id": req["job_id"],
                "priority": req.get("priority"),
                "deadline_sec": req.get("deadline_sec"),
                "credential": req.get("credential")}
        if recover is not None:
            body["recover"] = {
                "partners_count": recover.get("partners_count"),
                "values": [[list(s), v] for s, v in
                           sorted((recover.get("values") or {}).items())]}
        ack = self._request("/router/submit", body)
        return ack.get("job") or req["job_id"]

    def job_status(self, job_id: str) -> dict:
        return self._request(f"/router/job?id={_urlquote(job_id)}")

    def healthz_ok(self) -> bool:
        try:
            self._request("/healthz")
            return True
        except ShardUnavailable:
            return False
        except ServiceError:
            # an HTTP error status other than 503 still proves liveness
            return True

    def kill(self) -> None:
        # the process itself is killed by whoever owns it (load_gen's
        # driver, an operator); the router's part is the table drain
        self.dead = True

    def pump(self) -> bool:
        return False

    def describe(self) -> dict:
        return {"kind": self.kind, "dead": self.dead,
                "closed": self._closed, "port": self.port,
                "admission_state": self._admission_state,
                "queue_depth": self._queue_depth,
                "journal_path": self.journal_path}


def _job_doc(job) -> dict:
    """One job's wire/status document (shared by the in-proc handle and
    the ShardServer's GET /router/job): terminal status, scores and the
    full v(S) table — host-side floats that round-trip exactly through
    JSON, which is what makes the router's bit-identity check wire-safe."""
    doc = {"job": job.job_id, "tenant": job.tenant,
           "status": job.status, "done": job.done,
           "error": (f"{type(job.error).__name__}: {job.error}"
                     if job.error is not None else None)}
    if job.done and job.error is None:
        scores = job.scores
        if scores is not None and hasattr(scores, "tolist"):
            scores = scores.tolist()
        doc["scores"] = scores
        if job.values is not None:
            doc["values"] = [[list(s), float(v)]
                             for s, v in sorted(job.values.items())]
    return doc


# ---------------------------------------------------------------------------
# routed job handle
# ---------------------------------------------------------------------------

class RoutedJob:
    """Handle for one router-submitted job. Mirrors the `SweepJob`
    consumer surface (`done` / `status` / `result`) but survives
    failover: when the accepting shard dies, the router resubmits and
    swaps the inner handle — the caller's `result()` keeps working and
    returns values bit-identical to a fault-free run."""

    def __init__(self, router, job_id: str, tenant: str):
        self.router = router
        self.job_id = job_id
        self.tenant = tenant
        self.shard_id: "str | None" = None
        self.attempts = 0
        self.failed_over = False
        self._inner = None          # SweepJob (in-proc shards)
        self._remote: "HttpShard | None" = None
        self._error: "BaseException | None" = None
        self._final: "dict | None" = None

    @property
    def done(self) -> bool:
        if self._error is not None or self._final is not None:
            return True
        if self._inner is not None:
            return self._inner.done
        return False

    @property
    def status(self) -> str:
        if self._error is not None:
            return "failed"
        if self._final is not None:
            return self._final.get("status", "done")
        if self._inner is not None:
            return self._inner.status
        return "routed"

    def result(self, timeout: "float | None" = None):
        """Block for the contributivity scores; raises the terminal
        error (`RoutedJobFailed` after budget exhaustion, the shard's
        own `JobQuarantined`/`JobCancelled`/`JobShed` otherwise)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            if self._error is not None:
                raise self._error
            if self._inner is not None:
                wait = (None if deadline is None
                        else max(deadline - time.monotonic(), 0.0))
                try:
                    return self._inner.result(wait)
                except TimeoutError:
                    raise
                except ServiceError:
                    # the shard may have died mid-wait and the router
                    # swapped the handle — only surface a terminal error
                    # that is still THIS job's word
                    if self._error is not None:
                        raise self._error from None
                    raise
            doc = self._final or self.router._poll_job(self)
            if doc is not None and doc.get("done"):
                self._final = doc
                if doc.get("error"):
                    raise ServiceError(
                        f"routed job {self.job_id} failed on shard "
                        f"{self.shard_id}: {doc['error']}")
                return doc.get("scores")
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"routed job {self.job_id} not finished "
                    f"(status={self.status})")
            time.sleep(0.05)

    def values(self) -> "dict | None":
        """The completed job's full v(S) table `{subset_tuple: float}`
        (None until done) — the bit-identity surface the chaos
        acceptance compares against a solo fault-free run."""
        if self._inner is not None and self._inner.values is not None:
            return dict(self._inner.values)
        doc = self._final
        if doc is None and self._remote is not None:
            doc = self.router._poll_job(self)
            if doc is not None and doc.get("done"):
                self._final = doc
        if doc and doc.get("values") is not None:
            return {tuple(s): float(v) for s, v in doc["values"]}
        return None


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class FleetRouter:
    """The routing front (module docstring). Construct with explicit
    shards (`add_shard` / the `shards=` mapping of id -> SweepService
    for in-proc fleets) and/or a fleet `state_dir` whose published shard
    states are folded into the table on every refresh (HTTP peers).

    Thread-safe for concurrent `submit` callers; the backoff sleeps
    happen outside the table lock. `close()` unregisters the /varz
    provider and closes the router's own re-pin journal — it does NOT
    shut the shards down (the router fronts services it doesn't own)."""

    def __init__(self, shards: "dict | None" = None,
                 state_dir: "str | None" = None,
                 budget: "int | None" = None,
                 backoff_sec: "float | None" = None,
                 repin_overloads: "int | None" = None,
                 journal_path: "str | None" = None,
                 fault_plan: "list | str | None" = None,
                 credential: "str | None" = None):
        self._budget = (int(budget) if budget is not None
                        else constants._env_positive_int(
                            constants.ROUTER_BUDGET_ENV, 8))
        self._backoff = (float(backoff_sec) if backoff_sec is not None
                         else constants._env_nonneg_float(
                             constants.ROUTER_BACKOFF_ENV, 0.05))
        self._repin_overloads = (
            int(repin_overloads) if repin_overloads is not None
            else constants._env_positive_int(
                constants.ROUTER_REPIN_OVERLOADS_ENV, 3))
        if fault_plan is None:
            fault_plan = faults.router_fault_plan_from_env()
        elif isinstance(fault_plan, str):
            fault_plan = faults.parse_router_fault_plan(fault_plan)
        self._plan = list(fault_plan)
        self._fired: set = set()
        self._state_dir = state_dir
        self._credential = credential
        self._lock = threading.RLock()
        self._shards: dict = {}          # shard_id -> handle, insert order
        self._pins: dict = {}            # tenant -> shard_id
        self._pin_overloads: dict = {}   # tenant -> consecutive overloads
        self._routed: dict = {}          # job_id -> {"req", "shard", "handle"}
        self._done_jobs: dict = {}       # job_id -> summary (bounded archive)
        self._next_id = 0
        self._last_view_ts = 0.0
        self._t0 = time.monotonic()
        self._journal = (SweepJournal(journal_path)
                         if journal_path else None)
        # totals mirrored on /varz and the report's router row
        self.stats = {"routed": 0, "resubmits": 0, "repins": 0,
                      "failovers": 0, "budget_exhausted": 0}
        if shards:
            for sid, svc in shards.items():
                self.add_shard(sid, svc)
        self._provider_key = f"router_{id(self):x}"
        obs_export.register_varz(self._provider_key,
                                 weakref.WeakMethod(self.varz_view))

    # -- table management ------------------------------------------------

    def add_shard(self, shard_id: str, service_or_handle) -> None:
        """Add a shard: a `SweepService` (wrapped in an `InProcShard`)
        or a pre-built handle (`InProcShard` / `HttpShard`)."""
        handle = service_or_handle
        if not isinstance(handle, (InProcShard, HttpShard)):
            handle = InProcShard(shard_id, service_or_handle)
        with self._lock:
            self._shards[str(shard_id)] = handle

    def shard_ids(self) -> list:
        with self._lock:
            return list(self._shards)

    def close(self) -> None:
        obs_export.unregister(self._provider_key)
        if self._journal is not None:
            self._journal.close()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- liveness + chaos ------------------------------------------------

    def _resolve_shard_name(self, name: str) -> "str | None":
        """A fault-plan shard name: exact table id first, else
        `shard<N>` addresses the N-th shard (0-based insertion order) —
        so one plan string works against auto-generated `pid<...>`
        ids."""
        with self._lock:
            if name in self._shards:
                return name
            m = re.fullmatch(r"shard(\d+)", name)
            if m is not None:
                ids = list(self._shards)
                n = int(m.group(1))
                if n < len(ids):
                    return ids[n]
        return None

    def _poll_faults(self) -> None:
        if not self._plan:
            return
        elapsed = time.monotonic() - self._t0
        for i, entry in enumerate(self._plan):
            if i in self._fired or elapsed < entry["at_sec"]:
                continue
            self._fired.add(i)
            sid = self._resolve_shard_name(entry["shard"])
            obs_trace.event("router.fault", kind=entry["kind"],
                            shard=sid or entry["shard"],
                            at_sec=entry["at_sec"])
            if sid is not None:
                self.kill_shard(sid)
            else:
                logger.warning(
                    "router: fault plan names unknown shard %r "
                    "(table: %s)", entry["shard"], list(self._shards))

    def kill_shard(self, shard_id: str) -> None:
        """Chaos/test hook: kill a shard (in-proc: abandon the service
        without shutdown; HTTP: drain the handle — the process itself is
        killed by its owner) and run failover for its incomplete jobs."""
        with self._lock:
            shard = self._shards[str(shard_id)]
            shard.kill()
        self._failover(shard)

    def _refresh(self) -> None:
        """One liveness pass: fire due chaos entries, fold the published
        cluster view into the table (HTTP discovery + admission states),
        and failover any shard newly found dead (stale state / 503 /
        unreachable). Also retires terminal routed jobs: their full
        records (req + handle) are dropped and a small summary is
        archived for /varz — a long-lived router stays O(in-flight),
        not O(every job ever routed)."""
        with self._lock:
            for jid in [j for j, rec in self._routed.items()
                        if rec["handle"].done]:
                h = self._routed.pop(jid)["handle"]
                self._done_jobs[jid] = {
                    "shard": h.shard_id, "status": h.status,
                    "attempts": h.attempts,
                    "failed_over": h.failed_over}
            while len(self._done_jobs) > _DONE_JOBS_KEEP:
                del self._done_jobs[next(iter(self._done_jobs))]
        self._poll_faults()
        view = None
        if self._state_dir:
            from ..parallel import fleet
            view = fleet.cluster_view(self._state_dir)
            with self._lock:
                for sid, row in view["shards"].items():
                    handle = self._shards.get(sid)
                    if handle is None:
                        handle = HttpShard(sid,
                                           credential=self._credential)
                        handle.update_from_state(row)
                        self._shards[sid] = handle
                    elif isinstance(handle, HttpShard):
                        handle.update_from_state(row)
        dead = []
        with self._lock:
            for sid, shard in self._shards.items():
                if shard.dead:
                    continue
                if self._looks_dead(shard, view):
                    shard.kill()
                    dead.append(shard)
        for shard in dead:
            self._failover(shard)

    def _looks_dead(self, shard, view: "dict | None") -> bool:
        if isinstance(shard, InProcShard):
            return False  # killed only explicitly (kill_shard / plan)
        row = (view or {}).get("shards", {}).get(shard.shard_id)
        if row is not None and row.get("stale"):
            # heartbeat went stale: probe /healthz before declaring
            # death — a shard starved of publish cycles may still serve
            return not self._probe(shard)
        if row is None and view is not None:
            return not self._probe(shard)
        return False

    def _probe(self, shard: "HttpShard") -> bool:
        now = time.monotonic()
        if now - shard._last_probe < _PROBE_INTERVAL_SEC:
            return not shard.dead
        shard._last_probe = now
        return shard.healthz_ok()

    # -- shard selection -------------------------------------------------

    def _offerable(self, exclude) -> list:
        """Shards the router will offer new work: alive, not closed,
        admission-HEALTHY (the cluster-wide shed coordination — a
        deferring/shedding governor gets nothing new). Falls back to
        alive-but-unhealthy when no healthy shard remains: degraded
        routing beats refusing the whole fleet's work."""
        with self._lock:
            alive = [s for sid, s in self._shards.items()
                     if not s.dead and not s.closed()
                     and sid not in exclude]
        healthy = [s for s in alive
                   if s.admission_state() == "healthy"]
        return healthy or alive

    def _pick(self, tenant: str, exclude, prefer: "str | None" = None):
        """Sticky pin first, the redirect hint second, least-loaded
        last. Returns None when nothing is offerable."""
        cands = self._offerable(exclude)
        if not cands:
            return None
        by_id = {s.shard_id: s for s in cands}
        with self._lock:
            pin = self._pins.get(tenant)
        if pin in by_id:
            return by_id[pin]
        if prefer in by_id:
            return by_id[prefer]
        return min(cands, key=lambda s: s.queue_depth())

    # -- stickiness ------------------------------------------------------

    def _break_pin(self, tenant: str, reason: str,
                   to: "str | None" = None) -> None:
        with self._lock:
            old = self._pins.pop(tenant, None)
            if old is None:
                return
            self._pin_overloads.pop(tenant, None)
            self.stats["repins"] += 1
        obs_metrics.counter("router.repins").inc()
        obs_trace.event("router.repin", tenant=tenant, **{"from": old},
                        to=to, reason=reason)
        if self._journal is not None:
            try:
                self._journal.append({"type": "repin", "tenant": tenant,
                                      "from": old, "to": to,
                                      "reason": reason})
            except OSError as e:
                logger.warning("router: could not journal re-pin: %s", e)

    def _note_overload(self, tenant: str, shard_id: str) -> None:
        # the count-then-break must be one atomic step: a concurrent
        # _accept (re-pin to a fresh shard, counter reset) between the
        # increment and the break would otherwise get its new pin broken
        with self._lock:
            if self._pins.get(tenant) != shard_id:
                return
            n = self._pin_overloads.get(tenant, 0) + 1
            self._pin_overloads[tenant] = n
            if n >= self._repin_overloads:
                # sustained overload from the pinned shard: a deliberate
                # re-pin (the new pin lands on the next accepted submit)
                self._break_pin(tenant, reason="overload")

    # -- submission ------------------------------------------------------

    def submit(self, scenario=None, method: str = "Shapley values",
               tenant: str = "tenant0",
               deadline_sec: "float | None" = None,
               job_id: "str | None" = None,
               priority: "int | None" = None,
               credential: "str | None" = None,
               spec: "dict | None" = None) -> RoutedJob:
        """Route one job to the fleet. `scenario` feeds in-proc shards
        directly; `spec` is the serializable game description
        (`scenario_builder` arguments) an HTTP peer rebuilds it from —
        pass both when the fleet mixes flavors. Returns a `RoutedJob`;
        raises `RoutedJobFailed` when the routing budget is exhausted
        and `ServiceAuthError` when the shard rejects the credential
        (auth errors are the caller's mistake — retrying them would
        spend budget on a wrong password)."""
        with self._lock:
            self._next_id += 1
            if job_id is None:
                job_id = f"rt{self._next_id}"
            if job_id in self._routed or job_id in self._done_jobs:
                # terminal jobs older than the _DONE_JOBS_KEEP archive
                # window are forgotten — their ids become reusable
                raise ValueError(
                    f"job id {job_id!r} already routed by this router")
        if credential is None:
            credential = self._credential
        req = {"scenario": scenario, "spec": spec, "method": method,
               "tenant": tenant, "deadline_sec": deadline_sec,
               "job_id": job_id, "priority": priority,
               "credential": credential}
        handle = RoutedJob(self, job_id, tenant)
        t0 = time.monotonic()
        inner, shard, attempts = self._route(req, handle)
        route_s = time.monotonic() - t0
        handle.attempts = attempts
        self._accept(handle, req, shard, inner)
        self.stats["routed"] += 1
        obs_metrics.counter("router.jobs_routed").inc()
        obs_metrics.histogram("router.route_sec",
                              tenant=tenant).observe(route_s)
        obs_trace.event("router.submit", tenant=tenant, job=job_id,
                        shard=shard.shard_id, attempts=attempts,
                        route_s=round(route_s, 6))
        return handle

    def _accept(self, handle: RoutedJob, req: dict, shard,
                inner) -> None:
        with self._lock:
            handle.shard_id = shard.shard_id
            if isinstance(shard, InProcShard):
                handle._inner = inner
                handle._remote = None
            else:
                handle._inner = None
                handle._remote = shard
            self._routed[handle.job_id] = {"req": req,
                                           "shard": shard.shard_id,
                                           "handle": handle}
            self._pins[req["tenant"]] = shard.shard_id
            self._pin_overloads[req["tenant"]] = 0

    def _route(self, req: dict, handle: RoutedJob,
               recover: "dict | None" = None,
               exclude: "frozenset | set" = frozenset()) -> tuple:
        """The routing core: pick -> submit -> follow redirects, bounded
        by the budget. Returns `(inner, shard, attempts)`."""
        exclude = set(exclude)
        tenant, job_id = req["tenant"], req["job_id"]
        attempts = 0
        prefer = None
        last: "BaseException | None" = None
        while True:
            self._refresh()
            shard = self._pick(tenant, exclude, prefer)
            prefer = None
            if shard is None:
                raise self._exhaust(
                    req, attempts,
                    "no live shard remains in the routing table", last)
            attempts += 1
            try:
                inner = shard.submit(req, recover=recover)
            except (ServiceOverloaded, JobShed) as e:
                last = e
                self._note_overload(tenant, shard.shard_id)
                self.stats["resubmits"] += 1
                obs_metrics.counter("router.resubmits").inc()
                hint = float(getattr(e, "retry_after_sec", 0.0) or 0.0)
                prefer = ((getattr(e, "cluster", None) or {})
                          .get("least_loaded"))
                if prefer == shard.shard_id:
                    prefer = None
                obs_trace.event("router.redirect", tenant=tenant,
                                job=job_id, attempt=attempts,
                                retry_after_sec=round(hint, 6),
                                to=prefer, **{"from": shard.shard_id})
                if attempts >= self._budget:
                    raise self._exhaust(
                        req, attempts,
                        f"last shard {shard.shard_id!r} said: {e}", e)
                self._backoff_wait(hint, attempts)
            except (ShardUnavailable, ServiceClosed) as e:
                # the table was wrong: the shard died between refresh
                # and submit. Drain it (failover resubmits ITS jobs;
                # this one was never accepted there) and move on.
                last = e
                with self._lock:
                    dead_shard = self._shards.get(shard.shard_id)
                if dead_shard is not None and not dead_shard.dead:
                    dead_shard.kill()
                    self._failover(dead_shard)
                exclude.add(shard.shard_id)
                if attempts >= self._budget:
                    raise self._exhaust(
                        req, attempts,
                        f"last shard {shard.shard_id!r} said: {e}", e)
            else:
                return inner, shard, attempts

    def _exhaust(self, req: dict, attempts: int, why: str,
                 cause: "BaseException | None") -> RoutedJobFailed:
        self.stats["budget_exhausted"] += 1
        obs_metrics.counter("router.budget_exhausted").inc()
        obs_trace.event("router.exhausted", tenant=req["tenant"],
                        job=req["job_id"], attempts=attempts,
                        budget=self._budget)
        err = RoutedJobFailed(
            f"routing budget exhausted for job {req['job_id']!r} "
            f"(tenant {req['tenant']!r}): {attempts} attempt(s) of "
            f"{constants.ROUTER_BUDGET_ENV}={self._budget}; {why}",
            tenant=req["tenant"], job_id=req["job_id"],
            attempts=attempts)
        err.__cause__ = cause
        return err

    def _backoff_wait(self, hint: float, attempt: int) -> None:
        """Capped exponential backoff honoring the shard's own
        retry_after hint; in-proc inline shards are pumped while the
        router waits, so the very backpressure being backed off from is
        actually draining."""
        delay = max(hint,
                    min(self._backoff * (2.0 ** (attempt - 1)),
                        self._backoff * _BACKOFF_CAP_MULT))
        deadline = time.monotonic() + delay
        while True:
            worked = False
            with self._lock:
                shards = list(self._shards.values())
            for s in shards:
                worked = s.pump() or worked
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            if not worked:
                time.sleep(min(remaining, 0.01))

    # -- failover --------------------------------------------------------

    def _failover(self, shard) -> None:
        """Drain a dead shard: replay its journal, resubmit its
        incomplete routed jobs to survivors through the recovered-jobs/
        WAL-seeding path (bit-identical continuations), and break the
        sticky pins that pointed at the corpse — each tenant re-pins
        exactly once per death, on its first resubmitted job."""
        with self._lock:
            if shard._drained:
                return
            shard._drained = True
            victims = [rec for rec in self._routed.values()
                       if rec["shard"] == shard.shard_id
                       and not rec["handle"].done]
            pinned_tenants = [t for t, sid in self._pins.items()
                              if sid == shard.shard_id]
        self.stats["failovers"] += 1
        obs_metrics.counter("router.failovers").inc()
        recovered = self._replay_journal(shard)
        resubmitted = 0
        for rec in victims:
            req, handle = rec["req"], rec["handle"]
            jrec = recovered.get(req["job_id"]) or {}
            if jrec.get("done") and handle._inner is not None \
                    and handle._inner.done:
                continue  # completed before death; the handle has it
            recover = {"values": jrec.get("values") or {},
                       "partners_count": (
                           jrec.get("partners_count")
                           if jrec.get("partners_count") is not None
                           else self._partners_of(req))}
            # the re-pin: break the dead pin BEFORE routing so the pick
            # lands on a survivor; the accept below establishes the new
            # pin — exactly one repin per (tenant, death)
            with self._lock:
                pinned_to_corpse = (
                    self._pins.get(req["tenant"]) == shard.shard_id)
            if pinned_to_corpse:
                self._break_pin(req["tenant"], reason="death")
            try:
                inner, new_shard, attempts = self._route(
                    req, handle, recover=recover,
                    exclude={shard.shard_id})
            except RoutedJobFailed as e:
                # surfaced classified on the handle — a failover that
                # cannot place a job must not hang its consumer
                handle._error = e
                if handle._inner is not None:
                    handle._inner = None
                continue
            handle.attempts += attempts
            handle.failed_over = True
            self._accept(handle, req, new_shard, inner)
            resubmitted += 1
        # tenants pinned to the corpse with no in-flight job still need
        # their pin broken (their NEXT submit re-pins)
        with self._lock:
            remaining = [t for t in pinned_tenants
                         if self._pins.get(t) == shard.shard_id]
        for t in remaining:
            self._break_pin(t, reason="death")
        obs_trace.event("router.failover", shard=shard.shard_id,
                        jobs=len(victims), resubmitted=resubmitted)

    @staticmethod
    def _partners_of(req: dict) -> "int | None":
        sc = req.get("scenario")
        if sc is not None:
            return int(sc.partners_count)
        spec = req.get("spec") or {}
        return (int(spec["partners"]) if spec.get("partners") is not None
                else None)

    def _replay_journal(self, shard) -> dict:
        """A dead shard's WAL -> `{job_id: {"values": {subset: float},
        "done": bool, "partners_count": int}}` — the same records
        `SweepService._replay_record` reads, replayed router-side
        because the dead service can no longer do it for us. A missing
        or torn journal yields what it yields: failover reseeds from
        whatever was durably harvested, the rest retrains (identically —
        that is the WAL-seeding contract)."""
        path = shard.journal_path
        out: dict = {}
        if not path or not os.path.exists(path):
            return out
        try:
            records, _torn = SweepJournal.replay(path)
        except Exception as e:  # corrupt mid-file: recover nothing
            logger.warning("router: journal replay for dead shard %s "
                           "failed: %s", shard.shard_id, e)
            return out
        for rec in records:
            kind, job = rec.get("type"), rec.get("job")
            if kind == "submit":
                slot = out.setdefault(job, {"values": {}, "done": False})
                slot["partners_count"] = rec.get("partners_count")
            elif kind == "value" and job in out:
                out[job]["values"][tuple(rec["subset"])] = rec["value"]
            elif kind in ("done", "quarantine", "cancel", "shed") \
                    and job in out:
                out[job]["done"] = True
        return out

    # -- polling / pumping ----------------------------------------------

    def _poll_job(self, handle: RoutedJob) -> "dict | None":
        self._refresh()
        with self._lock:
            shard = self._shards.get(handle.shard_id)
        if shard is None or shard.dead:
            return None
        try:
            return shard.job_status(handle.job_id)
        except (ShardUnavailable, KeyError):
            return None

    def pump(self) -> bool:
        """Advance every alive inline in-proc shard one quantum (and
        fire due chaos entries). True while any shard reports work or
        any routed job is non-terminal — the deterministic drive loop
        for tests and BENCH_CONFIG=11."""
        self._refresh()
        with self._lock:
            shards = list(self._shards.values())
            handles = [r["handle"] for r in self._routed.values()]
        busy = False
        for s in shards:
            busy = s.pump() or busy
        return busy or any(not h.done for h in handles)

    def run_until_idle(self, timeout: "float | None" = None) -> None:
        """Pump until every routed job is terminal."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while self.pump():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("router did not drain in time")

    # -- observability ---------------------------------------------------

    def varz_view(self) -> dict:
        """The /varz `router_*` block: the live routing table, sticky
        pins and routing totals — what an operator reads to see WHERE
        the fleet's work is going and which shards are drained. `jobs`
        carries every in-flight job plus the last `_DONE_JOBS_KEEP`
        terminal ones (older terminals live on only in the totals)."""
        with self._lock:
            table = {sid: s.describe()
                     for sid, s in self._shards.items()}
            pins = dict(self._pins)
            jobs = {jid: dict(s) for jid, s in self._done_jobs.items()}
            jobs.update({jid: {"shard": r["shard"],
                               "status": r["handle"].status,
                               "attempts": r["handle"].attempts,
                               "failed_over": r["handle"].failed_over}
                         for jid, r in self._routed.items()})
        return {"budget": self._budget,
                "backoff_sec": self._backoff,
                "repin_overloads": self._repin_overloads,
                "table": table, "pins": pins, "jobs": jobs,
                **self.stats}


# ---------------------------------------------------------------------------
# shard-side HTTP peer
# ---------------------------------------------------------------------------

class ShardServer:
    """The shard-side half of the HTTP wire: wraps this process's
    `SweepService` as a routed peer. Registers the obs/export.py sink
    behind `POST /router/submit` / `GET /router/job` (routes exist only
    with `MPLC_TPU_ROUTER_SERVE=1` and a running telemetry server —
    `MPLC_TPU_METRICS_PORT`), rebuilds each wire spec into a real
    `Scenario` via the injected `scenario_builder(spec)`, and enforces
    the wire's auth rule: when `MPLC_TPU_METRICS_TOKEN` is set a routed
    submission MUST carry a credential, and the credential is validated
    BEFORE any state mutation — recover-payload adoption and scenario
    building happen on the far side of the auth check (the in-process
    embedder is trusted; the network authenticates)."""

    def __init__(self, service, scenario_builder):
        self.service = service
        self.scenario_builder = scenario_builder
        self._key = f"router_shard_{id(self):x}"
        obs_export.register_router(self._key,
                                   weakref.WeakMethod(self.handle))

    def close(self) -> None:
        obs_export.unregister(self._key)

    def handle(self, op: str, payload: dict) -> dict:
        if op == "submit":
            return self._handle_submit(payload)
        if op == "job":
            return self._handle_job(payload)
        raise ValueError(f"unknown router op {op!r}")

    def _handle_submit(self, doc: dict) -> dict:
        tenant = doc.get("tenant") or "tenant0"
        credential = doc.get("credential")
        if os.environ.get(constants.METRICS_TOKEN_ENV) and not credential:
            raise ServiceAuthError(
                "the routed submit surface requires a credential when "
                f"{constants.METRICS_TOKEN_ENV} is set (the master "
                "token, or tenant_token(master, tenant))")
        # authenticate BEFORE touching any service state: an invalid
        # wire caller must not get to install recover values (or spend
        # scenario_builder work) on its way to the 403 — a rejected
        # submission leaves the service exactly as it found it
        self.service._check_credential(tenant, credential)
        job_id = doc.get("job_id")
        recover = doc.get("recover")
        if recover is not None:
            if not job_id:
                raise ValueError("a recover payload requires the "
                                 "original job_id")
            values = {tuple(int(i) for i in s): float(v)
                      for s, v in (recover.get("values") or [])}
            # re-adoption of an identical seed on a routing retry is
            # idempotent inside adopt_recovered; a DIFFERING seed for a
            # known job raises (400 on the wire) — never silently kept
            self.service.adopt_recovered(
                job_id, tenant=tenant, method=doc.get("method"),
                partners_count=recover.get("partners_count"),
                values=values)
        scenario = self.scenario_builder(doc.get("spec") or {})
        job = self.service.submit(
            scenario, method=doc.get("method") or "Shapley values",
            tenant=tenant, deadline_sec=doc.get("deadline_sec"),
            job_id=job_id, priority=doc.get("priority"),
            credential=credential)
        return {"job": job.job_id, "tenant": job.tenant}

    def _handle_job(self, payload: dict) -> dict:
        job_id = payload["job"]
        job = self.service._jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        return _job_doc(job)
