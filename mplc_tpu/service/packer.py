"""Cross-tenant batch packing bookkeeping.

The engine already buckets a sweep's coalitions into merged slot buckets
(`_slot_buckets` / `_bucket_plan`), and the program bank (PR 8) makes a
bucket's executables a process-global, AOT-compiled resource. With the
bank in its SHARED (shape-scoped) key mode, the same `(slots, width)`
bucket maps to the same banked program *regardless of which tenant's game
a subset came from* — so a second tenant of the same shape compiles
nothing: its batches ride programs the first tenant already banked.

This module is the observation side of that sharing. Device batches
themselves stay single-tenant (one batch closes over ONE game's stacked
data tensor — rows of different tenants can't share a dispatch), so
"packing" means program-level packing: tenant B's bucket lands in tenant
A's compiled slot bucket. `CrossTenantPacker` tracks which tenants each
program key has served and tells the scheduler, per upcoming bucket,
whether its batches are cross-tenant packed — the scheduler counts every
such batch into `service.cross_tenant_packed_batches` (the acceptance
signal that the sharing is real, paired with the bank-hit assertion that
the second tenant compiled nothing new).
"""

from __future__ import annotations

import threading

from ..contrib.bank import ProgramBank


class CrossTenantPacker:
    """Tracks program-key -> tenants served, across every job the service
    has scheduled. The ownership map is lock-guarded: the scheduler's
    worker POOL observes plans from several threads at once."""

    def __init__(self):
        # program key -> set of tenant names whose buckets rode it.
        # Bounded by program diversity (one short hash + tenant names per
        # distinct (shape, slots, width) program — the same space the
        # global bank FIFO-bounds), never by job count.
        self._owners: dict = {}
        self._lock = threading.Lock()

    @staticmethod
    def _keyer(engine) -> ProgramBank:
        """A transient shared-scope keyer: the packer must hold NO
        reference to any engine (a retained engine pins the tenant's
        device arrays for the service lifetime — the scheduler's
        engine-drop on cancel/quarantine relies on this). The one
        expensive piece, the shape digest, is cached ON the engine."""
        # always key in SHARED scope, even when the engine's own bank is
        # disabled or game-scoped: the packing question is "would these
        # buckets share a program", which is a shape question
        k = ProgramBank(engine, shared=True)
        cached = getattr(engine, "_packer_shape_digest", None)
        if cached is not None:
            k._digest_cache = cached
        else:
            engine._packer_shape_digest = k._engine_digest()
        return k

    def observe_plan(self, tenant: str, engine, plan) -> dict:
        """Register a slice's bucket plan (`[(pipe, slot_count, width)]`,
        the engine's `_bucket_plan` order) for `tenant` and return
        `{slot_count: packed}` — packed=True when that bucket's program
        key has already served a DIFFERENT tenant, i.e. every batch the
        engine dispatches for it is cross-tenant packed."""
        keyer = self._keyer(engine)
        packed: dict = {}
        with self._lock:
            for pipe, slot_count, width in plan:
                key = keyer.program_key(pipe, slot_count, width)
                owners = self._owners.setdefault(key, set())
                shared = bool(owners - {tenant})
                # a slice can hold several None-slot buckets (singles +
                # the masked multi path); flag the slot_count packed if
                # ANY of its buckets is shared
                packed[slot_count] = packed.get(slot_count, False) or shared
                owners.add(tenant)
        return packed

    def tenants_for(self, engine, pipe, slot_count, width) -> set:
        """The tenants whose buckets have ridden this program (tests)."""
        key = self._keyer(engine).program_key(pipe, slot_count, width)
        with self._lock:
            return set(self._owners.get(key, ()))
