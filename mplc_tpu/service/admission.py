"""SLO-driven admission control and priority scheduling for the sweep
service.

The bounded queue (PR 9) is a *capacity* control: past
`MPLC_TPU_SERVICE_MAX_PENDING` pending jobs, `submit` raises
`ServiceOverloaded`. It says nothing about *latency*: a service can sit
well under its admission bound and still be drowning — every queued job
waiting minutes for its first quantum because the jobs ahead of it are
huge. This module closes that gap with two cooperating pieces, both
driven by the live queue-wait SLO signal the scheduler already measures:

  **`TierQueue`** — the scheduler's run queue, split into priority tiers
  (`submit(..., priority=)`, default `MPLC_TPU_SERVICE_PRIORITY_DEFAULT`;
  higher integers are more important). Tiers are served by deterministic
  stride scheduling: tier `t` carries weight `t + 1`, so a tier-2 job
  receives three scheduling quanta for every tier-0 quantum while BOTH
  keep making progress — weighting, not starvation. Within a tier the
  order stays round-robin FIFO, exactly the PR-9 behavior (a single-tier
  service schedules identically to the old deque).

  **`AdmissionController`** — a three-state overload governor:

      healthy ──p99 over threshold──▶ deferring ──still over──▶ shedding
         ▲                                                         │
         └───────────────p99 back under threshold──────────────────┘

  The signal is the queue-wait p99 over a sliding window of recent
  observed waits PLUS the live ages of everything still queued (so a
  wedged queue registers even when nothing is being scheduled and no new
  samples arrive). Past `MPLC_TPU_SERVICE_SHED_P99_SEC` (0 / unset =
  governor off) the controller first *defers*: the scheduler skips the
  lowest priority tier while any higher tier has work (strict priority
  under pressure; a no-op when only one tier is queued — deferral must
  never deadlock a uniform-priority service). If the p99 is still over
  the threshold at the next evaluation, it escalates to *shedding*: the
  newest never-started jobs of the lowest queued tier are terminated
  with a classified `JobShed` (journaled, counted in
  `service.jobs_shed` — separate from rejected/cancelled/quarantined,
  and never silent) until the queue is back to half the admission bound.
  Shed errors and `ServiceOverloaded` both carry a `retry_after_sec`
  hint — the windowed queue-wait p50, floored at
  `MPLC_TPU_SERVICE_RETRY_FLOOR_SEC` (default 0.05; without the floor a
  no-history hint of 0.0 tells a retrying client to hammer immediately)
  — so callers back off for roughly one queue's worth of time instead
  of hammering `submit` in a tight loop.

  The controller is deliberately *windowed*, not cumulative: the SLO
  histograms (obs/metrics.py) never forget, so a single overload spike
  would otherwise latch p99 above threshold forever and the service
  would shed until restart. The window is bounded BOTH by count (the
  most recent 256 waits) and by AGE (samples older than
  `max(10 x threshold, 30 s)` are pruned at evaluation time): a
  post-spike idle service stops shedding once the spike ages out, even
  if nothing new is ever scheduled to displace the stale samples. Two
  further anti-latch rules: escalation from deferring to shedding
  requires the p99 to still be over threshold after a DWELL of
  `0.1 x threshold` seconds (deferral gets wall-clock time to relieve
  the queue before jobs are destroyed, not just one scheduling
  decision), and the shed quota is zero whenever the queue is already
  at or below half the admission bound (a near-empty queue has nothing
  worth shedding — the next job runs, lands a fresh wait sample, and
  the window recovers). De-escalation back to `healthy` happens the
  moment the windowed p99 drops under the threshold.

All methods are caller-synchronized: the scheduler invokes them under
its own lock (one logical admission decision per scheduling quantum),
so neither class carries a lock of its own.
"""

from __future__ import annotations

import math
import time
from collections import deque

from .. import constants


def nearest_rank(samples, q: float) -> "float | None":
    """Exact nearest-rank quantile of a sample list (None when empty) —
    the same rule the report's slo row uses, so the controller and the
    offline quantiles can't disagree about what "p99" means."""
    if not samples:
        return None
    s = sorted(samples)
    return s[min(max(math.ceil(q * len(s)), 1), len(s)) - 1]


class TierQueue:
    """Priority-tiered run queue with stride scheduling across tiers.

    `push` files a job under its integer `priority` tier; `pop` serves
    tiers proportionally to weight `tier + 1` via stride scheduling
    (each tier holds a monotone "pass" value advanced by `1 / weight`
    per quantum served; the smallest pass runs next, ties to the higher
    tier) and round-robin FIFO within a tier. With `defer_lowest=True`
    the lowest nonempty tier is skipped — unless it is the ONLY
    nonempty tier, so deferral degrades to a no-op rather than a
    deadlock. `shed_candidates` returns the lowest tier's never-started
    jobs, newest first — the cheapest work to throw away."""

    def __init__(self):
        self._tiers: dict = {}   # tier -> deque of jobs
        self._pass: dict = {}    # tier -> stride pass value

    def __len__(self) -> int:
        return sum(len(d) for d in self._tiers.values())

    def jobs(self) -> list:
        """Every queued job (scheduling order not implied)."""
        return [j for d in self._tiers.values() for j in d]

    def tiers(self) -> list:
        """Sorted nonempty tier numbers."""
        return sorted(t for t, d in self._tiers.items() if d)

    def push(self, job) -> None:
        tier = int(getattr(job, "priority", 0))
        d = self._tiers.get(tier)
        if d is None:
            d = self._tiers[tier] = deque()
            # a tier joining mid-run starts at the current minimum pass:
            # it neither owes quanta for the time it was empty nor jumps
            # the tiers that were already waiting
            live = [self._pass[t] for t, q in self._tiers.items() if q]
            self._pass[tier] = min(live) if live else 0.0
        elif not d:
            live = [self._pass[t] for t, q in self._tiers.items()
                    if q and t != tier]
            if live:
                self._pass[tier] = max(self._pass[tier], min(live))
        d.append(job)

    def pop(self, defer_lowest: bool = False):
        """Next job to run, or None when empty (or everything eligible
        is deferred away — impossible by construction, see above)."""
        live = self.tiers()
        if not live:
            return None
        if defer_lowest and len(live) > 1:
            live = live[1:]
        tier = min(live, key=lambda t: (self._pass[t], -t))
        self._pass[tier] += 1.0 / (tier + 1)
        return self._tiers[tier].popleft()

    def shed_candidates(self, limit: int) -> list:
        """Up to `limit` never-started jobs from the lowest nonempty
        tier, NEWEST submission first (they have waited least — shedding
        them throws away the least invested patience), removed from the
        queue. Jobs that already ran a quantum are never shed: their
        harvested values represent paid-for device work."""
        victims = []
        live = self.tiers()
        if not live or limit <= 0:
            return victims
        d = self._tiers[live[0]]
        keep = deque()
        # walk from the newest end; keep relative order of survivors
        for job in reversed(d):
            if len(victims) < limit and job.first_quantum_at is None:
                victims.append(job)
            else:
                keep.appendleft(job)
        self._tiers[live[0]] = keep
        return victims


class AdmissionController:
    """The overload governor (module docstring). `shed_p99_sec <= 0`
    disables it: state stays `healthy` and nothing is ever deferred or
    shed; `retry_after_sec()` still serves the backoff hint."""

    HEALTHY = "healthy"
    DEFERRING = "deferring"
    SHEDDING = "shedding"

    # window samples older than max(_AGE_FACTOR x threshold, _AGE_MIN_SEC)
    # are pruned at read time: a post-spike idle service must recover
    # even when nothing new is scheduled to displace the stale waits
    _AGE_FACTOR = 10.0
    _AGE_MIN_SEC = 30.0

    def __init__(self, shed_p99_sec: float = 0.0, window: int = 256,
                 defer_dwell_sec: "float | None" = None):
        self.shed_p99_sec = float(shed_p99_sec)
        # escalation dwell: deferring must have been in force this long
        # (wall-clock, not decision count — under a worker pool two
        # scheduling decisions can be microseconds apart) before the
        # governor starts destroying jobs
        self.defer_dwell_sec = (float(defer_dwell_sec)
                                if defer_dwell_sec is not None
                                else 0.1 * self.shed_p99_sec)
        # floor under the retry hint: a fresh (or long-idle) service has
        # no queue-wait history, and a 0.0 hint is an instruction to
        # retry in a tight loop — resolved once at construction so a
        # governor's contract can't drift mid-run
        self.retry_floor_sec = constants._env_nonneg_float(
            constants.SERVICE_RETRY_FLOOR_ENV, 0.05)
        self._waits: deque = deque(maxlen=window)  # (monotonic ts, wait)
        self.state = self.HEALTHY
        self.shed_total = 0
        self.rejected_total = 0
        self._last_p99: "float | None" = None
        self._deferring_since: "float | None" = None

    @property
    def enabled(self) -> bool:
        return self.shed_p99_sec > 0.0

    # -- signal feeds ----------------------------------------------------

    def observe_queue_wait(self, sec: float) -> None:
        """One job's measured submit -> first-quantum wait."""
        self._waits.append((time.monotonic(), float(sec)))

    def note_reject(self) -> None:
        self.rejected_total += 1

    def note_shed(self, n: int = 1) -> None:
        self.shed_total += n

    # -- the decision ----------------------------------------------------

    def _recent_waits(self, now: "float | None" = None) -> list:
        now = time.monotonic() if now is None else now
        horizon = max(self._AGE_FACTOR * self.shed_p99_sec,
                      self._AGE_MIN_SEC)
        while self._waits and now - self._waits[0][0] > horizon:
            self._waits.popleft()
        return [w for _, w in self._waits]

    def _p99(self, queued_ages) -> "float | None":
        return nearest_rank(self._recent_waits() + list(queued_ages), 0.99)

    def evaluate(self, queued_ages) -> str:
        """Advance the governor one decision step and return the state.
        `queued_ages` are the current waiting times of still-queued jobs
        (their queue wait is AT LEAST that much), so a queue nothing is
        draining pushes p99 up without waiting for samples."""
        now = time.monotonic()
        p99 = self._last_p99 = self._p99(queued_ages)
        if (not self.enabled or p99 is None
                or p99 <= self.shed_p99_sec):
            self.state = self.HEALTHY
            self._deferring_since = None
        elif self.state == self.HEALTHY:
            self.state = self.DEFERRING
            self._deferring_since = now
        elif self.state == self.DEFERRING:
            # escalate only once deferral has had `defer_dwell_sec` of
            # wall-clock to relieve the p99 — never on the literal next
            # scheduling decision
            if now - (self._deferring_since or now) >= self.defer_dwell_sec:
                self.state = self.SHEDDING
        return self.state

    def shed_quota(self, queued: int, max_pending: int) -> int:
        """How many queued jobs to shed right now: enough to bring the
        queue back to half the admission bound. Zero when the queue is
        already at or below that target — shedding exists to cut a
        BACKLOG; a near-empty queue under a stale-window breach must
        run its jobs (and land fresh wait samples), not destroy them."""
        if self.state != self.SHEDDING:
            return 0
        return max(queued - max(max_pending // 2, 1), 0)

    def retry_after_sec(self) -> float:
        """The backoff hint carried by `ServiceOverloaded` and `JobShed`:
        the windowed queue-wait p50 — roughly one queue's worth of
        patience — floored at `retry_floor_sec` (a no-history hint of
        0.0 would tell a retrying client to hammer immediately)."""
        p50 = nearest_rank(self._recent_waits(), 0.50)
        return max(float(p50) if p50 is not None else 0.0,
                   self.retry_floor_sec)

    # -- observability ---------------------------------------------------

    def view(self, queued_ages=()) -> dict:
        """The /healthz `admission` block: current state, the live p99
        vs the threshold, and shed/reject accounting — overload made
        visible BEFORE it becomes a 503."""
        return {
            "state": self.state if self.enabled else self.HEALTHY,
            "enabled": self.enabled,
            "queue_wait_p99_sec": self._p99(queued_ages),
            "shed_threshold_sec": (self.shed_p99_sec
                                   if self.enabled else None),
            "shed_total": self.shed_total,
            "rejected_total": self.rejected_total,
            "retry_after_sec": self.retry_after_sec(),
            "window_samples": len(self._waits),
        }
