"""Write-ahead journal for the sweep service: crash recovery as replay.

The engine's cache autosave (PR 4) makes ONE engine's sweep resumable;
the service needs the same durability for a whole multi-tenant process —
every accepted submission and every harvested `(tenant, subset, value)`
must survive a hard kill so a restarted service completes every in-flight
sweep bit-identically to an uninterrupted run.

Format: JSONL, one record per line:

    {"sha256": "<hex>", "rec": {...}}

where the checksum covers the canonical serialization of `rec`
(`json.dumps(rec, sort_keys=True)`) — the same corruption-is-detectable
discipline as the engine's `save_cache`. Appends are flushed and fsync'd
before `append` returns: a record the service acted on (a value it
streamed to a tenant, a submission it acknowledged) is durable by the
time anyone can observe the action.

Replay distinguishes two failure shapes:

  - a TORN TAIL — the final line fails to parse or checksum, the
    signature of a kill mid-append. The bad bytes are quarantined to
    `<path>.torn`, the journal is truncated back to the last good
    record, and replay succeeds with everything before the tear (a
    re-run of the torn record's batch is bit-identical, so nothing is
    lost but one batch of work);
  - MID-FILE corruption — a bad line with good records after it cannot
    be a torn append; something rewrote history. That raises
    `JournalCorruptError`: recovery code must quarantine the whole file
    (or refuse to trust it), never silently skip interior records.

Float values round-trip exactly through `json` (repr-based float
serialization), so replayed v(S) tables are bit-identical to the
harvested ones — the property the service's recovery invariant rests on.

Terminal records (`done` / `cancel` / `quarantine` / `shed`) carry the
job's metered `device_seconds` (+ `tenant`, `device_basis` —
obs/devcost.py): replay restores the per-tenant billing meter, so a
kill→restart continues `service.device_seconds{tenant=...}` where the
killed process stopped instead of zeroing every tenant's bill.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import warnings

logger = logging.getLogger("mplc_tpu")


class JournalCorruptError(ValueError):
    """A journal record BEFORE the tail failed to parse or checksum —
    not a torn append but rewritten/corrupted history. Distinct from the
    torn-tail case, which replay quarantines and survives."""


def _checksum(rec: dict) -> str:
    return hashlib.sha256(
        json.dumps(rec, sort_keys=True).encode()).hexdigest()


class SweepJournal:
    """Append-only, checksummed, fsync'd journal. Appends are serialized
    by an internal lock: the service's worker POOL journals harvested
    values from several threads at once, and two interleaved writes to
    one append handle would tear both records."""

    def __init__(self, path):
        self.path = str(path)
        self._fh = None
        self._lock = threading.Lock()

    def _handle(self):
        if self._fh is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "ab")
        return self._fh

    def append(self, rec: dict) -> None:
        """Durably append one record: the line is flushed and fsync'd
        before this returns, so anything the caller does AFTER (stream a
        value, acknowledge a submission) is guaranteed replayable."""
        self.append_many([rec])

    def append_many(self, recs) -> None:
        """One durability point for a whole batch of records: every line
        is written, then ONE flush+fsync. Crash semantics are identical
        to per-record appends — replay already tolerates a torn tail, and
        losing a partially-written batch loses exactly the work a
        per-record kill at the same instant would — at 1/N the fsync
        cost, which matters because the scheduler journals every
        harvested coalition of a batch at once."""
        if not recs:
            return
        with self._lock:
            fh = self._handle()
            for rec in recs:
                fh.write(json.dumps(
                    {"sha256": _checksum(rec), "rec": rec}).encode() + b"\n")
            fh.flush()
            os.fsync(fh.fileno())

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- recovery --------------------------------------------------------

    @classmethod
    def replay(cls, path) -> tuple[list, bool]:
        """`(records, tail_torn)` for an existing journal file.

        Every good record's `rec` dict is returned in append order. A bad
        FINAL line (parse failure or checksum mismatch — a torn append
        from a mid-write kill) is quarantined to `<path>.torn`, the
        journal is truncated back to the last good record, `tail_torn` is
        True and a warning names the quarantine file. A bad line with
        good records after it raises `JournalCorruptError`. A missing
        file replays as an empty journal."""
        path = str(path)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return [], False

        records = []
        good_end = 0  # byte offset just past the last good line
        offset = 0
        bad_at = None  # (byte offset, reason) of the first bad line
        for line in raw.split(b"\n"):
            line_end = offset + len(line) + 1  # +1 for the split "\n"
            if line.strip():
                reason = None
                try:
                    doc = json.loads(line)
                    rec = doc["rec"]
                    if _checksum(rec) != doc.get("sha256"):
                        reason = "checksum mismatch"
                except (ValueError, KeyError, TypeError) as e:
                    reason = f"unparseable record ({e})"
                if reason is not None:
                    if bad_at is None:
                        bad_at = (offset, reason)
                else:
                    if bad_at is not None:
                        # a good record AFTER a bad one: not a torn
                        # append — history itself is corrupt. Dump the
                        # flight recorder first: the postmortem captures
                        # what the process was doing when it found its
                        # own history rewritten
                        from ..obs import flight as obs_flight
                        postmortem = obs_flight.dump(
                            "journal_corrupt",
                            extra={"journal": path, "offset": bad_at[0],
                                   "reason": bad_at[1]})
                        raise JournalCorruptError(
                            f"journal {path} has a corrupt record at byte "
                            f"{bad_at[0]} ({bad_at[1]}) followed by valid "
                            "records — this is not a torn tail; refusing "
                            "to replay selectively"
                            + (f" (postmortem flight record: {postmortem})"
                               if postmortem else ""))
                    records.append(rec)
                    good_end = min(line_end, len(raw))
            offset = line_end

        if bad_at is None:
            return records, False

        # torn tail: quarantine the bad bytes, truncate back to the last
        # good record, and carry on — one interrupted append must never
        # cost the journal's whole history
        torn = raw[bad_at[0]:]
        torn_path = path + ".torn"
        with open(torn_path, "wb") as f:
            f.write(torn)
            f.flush()
            os.fsync(f.fileno())
        with open(path, "r+b") as f:
            f.truncate(good_end)
            f.flush()
            os.fsync(f.fileno())
        from ..obs import metrics as obs_metrics
        obs_metrics.counter("service.journal_torn_records").inc()
        warnings.warn(
            f"sweep journal {path} ended in a torn record "
            f"({bad_at[1]}; the kill landed mid-append) — {len(torn)} "
            f"bytes quarantined to {torn_path}, journal truncated to the "
            f"last good record ({len(records)} records replayed)",
            stacklevel=2)
        return records, True
