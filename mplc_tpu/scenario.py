"""Scenario: the L6 orchestrator.

Same parameter surface, validation and `run()` sequence as the reference
`Scenario` (/root/reference/mplc/scenario.py:28-879): dataset selection,
partner instantiation, basic/advanced data split, batch-size derivation,
label corruption, the full-coalition MPL training, then the configured
contributivity methods; results exported via `to_dataframe()` with the same
column schema.

Deliberate fixes over the reference (SURVEY.md §7 "quirks"):
  - the kwargs whitelist accepts `aggregation_weighting` (the actual kwarg;
    the reference whitelists the nonexistent `aggregation`),
  - aggregator names accept both `data-volume` and `data_volume` spellings
    (the reference's docs/config and registry disagree),
  - `amounts_per_partner` sum check uses a tolerance instead of float
    equality,
  - `to_dataframe` uses `pd.concat` (pandas >= 2 removed `DataFrame.append`).

New TPU-native parameters: `seed` (end-to-end determinism) and
`compute_dtype` ("float32" | "bfloat16" for MXU-friendly training).
"""

from __future__ import annotations

import datetime
import logging
import uuid
from pathlib import Path

import numpy as np
import pandas as pd

from . import constants
from .contrib.contributivity import Contributivity
from .data import datasets as dataset_module
from .data.partition import compute_batch_sizes, split_advanced, split_basic
from .data.partner import Partner
from .mpl.approaches import MULTI_PARTNER_LEARNING_APPROACHES
from .ops.aggregation import AGGREGATOR_NAMES

logger = logging.getLogger("mplc_tpu")

_AGGREGATION_ALIASES = {
    "uniform": "uniform",
    "data-volume": "data-volume",
    "data_volume": "data-volume",
    "local-score": "local-score",
    "local_score": "local-score",
}

_PARAMS_KNOWN = [
    "dataset", "dataset_name", "dataset_proportion",
    "methods", "multi_partner_learning_approach", "aggregation",
    "aggregation_weighting",
    "partners_count", "amounts_per_partner", "corrupted_datasets",
    "samples_split_option",
    "gradient_updates_per_pass_count", "epoch_count", "minibatch_count",
    "is_early_stopping",
    "init_model_from", "is_quick_demo",
    "seed", "compute_dtype", "contributivity_cache_from",
    "partner_shards",
]


class Scenario:
    def __init__(self,
                 partners_count,
                 amounts_per_partner,
                 dataset=None,
                 dataset_name=constants.MNIST,
                 dataset_proportion=1,
                 samples_split_option=None,
                 corrupted_datasets=None,
                 init_model_from="random_initialization",
                 multi_partner_learning_approach="fedavg",
                 aggregation_weighting=None,
                 gradient_updates_per_pass_count=constants.DEFAULT_GRADIENT_UPDATES_PER_PASS_COUNT,
                 minibatch_count=constants.DEFAULT_BATCH_COUNT,
                 epoch_count=constants.DEFAULT_EPOCH_COUNT,
                 is_early_stopping=True,
                 methods=None,
                 is_quick_demo=False,
                 experiment_path=Path("./experiments"),
                 scenario_id=1,
                 repeats_count=1,
                 is_dry_run=False,
                 seed=42,
                 compute_dtype="float32",
                 contributivity_cache_from=None,
                 partner_shards=None,
                 **kwargs):
        unrecognised = [k for k in kwargs if k not in _PARAMS_KNOWN]
        if unrecognised:
            raise Exception(
                f"Unrecognised parameters {unrecognised}, check your configuration")

        # `aggregation` is an accepted alias for `aggregation_weighting`.
        # The reference whitelists `aggregation` but never reads it
        # (scenario.py kwargs list), so a config written with it silently
        # ran with the default weighting — here it takes effect, and a
        # conflicting pair is an error instead of a silent pick.
        aggregation_alias = kwargs.get("aggregation")
        if aggregation_alias is not None:
            if aggregation_weighting is not None and \
                    _AGGREGATION_ALIASES.get(aggregation_weighting) != \
                    _AGGREGATION_ALIASES.get(aggregation_alias):
                raise ValueError(
                    f"Conflicting aggregation settings: aggregation="
                    f"{aggregation_alias!r} vs aggregation_weighting="
                    f"{aggregation_weighting!r}; set only one")
            aggregation_weighting = aggregation_alias
        if aggregation_weighting is None:
            aggregation_weighting = "data-volume"

        # -- dataset ----------------------------------------------------
        if isinstance(dataset, dataset_module.Dataset):
            self.dataset = dataset
        else:
            self.dataset = dataset_module.load_dataset(dataset_name)
            logger.debug(f"Dataset selected: {dataset_name}")

        self.dataset_proportion = dataset_proportion
        assert self.dataset_proportion > 0, \
            "Error in the config file, dataset_proportion should be > 0"
        assert self.dataset_proportion <= 1, \
            "Error in the config file, dataset_proportion should be <= 1"
        if self.dataset_proportion < 1:
            self.dataset.shorten_dataset_proportion(self.dataset_proportion)

        self.nb_samples_used = len(self.dataset.x_train)
        self.final_relative_nb_samples = []

        # -- partners ---------------------------------------------------
        self.partners_list: list[Partner] = []
        self.partners_count = partners_count
        self.amounts_per_partner = amounts_per_partner
        if samples_split_option is not None:
            self.samples_split_type, self.samples_split_description = samples_split_option
        else:
            self.samples_split_type, self.samples_split_description = "basic", "random"
        if corrupted_datasets is not None:
            self.corrupted_datasets = corrupted_datasets
        else:
            self.corrupted_datasets = ["not_corrupted"] * self.partners_count
        # Validate the corruption specs AT CONSTRUCTION against the
        # vocabulary (data/partner.py CORRUPTION_KINDS): the reference —
        # and this framework until now — let unknown names flow through to
        # a debug log at corruption time, so a typo'd spec silently ran an
        # UNCORRUPTED partner through a robustness experiment.
        from .data.partner import CORRUPTION_KINDS
        if len(self.corrupted_datasets) != self.partners_count:
            raise ValueError(
                f"corrupted_datasets has {len(self.corrupted_datasets)} "
                f"entries for {self.partners_count} partners — one spec "
                "per partner")
        for idx, spec in enumerate(self.corrupted_datasets):
            kind = spec[0] if isinstance(spec, (list, tuple)) else spec
            if kind not in CORRUPTION_KINDS:
                raise ValueError(
                    f"corrupted_datasets[{idx}] = {kind!r} is not a valid "
                    "corruption; valid names: "
                    f"{', '.join(CORRUPTION_KINDS)}")

        # -- learning approach ------------------------------------------
        self.mpl = None
        self._charac_engine = None
        try:
            self.multi_partner_learning_approach = \
                MULTI_PARTNER_LEARNING_APPROACHES[multi_partner_learning_approach]
            self.multi_partner_learning_approach_key = multi_partner_learning_approach
        except KeyError:
            raise KeyError(
                f"Multi-partner learning approach '{multi_partner_learning_approach}' "
                f"is not a valid approach. List of supported approaches: "
                f"{', '.join(MULTI_PARTNER_LEARNING_APPROACHES)}")

        try:
            self.aggregation_name = _AGGREGATION_ALIASES[aggregation_weighting]
        except KeyError:
            raise ValueError(
                f"aggregation approach '{aggregation_weighting}' is not a valid "
                f"approach. Supported: {AGGREGATOR_NAMES}")
        self.aggregation = self.aggregation_name  # reference stores a class here

        # -- computation parameters -------------------------------------
        self.epoch_count = epoch_count
        assert self.epoch_count > 0, "epoch_count should be > 0"
        self.minibatch_count = minibatch_count
        assert self.minibatch_count > 0, "minibatch_count should be > 0"
        self.gradient_updates_per_pass_count = gradient_updates_per_pass_count
        assert self.gradient_updates_per_pass_count > 0, \
            "gradient_updates_per_pass_count should be > 0"
        self.is_early_stopping = is_early_stopping

        self.init_model_from = init_model_from
        self.use_saved_weights = init_model_from != "random_initialization"

        self.seed = seed
        self.compute_dtype = compute_dtype
        # resumable Shapley sweeps: path to a coalition cache saved by a
        # previous run of the same scenario shape (SURVEY.md §5 rebuild note)
        self.contributivity_cache_from = contributivity_cache_from
        # 2-D [coal x part] engine mode: shard the partner dimension over
        # this many devices inside each coalition training (1/None = 1-D
        # coalition-only sharding). MPLC_TPU_PARTNER_SHARDS overrides.
        self.partner_shards = 1 if partner_shards is None else int(partner_shards)
        if self.partner_shards < 1:
            raise ValueError(f"partner_shards must be >= 1, got {partner_shards}")
        # set by the CharacteristicEngine once it picks its execution mode
        # (exact / pow2 slot bucketing, or the masked path)
        self.slot_bucketing = None
        # set by data_corruption(): lets the engine warn when a partner
        # fault plan carries data-plane (noisy/glabel) entries but the
        # corruption step never ran (direct-engine callers)
        self._data_faults_applied = False

        # -- contributivity methods -------------------------------------
        self.contributivity_list: list[Contributivity] = []
        self.methods = []
        if methods is not None:
            for method in methods:
                if method in constants.CONTRIBUTIVITY_METHODS:
                    self.methods.append(method)
                else:
                    raise Exception(
                        f"Contributivity method '{method}' is not in methods list.")

        # -- misc -------------------------------------------------------
        self.scenario_id = scenario_id
        self.n_repeat = repeats_count
        self.is_quick_demo = is_quick_demo
        if self.is_quick_demo and self.dataset_proportion < 1:
            raise Exception("Don't start a quick_demo without the full dataset")
        if self.is_quick_demo:
            logger.info("Quick demo: limit number of data and number of epochs.")
            rng = np.random.RandomState(seed)
            if len(self.dataset.x_train) > constants.TRAIN_SET_MAX_SIZE_QUICK_DEMO:
                idx_tr = rng.choice(len(self.dataset.x_train),
                                    constants.TRAIN_SET_MAX_SIZE_QUICK_DEMO, replace=False)
                idx_v = rng.choice(len(self.dataset.x_val),
                                   min(constants.VAL_SET_MAX_SIZE_QUICK_DEMO,
                                       len(self.dataset.x_val)), replace=False)
                idx_te = rng.choice(len(self.dataset.x_test),
                                    min(constants.TEST_SET_MAX_SIZE_QUICK_DEMO,
                                        len(self.dataset.x_test)), replace=False)
                self.dataset.x_train = self.dataset.x_train[idx_tr]
                self.dataset.y_train = self.dataset.y_train[idx_tr]
                self.dataset.x_val = self.dataset.x_val[idx_v]
                self.dataset.y_val = self.dataset.y_val[idx_v]
                self.dataset.x_test = self.dataset.x_test[idx_te]
                self.dataset.y_test = self.dataset.y_test[idx_te]
            self.epoch_count = 3
            self.minibatch_count = 2

        now_str = datetime.datetime.now().strftime("%Y-%m-%d_%Hh%M")
        self.scenario_name = (f"scenario_{self.scenario_id}_repeat_{self.n_repeat}"
                              f"_{now_str}_{uuid.uuid4().hex[:3]}")
        self.short_scenario_name = f"{self.partners_count} {self.amounts_per_partner}"
        self.save_folder = Path(experiment_path) / self.scenario_name
        self.is_dry_run = is_dry_run
        if not is_dry_run:
            self.save_folder.mkdir(parents=True, exist_ok=True)
            logger.info("### Description of data scenario configured:")
            logger.info(f"   Number of partners defined: {self.partners_count}")
            logger.info(f"   Data distribution scenario chosen: {self.samples_split_description}")
            logger.info(f"   Multi-partner learning approach: {self.multi_partner_learning_approach_key}")
            logger.info(f"   Weighting option: {self.aggregation_name}")
            logger.info(f"   Dataset: {self.dataset.name} ({self.dataset.provenance}); "
                        f"{len(self.dataset.x_train)} train / "
                        f"{len(self.dataset.x_val)} val / "
                        f"{len(self.dataset.x_test)} test samples")

    # ------------------------------------------------------------------

    def instantiate_scenario_partners(self):
        if self.partners_list:
            raise Exception("self.partners_list should be []")
        self.partners_list = [Partner(i, seed=self.seed * 1000 + i)
                              for i in range(self.partners_count)]

    def split_data(self, is_logging_enabled=True):
        split_basic(self.dataset, self.partners_list, self.amounts_per_partner,
                    self.samples_split_description, self.minibatch_count)
        self.nb_samples_used = sum(len(p.x_train) for p in self.partners_list)
        self.final_relative_nb_samples = [
            p.final_nb_samples / self.nb_samples_used for p in self.partners_list]
        if is_logging_enabled:
            logger.info("### Splitting data among partners: basic split done.")
        return 0

    def split_data_advanced(self, is_logging_enabled=True):
        self.nb_samples_used, self.final_relative_nb_samples = split_advanced(
            self.dataset, self.partners_list, self.amounts_per_partner,
            self.samples_split_description, self.minibatch_count)
        if is_logging_enabled:
            logger.info("### Splitting data among partners: advanced split done.")
        return 0

    def compute_batch_sizes(self):
        compute_batch_sizes(self.partners_list, self.minibatch_count,
                            self.gradient_updates_per_pass_count,
                            constants.MAX_BATCH_SIZE)

    def data_corruption(self):
        """Reference scenario.py:726-786 dispatch, extended with the
        feature-noise ('noisy', parameter = sigma) and global-label-flip
        ('glabel', parameter = fraction) families, plus the data-plane
        entries of the partner fault plan (MPLC_TPU_PARTNER_FAULT_PLAN
        noisy/glabel entries — same seeded operators, env-driven)."""
        for partner_index, partner in enumerate(self.partners_list):
            spec = self.corrupted_datasets[partner_index]
            if isinstance(spec, (list, tuple)):
                kind, proportion = spec[0], spec[1]
            else:
                kind, proportion = spec, 1.0
            if kind == "corrupted":
                partner.corrupt_labels(proportion)
            elif kind == "shuffled":
                partner.shuffle_labels(proportion)
            elif kind == "permuted":
                partner.permute_labels(proportion)
            elif kind == "random":
                partner.random_labels(proportion)
            elif kind == "noisy":
                # the spec parameter is the noise sigma, not a proportion
                partner.noisy_features(0.1 if not isinstance(spec, (list, tuple))
                                       else proportion)
            elif kind == "glabel":
                partner.flip_to_global_label(proportion)
            elif kind == "not_corrupted":
                pass
            else:  # unreachable: validated at construction
                raise ValueError(f"unknown corruption {kind!r}")
        # partner-fault-plan data faults ride the same seeded operators —
        # a plan can corrupt a partner without editing the scenario config.
        # The parsed plan is stashed on the scenario so the engine's
        # fingerprint/trainer faults derive from the SAME parse that
        # corrupted the data (one env read per run, one clip warning).
        from . import faults
        plan = faults.clip_partner_plan(faults.partner_fault_plan_from_env(),
                                        self.partners_count)
        self._partner_fault_plan = plan
        for pid, specs in faults.data_fault_specs(plan).items():
            for kind, value in specs:
                if kind == "noisy":
                    self.partners_list[pid].noisy_features(value)
                else:
                    self.partners_list[pid].flip_to_global_label(value)
        self._data_faults_applied = True

    def plot_data_distribution(self):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from sklearn.preprocessing import LabelEncoder

        lb = LabelEncoder().fit([str(y) for y in self.dataset.y_train])
        for i, partner in enumerate(self.partners_list):
            plt.subplot(self.partners_count, 1, i + 1)
            data_count = np.bincount(lb.transform([str(y) for y in partner.y_train]))
            while len(data_count) < self.dataset.num_classes:
                data_count = np.append(data_count, 0)
            plt.bar(np.arange(0, self.dataset.num_classes), data_count)
            plt.ylabel("partner " + str(partner.id))
        plt.suptitle("Data distribution")
        plt.xlabel("Classes")
        graphs = self.save_folder / "graphs"
        graphs.mkdir(parents=True, exist_ok=True)
        plt.savefig(graphs / "data_distribution.png")
        plt.close()

    def append_contributivity(self, contributivity):
        self.contributivity_list.append(contributivity)

    # ------------------------------------------------------------------

    def run(self):
        self.instantiate_scenario_partners()
        if self.samples_split_type == "basic":
            self.split_data()
        elif self.samples_split_type == "advanced":
            self.split_data_advanced()
        if not self.is_dry_run:
            self.plot_data_distribution()
        self.compute_batch_sizes()
        self.data_corruption()

        self.mpl = self.multi_partner_learning_approach(self, is_save_data=True)
        self.mpl.fit()

        for method in self.methods:
            logger.info(f"{method}")
            contrib = Contributivity(scenario=self)
            if self.contributivity_cache_from and \
                    not self._charac_engine.first_charac_fct_calls_count:
                self._resume_coalition_cache()
            if not self.is_dry_run:
                # incremental checkpointing: every trained device batch is
                # durable immediately, so a crash mid-sweep resumes cheaply
                self._charac_engine.autosave_path = \
                    self.save_folder / "coalition_cache.json"
            contrib.compute_contributivity(method)
            self.append_contributivity(contrib)
            logger.info(f"## Evaluating contributivity with {method}: {contrib}")
        if self.methods and self._charac_engine is not None and not self.is_dry_run:
            self._charac_engine.save_cache(self.save_folder / "coalition_cache.json")
        return 0

    def _resume_coalition_cache(self):
        """Resume hardening: a corrupt or truncated autosave (power loss
        during the final write of a killed run, interrupted copy) is
        QUARANTINED — renamed to `<name>.corrupt`, warned about, and the
        sweep starts cold — instead of crashing `run()` before any
        compute. A fingerprint mismatch still raises: that cache is valid
        but describes a different game, and silently recomputing would
        mask a configuration error."""
        from .contrib.engine import CacheIntegrityError

        path = Path(self.contributivity_cache_from)
        try:
            self._charac_engine.load_cache(path)
        except CacheIntegrityError as e:
            quarantine = path.with_name(path.name + ".corrupt")
            try:
                path.replace(quarantine)
                where = f"quarantined to {quarantine}"
            except OSError as rename_err:
                where = f"left in place (quarantine rename failed: {rename_err})"
            logger.warning(
                f"coalition cache {path} is unusable ({e}); {where}; "
                f"starting the sweep cold")
            return
        logger.info(f"Resumed coalition cache from {path} "
                    f"({len(self._charac_engine.charac_fct_values)} entries)")

    # ------------------------------------------------------------------

    def to_dataframe(self) -> pd.DataFrame:
        """Same row/column schema as the reference (scenario.py:788-843)."""
        rows = []
        base = {
            "scenario_name": self.scenario_name,
            "short_scenario_name": self.short_scenario_name,
            "dataset_name": self.dataset.name,
            "train_data_samples_count": len(self.dataset.x_train),
            "test_data_samples_count": len(self.dataset.x_test),
            "partners_count": self.partners_count,
            "dataset_fraction_per_partner": str(self.amounts_per_partner),
            "samples_split_description": str(self.samples_split_description),
            "nb_samples_used": self.nb_samples_used,
            "final_relative_nb_samples": str(self.final_relative_nb_samples),
            "multi_partner_learning_approach": self.multi_partner_learning_approach_key,
            "aggregation": self.aggregation_name,
            "partner_shards": self.partner_shards,
            "slot_bucketing": self.slot_bucketing,
            "epoch_count": self.epoch_count,
            "minibatch_count": self.minibatch_count,
            "gradient_updates_per_pass_count": self.gradient_updates_per_pass_count,
            "is_early_stopping": self.is_early_stopping,
            "mpl_test_score": self.mpl.history.score if self.mpl else None,
            "mpl_nb_epochs_done": self.mpl.history.nb_epochs_done if self.mpl else None,
            "learning_computation_time_sec":
                self.mpl.learning_computation_time if self.mpl else None,
        }
        if not self.contributivity_list:
            rows.append(dict(base))
        for contrib in self.contributivity_list:
            extra = {
                "contributivity_method": contrib.name,
                "contributivity_scores": str(list(contrib.contributivity_scores)),
                "contributivity_stds": str(list(contrib.scores_std)),
                "computation_time_sec": contrib.computation_time_sec,
                "first_characteristic_calls_count": contrib.first_charac_fct_calls_count,
            }
            for i in range(self.partners_count):
                row = dict(base)
                row.update(extra)
                row["partner_id"] = i
                row["dataset_fraction_of_partner"] = self.amounts_per_partner[i]
                row["contributivity_score"] = contrib.contributivity_scores[i]
                row["contributivity_std"] = contrib.scores_std[i]
                rows.append(row)
        return pd.DataFrame(rows)
