"""Host-side multi-partner learning classes: the reference L4 API surface.

`MULTI_PARTNER_LEARNING_APPROACHES` keeps the reference registry keys
(/root/reference/mplc/multi_partner_learning.py:521-527) and each class keeps
the `Cls(scenario, **kwargs).fit()` contract with the same kwargs whitelist
(:21-30). The classes are thin: all training happens in the compiled
`MplTrainer` (mplc_tpu/mpl/engine.py); `fit()` is the epoch-chunk driver
plus History/book-keeping.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp

from .. import constants
from ..data.partition import StackedPartners, stack_eval_set
from ..obs import trace as obs_trace
from .engine import EvalSet, MplTrainer, TrainConfig
from .history import History

ALLOWED_PARAMETERS = ("partners_list",
                      "epoch_count",
                      "minibatch_count",
                      "dataset",
                      "aggregation_method",
                      "is_early_stopping",
                      "is_save_data",
                      "save_folder",
                      "init_model_from",
                      "use_saved_weights")


def _eval_chunk_size(n: int) -> int:
    return int(min(constants.EVAL_CHUNK_SIZE, max(128, 1 << (max(n - 1, 1)).bit_length())))


def save_params_npz(path: Path, params) -> None:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    np.savez(path, treedef=np.array(str(treedef)),
             **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})


def load_params_npz(path, like_params):
    with np.load(str(path), allow_pickle=True) as f:
        leaves = [jnp.asarray(f[f"leaf_{i}"]) for i in range(len(f.files) - 1)]
    treedef = jax.tree_util.tree_structure(like_params)
    return jax.tree_util.tree_unflatten(treedef, leaves)


class MultiPartnerLearning:
    """Base class: owns data staging, the compiled trainer and `fit()`."""

    approach_key = "fedavg"

    def __init__(self, scenario, **kwargs):
        self.dataset = scenario.dataset
        self.partners_list = scenario.partners_list
        self.init_model_from = scenario.init_model_from
        self.use_saved_weights = scenario.use_saved_weights

        self.epoch_count = scenario.epoch_count
        self.minibatch_count = scenario.minibatch_count
        self.gradient_updates_per_pass_count = scenario.gradient_updates_per_pass_count
        self.is_early_stopping = scenario.is_early_stopping
        self.aggregation_method = scenario.aggregation_name

        self.is_save_data = False
        self.save_folder = getattr(scenario, "save_folder", None)
        self.compute_dtype = getattr(scenario, "compute_dtype", "float32")
        self.seed = getattr(scenario, "seed", 0)

        self.__dict__.update((k, v) for k, v in kwargs.items() if k in ALLOWED_PARAMETERS)

        self.partners_list = sorted(self.partners_list, key=lambda p: p.id)
        self.val_data = (self.dataset.x_val, self.dataset.y_val)
        self.test_data = (self.dataset.x_test, self.dataset.y_test)
        self.dataset_name = self.dataset.name
        self.model = self.dataset.model

        self.epoch_index = 0
        self.minibatch_index = 0
        self.learning_computation_time = 0.0

        self.cfg = TrainConfig(
            approach=self.approach_key,
            aggregator=self.aggregation_method,
            epoch_count=self.epoch_count,
            minibatch_count=self.minibatch_count,
            gradient_updates_per_pass=self.gradient_updates_per_pass_count,
            is_early_stopping=self.is_early_stopping,
            compute_dtype=self.compute_dtype,
        )
        self.trainer = MplTrainer.get(self.model, self.cfg)
        self.history = History([p.id for p in self.partners_list],
                               self.epoch_count, self.minibatch_count,
                               save_folder=self.save_folder)
        self.model_params = None
        self._state = None

    @property
    def partners_count(self) -> int:
        return len(self.partners_list)

    # -- data staging ---------------------------------------------------

    def _stage(self):
        label_dim = self.model.label_dim()
        stacked = StackedPartners.build(self.partners_list, label_dim)
        val = EvalSet(*stack_eval_set(self.val_data[0], self.val_data[1], label_dim,
                                      _eval_chunk_size(len(self.val_data[0]))))
        test = EvalSet(*stack_eval_set(self.test_data[0], self.test_data[1], label_dim,
                                       _eval_chunk_size(len(self.test_data[0]))))
        return stacked, val, test

    def _init_params(self, rng):
        if self.use_saved_weights:
            template = self.model.init(rng)
            return load_params_npz(self.init_model_from, template)
        return None

    # -- the fit driver -------------------------------------------------

    def fit(self):
        # the fit span is the timer: learning_computation_time is its
        # duration, and the span lands in the telemetry trace/report
        with obs_trace.span("mpl.fit", approach=self.approach_key,
                            partners=self.partners_count,
                            epochs=self.epoch_count) as sp:
            stacked, val, test = self._stage()
            rng = jax.random.PRNGKey(self.seed)
            state = self.trainer.init_state(rng, self.partners_count,
                                            init_params=self._init_params(rng))
            coal_mask = jnp.ones((self.partners_count,), jnp.float32)

            chunk = self.cfg.patience if self.cfg.is_early_stopping else self.cfg.epoch_count
            chunk = max(1, min(chunk, self.cfg.epoch_count))
            run = self.trainer.jit_epoch_chunk
            epochs_left = self.cfg.epoch_count
            while epochs_left > 0:
                n = min(chunk, epochs_left)
                state = run(state, stacked, val, coal_mask, rng, n_epochs=n)
                epochs_left -= n
                if bool(jax.device_get(state.done)):
                    break

            test_loss, test_acc = self.trainer.jit_finalize(state, test)
            self._state = state
            self.model_params = state.params
            self.epoch_index = int(jax.device_get(state.epoch))
            self.history.fill_from_state(
                [p.id for p in self.partners_list],
                state.val_loss_h, state.val_acc_h, state.partner_h,
                int(jax.device_get(state.nb_epochs_done)), float(test_acc))
            if self.approach_key == "lflip" and state.theta.size:
                # Real per-epoch snapshots from the device-side [E, P, K, K]
                # history; epochs never run (early stop) stay None, matching the
                # reference's pre-filled list (multi_partner_learning.py:442).
                theta_h = np.asarray(state.theta_h)
                done = int(jax.device_get(state.nb_epochs_done))
                self.history.theta = [
                    [theta_h[e, i] for i in range(self.partners_count)]
                    if e < done else [None] * self.partners_count
                    for e in range(self.epoch_count)]
            if self.is_save_data:
                self.save_final_model()
                self.history.save_data()
        self.learning_computation_time = sp.duration
        return self.history.score

    # -- misc reference-API methods -------------------------------------

    def save_final_model(self):
        if self.save_folder is None or self.model_params is None:
            return
        model_folder = Path(self.save_folder) / "model"
        model_folder.mkdir(parents=True, exist_ok=True)
        save_params_npz(model_folder / f"{self.dataset_name}_final_weights.npz",
                        self.model_params)

    def eval_and_log_final_model__test_perf(self):
        return self.history.score


class FederatedAverageLearning(MultiPartnerLearning):
    approach_key = "fedavg"

    def __init__(self, scenario, **kwargs):
        super().__init__(scenario, **kwargs)
        if self.partners_count == 1:
            raise ValueError("Only one partner is provided. Please use the "
                             "dedicated SinglePartnerLearning class")


class SequentialLearning(MultiPartnerLearning):
    approach_key = "seq-pure"

    def __init__(self, scenario, **kwargs):
        super().__init__(scenario, **kwargs)
        if self.partners_count == 1:
            raise ValueError("Only one partner is provided. Please use the "
                             "dedicated SinglePartnerLearning class")


class SequentialWithFinalAggLearning(SequentialLearning):
    approach_key = "seq-with-final-agg"


class SequentialAverageLearning(SequentialLearning):
    approach_key = "seqavg"


class MplLabelFlip(MultiPartnerLearning):
    approach_key = "lflip"

    def __init__(self, scenario, epsilon: float = 0.01, **kwargs):
        super().__init__(scenario, **kwargs)
        if self.model.loss_kind != "categorical":
            raise ValueError("LFlip requires a categorical model")
        self.epsilon = epsilon
        import dataclasses
        self.cfg = dataclasses.replace(self.cfg, lflip_epsilon=epsilon)
        self.trainer = MplTrainer.get(self.model, self.cfg)


class SinglePartnerLearning(MultiPartnerLearning):
    """Class-path analogue of the engine's sliced-singles rule
    (contrib/engine.py `_run_singles_sliced`): `partners_list` is pinned to
    `[partner]` BEFORE staging, so `_stage` builds a [1, n_own, ...] tensor
    — this partner's rows only, never the scenario's full stacked axis
    padded to the largest partner (locked by
    tests/test_mpl.py::test_single_partner_class_stages_only_its_partner)."""

    approach_key = "single"

    def __init__(self, scenario, partner=None, **kwargs):
        if partner is not None:
            if isinstance(partner, (list, np.ndarray)):
                raise ValueError("More than one partner is provided")
            kwargs["partners_list"] = [partner]
        super().__init__(scenario, **kwargs)
        if self.partners_count != 1:
            raise ValueError("SinglePartnerLearning requires exactly one partner")
        self.partner = self.partners_list[0]


MULTI_PARTNER_LEARNING_APPROACHES = {
    "fedavg": FederatedAverageLearning,
    "seq-pure": SequentialLearning,
    "seq-with-final-agg": SequentialWithFinalAggLearning,
    "seqavg": SequentialAverageLearning,
    "lflip": MplLabelFlip,
}
