from .engine import MplTrainer, TrainConfig, TrainState, EvalSet, APPROACH_NAMES
from .history import History
from .approaches import (MULTI_PARTNER_LEARNING_APPROACHES, MultiPartnerLearning,
                         FederatedAverageLearning, SequentialLearning,
                         SequentialWithFinalAggLearning, SequentialAverageLearning,
                         MplLabelFlip, SinglePartnerLearning, save_params_npz,
                         load_params_npz)

__all__ = [
    "MplTrainer", "TrainConfig", "TrainState", "EvalSet", "APPROACH_NAMES",
    "History", "MULTI_PARTNER_LEARNING_APPROACHES", "MultiPartnerLearning",
    "FederatedAverageLearning", "SequentialLearning",
    "SequentialWithFinalAggLearning", "SequentialAverageLearning",
    "MplLabelFlip", "SinglePartnerLearning", "save_params_npz", "load_params_npz",
]
