"""Training history: per-partner and global [epoch, minibatch] metric matrices.

API-compatible with the reference `History` (/root/reference/mplc/
mpl_utils.py:11-79): `history[partner_id][metric]` and
`history['mpl_model']` matrices, `score`, `nb_epochs_done`,
`partners_to_dataframe()`, `save_data()`. The difference is provenance: the
matrices are computed on-device inside the compiled training program and
fetched once at the end, instead of being filled by Python per minibatch.

The reference's `save_data` indexes a column that is out of bounds for the
current matrix shape and is never invoked (mpl_utils.py:55-71, SURVEY.md §5);
here it is implemented correctly (plots the end-of-epoch column).
"""

from __future__ import annotations

import os
import pickle
from pathlib import Path

import numpy as np
import pandas as pd

METRICS = ["val_accuracy", "val_loss", "loss", "accuracy"]
# row order of TrainState.partner_h
_PARTNER_H_ROWS = {"loss": 0, "accuracy": 1, "val_loss": 2, "val_accuracy": 3}


class History:
    def __init__(self, partner_ids, epoch_count: int, minibatch_count: int,
                 save_folder=None):
        self.save_folder = Path(save_folder) if save_folder else None
        self.nb_epochs_done = 0
        self.score = None
        self.metrics = list(METRICS)
        nanmat = lambda: np.full((epoch_count, minibatch_count), np.nan)  # noqa: E731
        self.history = {pid: {m: nanmat() for m in self.metrics} for pid in partner_ids}
        self.history["mpl_model"] = {"val_accuracy": np.zeros((epoch_count, minibatch_count)),
                                     "val_loss": np.zeros((epoch_count, minibatch_count))}
        self.theta = None          # lflip: [epoch][partner] K x K matrices
        self.theta_ = None

    def fill_from_state(self, partner_ids, val_loss_h, val_acc_h, partner_h,
                        nb_epochs_done: int, score: float):
        """Ingest the device-side history arrays of a finished TrainState."""
        self.history["mpl_model"]["val_loss"] = np.nan_to_num(np.asarray(val_loss_h))
        self.history["mpl_model"]["val_accuracy"] = np.nan_to_num(np.asarray(val_acc_h))
        ph = np.asarray(partner_h)
        for i, pid in enumerate(partner_ids):
            for metric, row in _PARTNER_H_ROWS.items():
                self.history[pid][metric] = ph[row, i]
        self.nb_epochs_done = int(nb_epochs_done)
        self.score = float(score)

    def partners_to_dataframe(self) -> pd.DataFrame:
        temp = {"Partner": [], "Epoch": [], "Minibatch": []}
        for m in self.metrics:
            temp[m] = []
        for pid, hist in self.history.items():
            if pid == "mpl_model":
                continue
            epoch_count, minibatch_count = self.history["mpl_model"]["val_loss"].shape
            for e in range(epoch_count):
                for mb in range(minibatch_count):
                    temp["Partner"].append(pid)
                    temp["Epoch"].append(e)
                    temp["Minibatch"].append(mb)
                    for metric, matrix in hist.items():
                        temp[metric].append(matrix[e, mb])
        return pd.DataFrame.from_dict(temp)

    def save_data(self):
        if self.save_folder is None:
            return
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        with open(self.save_folder / "history_data.p", "wb") as f:
            pickle.dump(self.history, f)

        graphs = self.save_folder / "graphs"
        os.makedirs(graphs, exist_ok=True)
        e_done = max(self.nb_epochs_done, 1)
        last_mb = self.history["mpl_model"]["val_loss"].shape[1] - 1

        plt.figure()
        plt.plot(self.history["mpl_model"]["val_loss"][:e_done, last_mb])
        plt.ylabel("Loss")
        plt.xlabel("Epoch")
        plt.savefig(graphs / "federated_training_loss.png")
        plt.close()

        plt.figure()
        plt.plot(self.history["mpl_model"]["val_accuracy"][:e_done, last_mb])
        plt.ylabel("Accuracy")
        plt.xlabel("Epoch")
        plt.ylim([0, 1])
        plt.savefig(graphs / "federated_training_acc.png")
        plt.close()

        plt.figure()
        for key, value in self.history.items():
            plt.plot(value["val_accuracy"][:e_done, last_mb],
                     label=(f"partner {key}" if key != "mpl_model" else key))
        plt.title("Model accuracy")
        plt.ylabel("Accuracy")
        plt.xlabel("Epoch")
        plt.legend()
        plt.ylim([0, 1])
        plt.savefig(graphs / "all_partners.png")
        plt.close()
