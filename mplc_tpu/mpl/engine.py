"""The multi-partner training engine: one compiled, coalition-maskable trainer.

This replaces the reference's L4 layer (/root/reference/mplc/
multi_partner_learning.py) — where "multi-partner training" is a Python
for-loop of serialized Keras `.fit()` calls — with a single functional
program designed for XLA:

  - Partners are a stacked leading axis: per-partner local training is a
    `vmap` (fedavg/lflip) or a `lax.scan` over a permuted order (seq-*).
  - A coalition is a length-P 0/1 mask. The mask multiplies every per-sample
    loss mask (so inactive partners produce exactly-zero gradients and
    therefore exactly-zero optimizer updates) and gates the aggregation
    weight vector. Because of this, the WHOLE trainer is vmappable over a
    batch of coalition masks — the key to evaluating 2^N Shapley coalitions
    in parallel (SURVEY.md §2.2).
  - Training runs in *epoch chunks*: a jitted `lax.scan` over up to
    `patience` epochs, driven by a tiny host loop that stops as soon as
    every coalition in the batch has early-stopped. This keeps data-dependent
    stopping out of the compiled graph while wasting at most one chunk of
    extra epochs.
  - All data selection is static-shape: each partner's epoch permutation
    lives in a padded [P, Nmax] index array; minibatch i / gradient-step g
    slices are `dynamic_slice`s with validity masks, reproducing the
    reference's minibatch semantics (partner.py:155-167) without ragged
    shapes.

Reference loop semantics reproduced deliberately:
  - A fresh optimizer per partner-pass (the reference builds and compiles a
    new Keras model every `fit_minibatch`, multi_partner_learning.py:319).
  - Global-model validation is logged at the *start* of every minibatch
    (multi_partner_learning.py:314).
  - Early stopping compares val_loss at [e, col] vs [e-PATIENCE, col] where
    col is 0 for fedavg-family and MB-1 for seq-family — the reference's
    minibatch_index reset quirk (multi_partner_learning.py:299 vs seq).
  - `single` (1-partner) training keeps a persistent optimizer across epochs
    and uses Keras-style "no improvement for PATIENCE epochs" early stopping
    (multi_partner_learning.py:247-260).

Known deviations (documented in DESIGN_NOTES.md): minibatch remainders
(n_p mod minibatch_count samples per epoch) are dropped to keep shapes
static; the reference's np.split keeps them.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax import lax

from .. import constants
from ..models.core import Model
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..ops.aggregation import (aggregate, aggregation_weights, broadcast,
                               fusion_fence)
from ..ops.metrics import masked_loss_and_metrics

APPROACH_NAMES = ("fedavg", "seq-pure", "seq-with-final-agg", "seqavg", "lflip", "single")


def buffer_donation_enabled() -> bool:
    """MPLC_TPU_DONATE_BUFFERS (default on): the trainer's state-carrying
    jits donate their TrainState argument, so each epoch chunk's output
    state reuses the input state's buffers instead of coexisting with them
    — roughly half the param-side HBM per in-flight batch. Donation is an
    aliasing contract, never a numerics change: donated and non-donated
    runs are bit-identical (tests/test_donation.py). Read at
    jit-construction time and keyed into the per-trainer jit cache, so
    toggling the env between engine constructions takes effect.

    Callers holding a donated state MUST treat it as consumed: rebind
    (`state = run(state, ...)`) and copy any leaf needed afterwards BEFORE
    the donating call (the engine copies `nb_epochs_done` ahead of the
    donating finalize; contrib/reconstruct.py copies the init params ahead
    of the recording loop). On a failed dispatch the donated buffers are
    dead — every retry path re-materializes its inputs from host arrays
    before re-dispatching (contrib/engine.py dispatch closures)."""
    import os
    return os.environ.get(constants.DONATE_BUFFERS_ENV, "1") != "0"


class _CompileTimedFn:
    """Transparent wrapper around a jitted callable that records compile
    events: when a call grows the jit's executable cache (a new program
    shape — e.g. a new `n_epochs` static arg), the call's wall-clock is
    attributed to compilation (`trainer.compile` trace event +
    compile_seconds metrics). Dispatch is async under jit, so the first
    call's time is dominated by trace+compile; steady-state calls see two
    `perf_counter` reads and one int compare of overhead. Attribute access
    (`.lower()`, `._cache_size()`, ...) passes through to the wrapped jit."""

    __slots__ = ("_fn", "_label")

    def __init__(self, fn, label: str):
        self._fn = fn
        self._label = label

    def __call__(self, *args, **kwargs):
        try:
            before = self._fn._cache_size()
        except Exception:
            return self._fn(*args, **kwargs)
        import time as _time
        t0 = _time.perf_counter()
        out = self._fn(*args, **kwargs)
        try:
            grew = self._fn._cache_size() > before
        except Exception:
            grew = False
        if grew:
            dt = _time.perf_counter() - t0
            obs_trace.event("trainer.compile", dur=dt, fn=self._label)
            obs_metrics.counter("trainer.compiles_total").inc()
            obs_metrics.counter("trainer.compile_seconds_total").inc(dt)
            obs_metrics.counter(f"trainer.compiles[{self._label}]").inc()
            obs_metrics.counter(f"trainer.compile_seconds[{self._label}]").inc(dt)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    approach: str = "fedavg"
    aggregator: str = "uniform"
    epoch_count: int = constants.DEFAULT_EPOCH_COUNT
    minibatch_count: int = constants.DEFAULT_BATCH_COUNT
    gradient_updates_per_pass: int = constants.DEFAULT_GRADIENT_UPDATES_PER_PASS_COUNT
    is_early_stopping: bool = True
    patience: int = constants.PATIENCE
    compute_dtype: str = "float32"
    record_partner_val: bool = True
    # Record the global val loss/acc at the start of EVERY minibatch
    # (reference multi_partner_learning.py:314). Early stopping only reads
    # one column per epoch (0 for fedavg-family, MB-1 for seq-family), so
    # coalition sweeps turn this off and pay one val pass per epoch — or
    # zero when early stopping is off too — instead of `minibatch_count`.
    record_val_history: bool = True
    lflip_epsilon: float = 0.01
    # Name of the mesh axis the partner dimension is sharded over (shard_map);
    # None = all partners resident on each device. Only the vmap-parallel
    # approaches (fedavg/lflip) can shard partners; seq-* visit partners
    # serially and `single` reduces to one partner.
    partner_axis: str | None = None
    # Slot execution (coalition sweeps): instead of running all P partners
    # with inactive ones masked to zero — wasting |P|-|S| partners' worth of
    # compute per coalition — run exactly `slot_count` slots, each bound at
    # runtime to a partner index. The coalition argument becomes an int32
    # id array [slot_count] (pad with -1) instead of a float mask [P].
    # fedavg and the seq family; RNG streams are keyed by partner id (and,
    # for seq visit order, by the full-width order-key draw), so slotted
    # and masked runs train identically.
    slot_count: int | None = None
    # Fused wide-step mode: fold `step_width_mult` consecutive
    # gradient_updates_per_pass sub-batches into one wider SGD step inside
    # _partner_pass. 1 (default, from MPLC_TPU_STEP_WIDTH_MULT) = exact
    # parity with the historical per-sub-batch stepping; >1 is a documented
    # deviation (ceil(gup/mult) optimizer updates per pass instead of gup).
    step_width_mult: int = constants.STEP_WIDTH_MULT
    # Partner-level fault model (MPLC_TPU_PARTNER_FAULT_PLAN, parsed by the
    # engine into these per-partner tuples; None = fault-free, and the
    # compiled programs are byte-identical to the pre-fault build):
    #   partner_drop_epochs[p]      1-based epoch at which partner p drops
    #       out FOREVER (0 = never). Its slot is masked to exactly-zero
    #       gradients from that epoch on and its aggregation weight is
    #       zeroed, so FedAvg renormalizes over the survivors — a partner
    #       dropped from epoch 1 trains bit-identically to a run that
    #       excluded it outright (equality-tested).
    #   partner_straggler_delays[p] staleness in aggregation rounds: the
    #       partner's per-round local pass starts from the global params of
    #       `delay` rounds ago (a rolling buffer of the last max(delay)
    #       post-aggregation params rides the TrainState), and its late
    #       result still joins the CURRENT round's aggregation.
    # Both are static config — they shape the compiled program and the
    # trainer-registry key, exactly like slot_count.
    partner_drop_epochs: tuple | None = None
    partner_straggler_delays: tuple | None = None
    # Update-recording mode (retrain-free contributivity,
    # contrib/reconstruct.py): capture every aggregation round's
    # per-partner parameter delta (local params - round-start global
    # params) and the normalized aggregation weight vector actually used,
    # stacked as device arrays on the TrainState (`upd_h` [R, P, ...]
    # leaves, `w_h` [R, P]; R = epoch_count x minibatch_count rounds).
    # Because inactive/dropped partners produce exactly-zero optimizer
    # updates and zero aggregation weight, their recorded rows are exact
    # zeros — the fault model composes for free. fedavg masked path only
    # (the recording run is ONE grand-coalition training, where slot
    # execution has nothing to save); off by default, and the off build
    # is byte-identical to the pre-recording trainer.
    record_updates: bool = False
    # Deterministic-reduction mode (MPLC_TPU_DETERMINISTIC_REDUCE,
    # obs/numerics.py): every aggregation reduces its weighted per-partner
    # terms by a strict left-to-right fold in GLOBAL partner order
    # (ops/aggregation.py `ordered_fold`) instead of the order-sensitive
    # `sum`/`psum` pair — under partner-axis sharding the terms are
    # all-gathered over `part` first, so the 2-D [coal x part] path is
    # BIT-IDENTICAL to the unsharded reference. Off (the default) keeps
    # the historical reduction and is byte-identical to the pre-knob
    # build. None = resolve from the env at construction time (the
    # resolved value is part of the frozen config, the trainer-registry
    # key and the engine cache fingerprint).
    deterministic_reduce: bool | None = None
    # Mixed-precision mode (MPLC_TPU_PRECISION, constants.precision_mode):
    #   fp32   (default) byte-identical compiled programs to the pre-knob
    #          build — compute_dtype alone decides the model dtype, as it
    #          always has.
    #   mixed  model compute (fwd/bwd matmuls, activations) in bf16 with
    #          fp32 master params, optimizer state and FedAvg aggregation
    #          (models/zoo.py casts params INSIDE apply, so the carried
    #          state never leaves fp32); the recorded update stream and
    #          the reconstruction scan stay fp32.
    #   bf16   `mixed` plus a bf16 reconstruction accumulate: the
    #          retrain-free batch-eval path casts the recorded deltas and
    #          the init params to bf16 at scan entry
    #          (contrib/reconstruct.py), trading reconstruction ulps for
    #          bandwidth.
    # Like STEP_WIDTH_MULT, non-fp32 modes are documented deviations:
    # v(S) changes, so the mode is part of the trainer-registry key and
    # the engine cache fingerprint, and every non-fp32 bench run must
    # carry an fp32 reference ledger pair (ulp histogram + tau-b) in its
    # sidecar. None = resolve from the env at construction time.
    precision: str | None = None

    def __post_init__(self):
        if self.deterministic_reduce is None:
            object.__setattr__(self, "deterministic_reduce",
                               constants.deterministic_reduce_enabled())
        if self.precision is None:
            object.__setattr__(self, "precision", constants.precision_mode())
        if self.precision not in constants.PRECISION_MODES:
            raise ValueError(
                f"precision must be one of {constants.PRECISION_MODES}, "
                f"got {self.precision!r}")
        if self.approach not in APPROACH_NAMES:
            raise KeyError(
                f"Multi-partner learning approach '{self.approach}' is not a valid "
                f"approach. List of supported approaches: {', '.join(APPROACH_NAMES)}")
        if self.partner_axis is not None and self.approach not in ("fedavg", "lflip"):
            raise ValueError(
                f"partner-axis sharding requires a partner-parallel approach "
                f"(fedavg/lflip), got '{self.approach}'")
        if self.step_width_mult < 1:
            raise ValueError(
                f"step_width_mult must be >= 1, got {self.step_width_mult}")
        if self.partner_drop_epochs is not None or \
                self.partner_straggler_delays is not None:
            if self.approach not in ("fedavg", "single"):
                raise ValueError(
                    "partner-level dropout/straggler faults support fedavg "
                    "coalition training (and the single-partner trainer) "
                    f"only, got '{self.approach}'")
            if self.partner_axis is not None:
                raise ValueError("partner-level faults and partner-axis "
                                 "sharding are mutually exclusive")
        if self.slot_count is not None:
            if self.approach not in ("fedavg", "seq-pure",
                                     "seq-with-final-agg", "seqavg"):
                raise ValueError("slot execution supports fedavg and the "
                                 "seq family only")
            if self.partner_axis is not None:
                raise ValueError("slot execution and partner-axis sharding "
                                 "are mutually exclusive")
        if self.record_updates:
            if self.approach != "fedavg":
                raise ValueError(
                    "update recording (record_updates) captures FedAvg "
                    "aggregation-round deltas; it supports the fedavg "
                    f"approach only, got '{self.approach}'")
            if self.slot_count is not None:
                raise ValueError("update recording runs the masked fedavg "
                                 "path; slot execution is not supported")
            if self.partner_axis is not None:
                raise ValueError(
                    "update recording is not supported with partner-axis "
                    "sharding (the 2-D coalition x data mode): the "
                    "recorded [rounds, partners, ...] update stack needs "
                    "the whole partner axis resident per device")

    @property
    def dtype(self):
        if self.precision in ("mixed", "bf16"):
            return jnp.bfloat16
        return jnp.bfloat16 if self.compute_dtype == "bfloat16" else jnp.float32


class TrainState(NamedTuple):
    """Carried across epoch chunks. Every leaf is per-coalition when the
    trainer is vmapped (leading batch axis added by vmap)."""
    params: Any              # global model params pytree
    opt_state: Any           # persistent optimizer state ('single' only; else empty)
    theta: jax.Array         # [P, K, K] label-flip matrices (lflip only; else [0])
    theta_h: jax.Array       # [E, P, K, K] end-of-epoch theta (lflip only; else [0])
                             # (reference history.theta, multi_partner_learning.py:482-484)
    epoch: jax.Array         # i32 scalar: next epoch index
    done: jax.Array          # bool scalar: early-stopped
    nb_epochs_done: jax.Array  # i32 scalar
    best_val_loss: jax.Array   # f32 scalar ('single' ES)
    es_wait: jax.Array         # i32 scalar ('single' ES)
    val_loss_h: jax.Array    # [E, MB] global val loss history
    val_acc_h: jax.Array     # [E, MB]
    partner_h: jax.Array     # [4, P, E, MB]: loss, acc, val_loss, val_acc
    stale: Any = ()          # [D, ...] rolling buffer of the last D post-
                             # aggregation global params (straggler faults
                             # only; () when no partner straggles)
    upd_h: Any = ()          # [R, P, ...] per-round per-partner parameter
                             # deltas (record_updates only; else ())
    w_h: Any = ()            # [R, P] per-round normalized aggregation
                             # weights (record_updates only; else ())


class EvalSet(NamedTuple):
    x: jax.Array   # [n_chunks, chunk, ...]
    y: jax.Array   # [n_chunks, chunk, L]
    mask: jax.Array  # [n_chunks, chunk]


def tree_where(cond, a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(jnp.reshape(cond, (1,) * x.ndim), x, y), a, b)


class MplTrainer:
    """Compiled trainer for one (model, config, data-shape) combination.

    Methods are pure and vmap/shard_map-friendly; `init_state` and
    `epoch_chunk` are the primitives, `finalize` evaluates the test score.
    Host-side orchestration (epoch-chunk loop, coalition batching) lives in
    mplc_tpu.mpl.approaches and mplc_tpu.contrib.engine.
    """

    def __init__(self, model: Model, cfg: TrainConfig):
        self.model = model
        self.cfg = cfg
        self.opt = model.make_optimizer()
        self.label_dim = model.label_dim()
        self._jits: dict = {}

    # ------------------------------------------------------------------
    # shared-instance + jit caches: XLA compilations are keyed on function
    # identity, so every fresh MplTrainer (one per approach object in the
    # reference API) would otherwise recompile identical programs. `get`
    # dedupes trainer instances per (model, cfg); the jit_* properties pin
    # one jitted callable per instance. The registry holds weak references
    # so that trainers (and their compiled executables) from long-finished
    # grid scenarios are reclaimable — live approach objects keep theirs
    # alive through their own strong reference.
    # ------------------------------------------------------------------

    _instances = None  # lazily a WeakValueDictionary

    @classmethod
    def get(cls, model: Model, cfg: TrainConfig) -> "MplTrainer":
        import weakref
        if cls._instances is None:
            cls._instances = weakref.WeakValueDictionary()
        key = (id(model), cfg)
        inst = cls._instances.get(key)
        if inst is None:
            inst = cls(model, cfg)
            cls._instances[key] = inst
        return inst

    @staticmethod
    def _donate_state():
        """donate_argnums for the state-carrying jits below under the
        donation policy: argument 0 is always the TrainState, the only
        state-sized input that is dead after the call at every call site.
        The data/eval-set/mask/rng arguments are NEVER donated — they are
        reused across batches (stacked/val/test live for the whole sweep)
        or across chunk iterations (masks/rngs in the early-stopping
        loop)."""
        return (0,) if buffer_donation_enabled() else ()

    # ------------------------------------------------------------------
    # deterministic-reduce stream hoisting: under the numeric-truth
    # plane's MPLC_TPU_DETERMINISTIC_REDUCE mode, the per-epoch partner
    # permutations and per-partner pass keys are generated by a SEPARATE
    # jitted dispatch and passed into the training program as DATA. The
    # values are identical to the in-program generation (same fold_in
    # formulas), but the numerics audit (obs/numerics.py) localized the
    # residual 2-D drift to exactly this: a program that GENERATES its
    # threefry streams next to a collective compiles the training pass
    # differently per topology, while the same program consuming the
    # streams as inputs is bit-stable (tests/test_numerics.py).
    # ------------------------------------------------------------------

    def _det_hoist_streams(self) -> bool:
        """Stream hoisting applies to the deterministic masked
        fedavg/lflip path with early stopping off — the coalition-sweep
        configuration, where `state.epoch == i` throughout a chunk so the
        per-epoch rng folds are concrete. ES-on deterministic runs keep
        in-program generation (same fold rule; no cross-topology claim)."""
        cfg = self.cfg
        return (bool(cfg.deterministic_reduce)
                and not cfg.is_early_stopping
                and cfg.approach in ("fedavg", "lflip")
                and cfg.slot_count is None)

    def gen_epoch_streams(self, rng: jax.Array, mask_pn, start_epoch,
                          n_epochs: int):
        """([E, P, Nmax] epoch permutations, [E, MB, P, 2] per-partner
        pass keys) for one coalition's chunk — the exact streams
        `_fedavg_epoch` would generate in-program for chunk positions
        0..E-1: the chunk body folds the rng by POSITION i, then
        run_epoch folds by `state.epoch` = start_epoch + i. Carrying
        `start_epoch` (a traced scalar) keeps resumed chunks — e.g.
        PVRL's repeated n_epochs=1 calls on a live state — on the same
        stream rule as the in-program generation, so E one-epoch chunks
        and one E-epoch chunk train identically."""
        P = mask_pn.shape[0]
        perms, keys = [], []
        for i in range(n_epochs):
            re = jax.random.fold_in(jax.random.fold_in(rng, i),
                                    start_epoch + i)
            perms.append(self._epoch_perms(jax.random.fold_in(re, 0),
                                           mask_pn))
            mbs = []
            for mb_i in range(self.cfg.minibatch_count):
                rng_mb = jax.random.fold_in(jax.random.fold_in(re, 1), mb_i)
                mbs.append(jax.vmap(
                    lambda p, r=rng_mb: jax.random.fold_in(r, p))(
                        jnp.arange(P, dtype=jnp.int32)))
            keys.append(jnp.stack(mbs))
        return jnp.stack(perms), jnp.stack(keys)

    def jit_gen_streams(self, rng, n_epochs: int, mask_pn, batched: bool,
                        start_epoch=None):
        """Dispatch the stream generator as its OWN compiled program
        (cached per (n_epochs, batched)); `batched` vmaps over a [B, 2]
        rng batch (and the matching [B] start-epoch vector) for the
        coalition-batched pipelines. `start_epoch` defaults to zero(s) —
        a fresh chunk."""
        key = ("gen_streams", n_epochs, batched)
        if key not in self._jits:
            fn = partial(self.gen_epoch_streams, n_epochs=n_epochs)
            if batched:
                fn = jax.vmap(fn, in_axes=(0, None, 0))
            # no-donation by policy: inputs are the live rng batch and
            # the stacked mask, both reused by the chunk call right after
            self._jits[key] = _CompileTimedFn(
                jax.jit(fn), "gen_streams")
        if start_epoch is None:
            start_epoch = (jnp.zeros((rng.shape[0],), jnp.int32)
                           if batched else jnp.zeros((), jnp.int32))
        return self._jits[key](rng, mask_pn, start_epoch)

    def _epoch_chunk_streams(self, state, stacked, val, coal_mask, rng,
                             streams_all, n_epochs: int):
        return self.epoch_chunk(state, stacked, val, coal_mask, rng,
                                n_epochs, streams_all=streams_all)

    @property
    def jit_epoch_chunk(self):
        don = buffer_donation_enabled()
        key = ("epoch_chunk", don)
        if key not in self._jits:
            if self._det_hoist_streams():
                inner = _CompileTimedFn(jax.jit(
                    self._epoch_chunk_streams,
                    static_argnames=("n_epochs",),
                    donate_argnums=self._donate_state()), "epoch_chunk")

                def hoisted(state, stacked, val, coal_mask, rng, n_epochs):
                    streams = self.jit_gen_streams(
                        rng, n_epochs, stacked.mask, batched=False,
                        start_epoch=state.epoch)
                    return inner(state, stacked, val, coal_mask, rng,
                                 streams, n_epochs=n_epochs)
                self._jits[key] = hoisted
            else:
                self._jits[key] = _CompileTimedFn(jax.jit(
                    self.epoch_chunk, static_argnames=("n_epochs",),
                    donate_argnums=self._donate_state()), "epoch_chunk")
        return self._jits[key]

    @property
    def jit_finalize(self):
        if "finalize" not in self._jits:
            # no-donation by policy: the fit driver (mpl/approaches.py)
            # and the sharding tests read state.params / histories AFTER
            # finalize — the state must survive this call
            self._jits["finalize"] = _CompileTimedFn(
                jax.jit(self.finalize), "finalize")
        return self._jits["finalize"]

    @property
    def jit_evaluate(self):
        if "evaluate" not in self._jits:
            # no-donation by policy: callers (PVRL's reward eval) pass the
            # LIVE carried params, which train on in the next epoch
            self._jits["evaluate"] = _CompileTimedFn(
                jax.jit(self.evaluate), "evaluate")
        return self._jits["evaluate"]

    @property
    def jit_batched_init(self):
        if "binit" not in self._jits:
            # no-donation by policy: the only array input is the per-
            # coalition rng batch, which the caller passes again to the
            # epoch chunk — donating it would kill the training streams
            self._jits["binit"] = _CompileTimedFn(jax.jit(
                jax.vmap(self.init_state, in_axes=(0, None)),
                static_argnums=(1,)), "batched_init")
        return self._jits["binit"]

    @property
    def jit_batched_epoch_chunk(self):
        don = buffer_donation_enabled()
        key = ("brun", don)
        if key not in self._jits:
            if self._det_hoist_streams():
                inner = _CompileTimedFn(jax.jit(
                    jax.vmap(self._epoch_chunk_streams,
                             in_axes=(0, None, None, 0, 0, 0, None)),
                    static_argnames=("n_epochs",),
                    donate_argnums=self._donate_state()),
                    "batched_epoch_chunk")

                def hoisted(states, stacked, val, masks, rngs, n_epochs):
                    streams = self.jit_gen_streams(
                        rngs, n_epochs, stacked.mask, batched=True,
                        start_epoch=states.epoch)
                    return inner(states, stacked, val, masks, rngs,
                                 streams, n_epochs)
                self._jits[key] = hoisted
            else:
                self._jits[key] = _CompileTimedFn(jax.jit(
                    jax.vmap(self.epoch_chunk,
                             in_axes=(0, None, None, 0, 0, None)),
                    static_argnames=("n_epochs",),
                    donate_argnums=self._donate_state()),
                    "batched_epoch_chunk")
        return self._jits[key]

    @property
    def jit_batched_finalize(self):
        don = buffer_donation_enabled()
        key = ("bfin", don)
        if key not in self._jits:
            # donating the batch state into the test eval frees the
            # batch's params + optimizer buffers the moment scoring
            # starts; the engine pipeline copies nb_epochs_done out
            # first (BatchedTrainerPipeline.scores_async)
            self._jits[key] = _CompileTimedFn(
                jax.jit(jax.vmap(self.finalize, in_axes=(0, None)),
                        donate_argnums=self._donate_state()),
                "batched_finalize")
        return self._jits[key]

    # ------------------------------------------------------------------
    # state init
    # ------------------------------------------------------------------

    def init_state(self, rng: jax.Array, partners_count: int,
                   init_params=None) -> TrainState:
        cfg = self.cfg
        params = self.model.init(rng) if init_params is None else init_params
        if cfg.approach == "single":
            opt_state = self.opt.init(params)
        else:
            opt_state = ()
        E, MB = cfg.epoch_count, cfg.minibatch_count
        if cfg.approach == "lflip":
            k = self.model.num_outputs
            eye = jnp.eye(k)
            theta0 = eye * (1 - cfg.lflip_epsilon) + (1 - eye) * (cfg.lflip_epsilon / (k - 1))
            theta = jnp.broadcast_to(theta0, (partners_count, k, k))
            theta_h = jnp.full((E, partners_count, k, k), jnp.nan, jnp.float32)
        else:
            theta = jnp.zeros((0,))
            theta_h = jnp.zeros((0,))
        if cfg.approach == "fedavg" and cfg.partner_straggler_delays and \
                any(cfg.partner_straggler_delays):
            # straggler buffer: the last D post-aggregation global params,
            # seeded with D copies of the init params (a round-r straggler
            # older than the run so far trains from the initial model)
            stale = broadcast(params, max(cfg.partner_straggler_delays))
        else:
            stale = ()
        if cfg.record_updates:
            # one recorded row per aggregation round; rounds the run never
            # reaches (early stopping) stay all-zero, which the
            # reconstruction scan skips via its zero-weight-denominator rule
            R = E * MB
            upd_h = jax.tree_util.tree_map(
                lambda leaf: jnp.zeros((R, partners_count) + leaf.shape,
                                       leaf.dtype), params)
            w_h = jnp.zeros((R, partners_count), jnp.float32)
        else:
            upd_h = w_h = ()
        return TrainState(
            params=params, opt_state=opt_state, theta=theta, theta_h=theta_h,
            epoch=jnp.zeros((), jnp.int32), done=jnp.zeros((), bool),
            nb_epochs_done=jnp.zeros((), jnp.int32),
            best_val_loss=jnp.full((), jnp.inf, jnp.float32),
            es_wait=jnp.zeros((), jnp.int32),
            val_loss_h=jnp.full((E, MB), jnp.nan, jnp.float32),
            val_acc_h=jnp.full((E, MB), jnp.nan, jnp.float32),
            partner_h=jnp.full((4, partners_count, E, MB), jnp.nan, jnp.float32),
            stale=stale, upd_h=upd_h, w_h=w_h,
        )

    # ------------------------------------------------------------------
    # evaluation (chunked scan: bounded memory under vmap)
    # ------------------------------------------------------------------

    def evaluate(self, params, ev: EvalSet) -> tuple[jax.Array, jax.Array]:
        """(mean_loss, accuracy) over a chunked eval set."""
        loss_kind = self.model.loss_kind
        dtype = self.cfg.dtype

        def body(carry, chunk):
            ls, cs, cnt = carry
            cx, cy, cm = chunk
            logits = self.model.apply(params, cx, train=False, compute_dtype=dtype)
            l, a, c = masked_loss_and_metrics(loss_kind, logits, cy, cm)
            return (ls + l * c, cs + a * c, cnt + c), None

        (ls, cs, cnt), _ = lax.scan(body, (0.0, 0.0, 0.0), (ev.x, ev.y, ev.mask))
        denom = jnp.maximum(cnt, 1.0)
        return ls / denom, cs / denom

    def _maybe_val_eval(self, params, val: EvalSet, mb_i, es_col: int):
        """Global val (loss, acc) at the start of minibatch `mb_i`, honoring
        `record_val_history`: when off, only the minibatch column early
        stopping reads (`es_col`) is evaluated — `mb_i` is a scan index,
        unbatched under the coalition vmap, so the `lax.cond` is a real
        branch and the skipped val passes never execute — and when early
        stopping is off too, none are."""
        cfg = self.cfg

        def run():
            vl, va = self.evaluate(params, val)
            return jnp.asarray(vl, jnp.float32), jnp.asarray(va, jnp.float32)

        if cfg.record_val_history:
            return run()
        nan = jnp.full((), jnp.nan, jnp.float32)
        if cfg.is_early_stopping:
            return lax.cond(mb_i == es_col, run, lambda: (nan, nan))
        return nan, nan

    # ------------------------------------------------------------------
    # data selection helpers (all static shapes)
    # ------------------------------------------------------------------

    def _epoch_perms(self, rng, mask_pn, offset=0):
        """Per-partner random permutation of real rows: [P, Nmax] indices with
        all valid rows first, in random order. Keys are derived per GLOBAL
        partner index (`offset` = shard offset under partner sharding) so a
        sharded run shuffles identically to the unsharded one."""
        P = mask_pn.shape[0]

        def one(i, mask_p):
            keys = jax.random.uniform(jax.random.fold_in(rng, i), mask_p.shape) \
                + (1.0 - mask_p) * 1e9
            return jnp.argsort(keys).astype(jnp.int32)

        return jax.vmap(one)(jnp.arange(P, dtype=jnp.int32) + offset, mask_pn)

    def _subbatch(self, perm_p, size_p, mb_i, g, sb_cap):
        """Indices + mask for (fused) gradient step g of minibatch mb_i of
        one partner. With `step_width_mult` = k > 1, step g covers the k
        consecutive base sub-batch windows g*k .. g*k+k-1 as one contiguous
        k-x-wider window (the fused wide-step mode); k = 1 reproduces the
        base per-sub-batch window bit-for-bit (same shapes, same values)."""
        cfg = self.cfg
        mbc, gup = cfg.minibatch_count, cfg.gradient_updates_per_pass
        mult = cfg.step_width_mult
        valid_mb = size_p // mbc                      # samples per minibatch
        sb = (valid_mb + gup - 1) // gup              # samples per base step
        ar = jnp.arange(sb_cap * mult, dtype=jnp.int32)
        local = g * (sb * mult) + ar
        valid = (ar < sb * mult) & (local < valid_mb)
        pos = mb_i * valid_mb + local
        idx = perm_p[jnp.clip(pos, 0, perm_p.shape[0] - 1)]
        return idx, valid.astype(jnp.float32)

    def _minibatch_window(self, perm_p, size_p, mb_i, mb_cap):
        """Indices + mask for the whole minibatch mb_i of one partner."""
        mbc = self.cfg.minibatch_count
        valid_mb = size_p // mbc
        ar = jnp.arange(mb_cap, dtype=jnp.int32)
        valid = ar < valid_mb
        pos = mb_i * valid_mb + ar
        idx = perm_p[jnp.clip(pos, 0, perm_p.shape[0] - 1)]
        return idx, valid.astype(jnp.float32)

    # ------------------------------------------------------------------
    # gradient step
    # ------------------------------------------------------------------

    def _loss_fn(self, params, x, y, m, rng):
        logits = self.model.apply(params, x, train=True, rng=rng,
                                  compute_dtype=self.cfg.dtype)
        loss, acc, cnt = masked_loss_and_metrics(self.model.loss_kind, logits, y, m)
        return loss, (acc, cnt)

    def _sgd_step(self, params, opt_state, x, y, m, rng):
        (loss, (acc, cnt)), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
            params, x, y, m, rng)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, acc, cnt

    # ------------------------------------------------------------------
    # one partner's local pass over its minibatch (fresh optimizer)
    # ------------------------------------------------------------------

    def _partner_pass(self, start_params, x_p, y_p, perm_p, size_p, active_p,
                      mb_i, rng_p, opt_state=None, y_override=None,
                      window_idx=None, row_offset=0, n_max=None):
        """Run the pass's masked SGD steps for one partner on minibatch mb_i:
        `gup` base steps, fused into ceil(gup / step_width_mult) wider steps
        when the wide-step mode is on (mult = 1 is bit-identical).

        If `y_override`/`window_idx` are given (lflip), steps slice rows from
        that pre-gathered minibatch window instead of the raw arrays.
        Slot execution passes the FLAT [P*Nmax, ...] arrays as x_p/y_p with
        `row_offset = partner_id * Nmax` (one fused gather, no per-slot copy)
        and `n_max` = Nmax explicitly.
        Returns (params, opt_state, pass_loss, pass_acc).
        """
        cfg = self.cfg
        mult = cfg.step_width_mult
        if n_max is None:
            n_max = x_p.shape[0]
        mb_cap = max(n_max // cfg.minibatch_count, 1)
        sb_cap = (mb_cap + cfg.gradient_updates_per_pass - 1) // cfg.gradient_updates_per_pass
        n_steps = (cfg.gradient_updates_per_pass + mult - 1) // mult
        fresh = opt_state is None
        if fresh:
            opt_state = self.opt.init(start_params)

        def step(carry, g):
            params, opt_state, sums = carry
            idx, valid = self._subbatch(perm_p, size_p, mb_i, g, sb_cap)
            if y_override is not None:
                # rows within the pre-flipped minibatch window
                mbc, gup = cfg.minibatch_count, cfg.gradient_updates_per_pass
                valid_mb = size_p // mbc
                sb = (valid_mb + gup - 1) // gup
                ar = jnp.arange(sb_cap * mult, dtype=jnp.int32)
                local = jnp.clip(g * (sb * mult) + ar,
                                 0, y_override.shape[0] - 1)
                x = jnp.take(x_p, jnp.take(window_idx, local, axis=0), axis=0)
                y = jnp.take(y_override, local, axis=0)
            else:
                x = jnp.take(x_p, idx + row_offset, axis=0)
                y = jnp.take(y_p, idx + row_offset, axis=0)
            m = valid * active_p
            step_rng = jax.random.fold_in(rng_p, g)
            params, opt_state, loss, acc, cnt = self._sgd_step(
                params, opt_state, x, y, m, step_rng)
            sums = (sums[0] + loss * cnt, sums[1] + acc * cnt, sums[2] + cnt)
            return (params, opt_state, sums), None

        (params, opt_state, sums), _ = lax.scan(
            step, (start_params, opt_state, (0.0, 0.0, 0.0)),
            jnp.arange(n_steps))
        denom = jnp.maximum(sums[2], 1.0)
        return params, opt_state, sums[0] / denom, sums[1] / denom

    # ------------------------------------------------------------------
    # lflip: EM update of theta + label resampling for one partner minibatch
    # ------------------------------------------------------------------

    def _lflip_flip(self, params, theta_p, x_p, y_p, perm_p, size_p, mb_i,
                    mb_cap, rng):
        """Reference MplLabelFlip.fit_minibatch EM scheme
        (multi_partner_learning.py:452-516), vectorized and masked."""
        idx, valid = self._minibatch_window(perm_p, size_p, mb_i, mb_cap)
        x = jnp.take(x_p, idx, axis=0)
        y = jnp.take(y_p, idx, axis=0)                       # [M, K] one-hot
        logits = self.model.apply(params, x, train=False, compute_dtype=self.cfg.dtype)
        preds = jax.nn.softmax(logits, axis=-1)              # [M, K]
        vm = valid[:, None]

        def posterior(theta):
            # theta_[i, :] = preds[i, :] * theta[:, argmax(y_i)], then l1-normalize columns
            t = preds * (y @ theta.T) * vm                   # rows for labels' columns
            col = jnp.maximum(jnp.sum(jnp.abs(t), axis=0, keepdims=True), 1e-12)
            return t / col

        theta_post = posterior(theta_p)
        new_theta = theta_post.T @ y                         # [K, K]
        row = jnp.maximum(jnp.sum(jnp.abs(new_theta), axis=1, keepdims=True), 1e-12)
        new_theta = new_theta / row
        theta_post = posterior(new_theta)

        # Draw flipped labels from each row's categorical distribution.
        cdf = jnp.cumsum(theta_post, axis=1)
        u = jax.random.uniform(rng, (theta_post.shape[0], 1)) * jnp.maximum(
            cdf[:, -1:], 1e-12)
        draw = jnp.argmax(u <= cdf, axis=1)
        y_flip = jax.nn.one_hot(draw, y.shape[1], dtype=jnp.float32)
        return new_theta, y_flip, idx, valid

    # ------------------------------------------------------------------
    # epoch bodies
    # ------------------------------------------------------------------

    def _record_partner(self, partner_h, e, mb_i, metrics):
        """metrics: [4, P] (loss, acc, val_loss, val_acc) for this round."""
        return partner_h.at[:, :, e, mb_i].set(metrics)

    def _det_isolated_vmap(self, fn, args, in_axes):
        """vmap `fn` over the partner/slot axis; under deterministic-reduce
        the batched call is fenced (`fusion_fence`) on every input and
        output edge, so XLA compiles the per-partner pass as the same
        isolated computation in every program that embeds it — the
        unsharded [P] epoch, each shard_map-local [P/shards] epoch, and
        the [K]-slot epoch. Without the fence, cross-boundary fusion
        (e.g. an FMA forming between a surrounding multiply and an
        in-pass dot, or a dot tiled differently against its consumers)
        rounds a few lanes differently per embedding — one root of the
        2-D drift beside the psum order, localized by the reduction
        audit (obs/numerics.py) — and adam's sqrt(v)-normalized updates
        amplify those last-ulp differences chaotically. The other root
        is handled by the callers: the pass's train-loss/acc aux outputs
        are DROPPED under deterministic-reduce (the partner history gets
        NaN), because keeping the loss reductions live alongside the
        backward makes XLA tile the shared forward width-dependently.
        Default mode is byte-identical to the historical plain vmap."""
        if not self.cfg.deterministic_reduce:
            return jax.vmap(fn, in_axes=in_axes)(*args)
        args = fusion_fence(args)
        return fusion_fence(jax.vmap(fn, in_axes=in_axes)(*args))

    # ------------------------------------------------------------------
    # partner-level faults (dropout / straggler) — helpers shared by the
    # masked and slot fedavg epochs and the single trainer. All three are
    # STATIC no-ops when the config carries no fault plan: the compiled
    # programs are byte-identical to the fault-free build.
    # ------------------------------------------------------------------

    @property
    def _partner_faulted(self) -> bool:
        cfg = self.cfg
        return (cfg.partner_drop_epochs is not None
                or cfg.partner_straggler_delays is not None)

    def _drop_active(self, e, P: int) -> jax.Array:
        """[P] 0/1 activity under the dropout plan for (0-based) epoch `e`:
        partner p participates iff it never drops (entry 0) or the 1-based
        epoch e+1 is still before its drop epoch. Exact 1.0/0.0 floats, so
        multiplying an unaffected coalition mask leaves it bit-identical."""
        if self.cfg.partner_drop_epochs is None:
            return jnp.ones((P,), jnp.float32)
        drop = jnp.asarray(self.cfg.partner_drop_epochs, jnp.int32)
        return jnp.where(drop == 0, jnp.float32(1.0),
                         (e + 1 < drop).astype(jnp.float32))

    def _straggler_starts(self, params, stale):
        """[P, ...]-stacked start params for the masked fedavg path:
        partner p's local pass starts from the global params `delay_p`
        aggregation rounds stale (rolling-buffer row delay_p - 1); delay 0
        partners get exact copies of the current params. The per-partner
        delays are static config, so the stack resolves at trace time."""
        delays = self.cfg.partner_straggler_delays

        def leaf(g, st):
            return jnp.stack([g if d == 0 else st[d - 1] for d in delays], 0)

        return jax.tree_util.tree_map(leaf, params, stale)

    def _push_stale(self, stale, params):
        """Advance the straggler buffer one aggregation round: the params
        that were current at the round's START become staleness-1."""
        return jax.tree_util.tree_map(
            lambda st, g: jnp.concatenate([g[None], st[:-1]], axis=0),
            stale, params)

    def _fedavg_epoch(self, state: TrainState, stacked, val: EvalSet,
                      coal_mask, rng, streams=None) -> TrainState:
        cfg = self.cfg
        P = stacked.x.shape[0]
        e = state.epoch
        if cfg.partner_axis is not None:
            shard_offset = jax.lax.axis_index(cfg.partner_axis) * P
        else:
            shard_offset = 0
        if streams is not None:
            # hoisted deterministic streams ([P(, local), Nmax] perms +
            # [MB, P, 2] pass keys), generated by a separate dispatch and
            # entering this program as DATA — under partner sharding the
            # in_specs sliced them to the local partner rows already, so
            # no shard offset applies. The numerics audit localized the
            # residual 2-D drift to in-program generation: a program that
            # derives its threefry streams next to a collective compiles
            # the training pass differently per topology.
            perms, mb_keys = streams
        else:
            mb_keys = None
            perms = self._epoch_perms(jax.random.fold_in(rng, 0),
                                      stacked.mask, offset=shard_offset)
            if cfg.deterministic_reduce:
                # fence the generated permutations (and below, the
                # per-partner pass rngs) — second-best to hoisting, for
                # the ES-on deterministic path that cannot hoist
                perms = fusion_fence(perms)
        lflip = cfg.approach == "lflip"
        n_max = stacked.x.shape[1]
        mb_cap = max(n_max // cfg.minibatch_count, 1)
        # partner-level faults (fedavg only — post_init forbids the rest):
        # the dropout plan zeroes dropped partners' activity for the whole
        # epoch (exact-zero gradients + zero aggregation weight, so FedAvg
        # renormalizes over the survivors), stragglers start their local
        # pass from delay-stale global params via the TrainState buffer.
        faulted = self._partner_faulted
        act_mask = coal_mask * self._drop_active(e, P) if faulted \
            else coal_mask
        stragglers = faulted and bool(cfg.partner_straggler_delays)

        recording = cfg.record_updates

        def mb_body(carry, mb_i):
            # uniform carry: the straggler buffer and the recording stacks
            # are empty pytrees (()) when their mode is off, so the scan
            # structure — and the compiled program — matches the
            # pre-recording build exactly in the off configuration
            params, theta, vl_h, va_h, p_h, stale, upd_h, w_h = carry
            vl, va = self._maybe_val_eval(params, val, mb_i, es_col=0)
            vl_h = vl_h.at[e, mb_i].set(vl)
            va_h = va_h.at[e, mb_i].set(va)

            if mb_keys is not None:
                p_rngs = mb_keys[mb_i]
            else:
                rng_mb = jax.random.fold_in(jax.random.fold_in(rng, 1), mb_i)
                # Per-partner rng keyed by GLOBAL partner index, so a
                # partner-sharded run trains identically to the unsharded
                # one.
                p_rngs = jax.vmap(lambda i: jax.random.fold_in(rng_mb, i))(
                    jnp.arange(P, dtype=jnp.int32) + shard_offset)
                if cfg.deterministic_reduce:
                    p_rngs = fusion_fence(p_rngs)

            # deterministic-reduce: the pass's train-loss/acc aux outputs
            # are dropped (the partner history records NaN for them) —
            # with the loss reductions live next to the backward, XLA
            # tiles the shared forward differently per batch width, and
            # the [P]-wide, [P/shards]-wide and [K]-slot embeddings of
            # the SAME pass round differently (see _det_isolated_vmap)
            det = cfg.deterministic_reduce
            if lflip:
                def one(start, theta_p, x_p, y_p, perm_p, size_p, act, r):
                    new_theta, y_flip, w_idx, _ = self._lflip_flip(
                        start, theta_p, x_p, y_p, perm_p, size_p, mb_i, mb_cap, r)
                    new_theta = jnp.where(act > 0, new_theta, theta_p)
                    p, _, ls, ac = self._partner_pass(
                        start, x_p, y_p, perm_p, size_p, act, mb_i,
                        jax.random.fold_in(r, 7), y_override=y_flip, window_idx=w_idx)
                    if det:
                        return p, new_theta
                    return p, new_theta, ls, ac
                out = self._det_isolated_vmap(
                    one, (params, theta, stacked.x, stacked.y, perms,
                          stacked.sizes, coal_mask, p_rngs),
                    in_axes=(None, 0, 0, 0, 0, 0, 0, 0))
                if det:
                    new_params, theta = out
                    losses = accs = jnp.full((P,), jnp.nan)
                else:
                    new_params, theta, losses, accs = out
            elif stragglers:
                starts = self._straggler_starts(params, stale)

                def one(start_p, x_p, y_p, perm_p, size_p, act, r):
                    p, _, ls, ac = self._partner_pass(
                        start_p, x_p, y_p, perm_p, size_p, act, mb_i, r)
                    return (p,) if det else (p, ls, ac)
                out = self._det_isolated_vmap(
                    one, (starts, stacked.x, stacked.y, perms, stacked.sizes,
                          act_mask, p_rngs),
                    in_axes=(0, 0, 0, 0, 0, 0, 0))
                if det:
                    (new_params,) = out
                    losses = accs = jnp.full((P,), jnp.nan)
                else:
                    new_params, losses, accs = out
            else:
                def one(start, x_p, y_p, perm_p, size_p, act, r):
                    p, _, ls, ac = self._partner_pass(
                        start, x_p, y_p, perm_p, size_p, act, mb_i, r)
                    return (p,) if det else (p, ls, ac)
                out = self._det_isolated_vmap(
                    one, (params, stacked.x, stacked.y, perms, stacked.sizes,
                          act_mask, p_rngs),
                    in_axes=(None, 0, 0, 0, 0, 0, 0))
                if det:
                    (new_params,) = out
                    losses = accs = jnp.full((P,), jnp.nan)
                else:
                    new_params, losses, accs = out

            need_pval = cfg.record_partner_val or cfg.aggregator == "local-score"
            if need_pval:
                pvl, pva = jax.vmap(lambda pp: self.evaluate(pp, val))(new_params)
            else:
                pvl = jnp.full((P,), jnp.nan)
                pva = jnp.full((P,), jnp.nan)
            p_h = self._record_partner(p_h, e, mb_i,
                                       jnp.stack([losses, accs, pvl, pva]))

            w = aggregation_weights(cfg.aggregator, act_mask,
                                    stacked.sizes, jnp.nan_to_num(pva),
                                    axis_name=cfg.partner_axis,
                                    deterministic=cfg.deterministic_reduce)
            if recording:
                # the round's recorded row: per-partner delta from the
                # round-start global params (inactive/dropped partners
                # trained to exactly their start params, so their rows are
                # exact zeros) and the normalized weight vector the
                # aggregation below actually applies
                r_idx = e * cfg.minibatch_count + mb_i
                upd_h = jax.tree_util.tree_map(
                    lambda h, loc, g: h.at[r_idx].set(loc - g),
                    upd_h, new_params, params)
                w_h = w_h.at[r_idx].set(w)
            agg = aggregate(new_params, w, axis_name=cfg.partner_axis,
                            deterministic=cfg.deterministic_reduce)
            if faulted:
                # a round with zero survivors (every coalition member
                # dropped) keeps the global params instead of aggregating
                # an all-zero weight vector into a zero model
                agg = tree_where(jnp.sum(act_mask) > 0, agg, params)
            if stragglers:
                stale = self._push_stale(stale, params)
            return (agg, theta, vl_h, va_h, p_h, stale, upd_h, w_h), None

        carry = (state.params, state.theta, state.val_loss_h,
                 state.val_acc_h, state.partner_h, state.stale,
                 state.upd_h, state.w_h)
        if cfg.deterministic_reduce:
            # trace-time unroll instead of lax.scan: a round body INSIDE a
            # while loop compiles differently per device/topology on this
            # toolchain (the numerics audit's localization — even a
            # length-1 scan wrapping the pass+collective block breaks
            # cross-topology bit-identity), while the identical blocks
            # unrolled at top level compile stably. minibatch_count is
            # static, so the unroll is exact, not an approximation (and
            # the python-int minibatch index makes the hoisted-stream
            # slicing and history writes static ops).
            for _mb in range(cfg.minibatch_count):
                carry, _ = mb_body(carry, _mb)
        else:
            carry, _ = lax.scan(mb_body, carry,
                                jnp.arange(cfg.minibatch_count))
        (params, theta, vl_h, va_h, p_h, stale, upd_h, w_h) = carry
        return state._replace(params=params, theta=theta, val_loss_h=vl_h,
                              val_acc_h=va_h, partner_h=p_h, stale=stale,
                              upd_h=upd_h, w_h=w_h)

    def _slot_binding(self, stacked, active_ids, rng):
        """Shared slot-execution prep: bind each slot to its partner's data
        (row offsets into the flat [P*Nmax, ...] view — one fused gather, no
        per-slot copy) and draw each slot's epoch permutation keyed by
        GLOBAL partner id, the identical stream to the masked path's
        `_epoch_perms`. Returns (ids, active, pids, flat_x, flat_y,
        slot_sizes, perms)."""
        P, n_max = stacked.x.shape[0], stacked.x.shape[1]
        ids = active_ids.astype(jnp.int32)            # [K]
        active = (ids >= 0).astype(jnp.float32)       # [K]
        pids = jnp.maximum(ids, 0)                    # [K] safe partner rows

        flat_x = stacked.x.reshape((P * n_max,) + stacked.x.shape[2:])
        flat_y = stacked.y.reshape((P * n_max,) + stacked.y.shape[2:])
        slot_sizes = jnp.take(stacked.sizes, pids, axis=0)          # [K]
        slot_mask_rows = jnp.take(stacked.mask, pids, axis=0)       # [K, Nmax]

        rng_perm = jax.random.fold_in(rng, 0)

        def perm_of(pid, mask_row):
            keys = jax.random.uniform(jax.random.fold_in(rng_perm, pid),
                                      mask_row.shape) + (1.0 - mask_row) * 1e9
            return jnp.argsort(keys).astype(jnp.int32)

        perms = jax.vmap(perm_of)(pids, slot_mask_rows)             # [K, Nmax]
        return ids, active, pids, flat_x, flat_y, slot_sizes, perms

    def _fedavg_slot_epoch(self, state: TrainState, stacked, val: EvalSet,
                           active_ids, rng) -> TrainState:
        """fedavg epoch over `slot_count` partner slots instead of all P
        partners: a size-k coalition costs k partner passes, not P. Slot s
        binds to partner `active_ids[s]` (-1 = unused slot); data rows come
        from one fused gather into the flat [P*Nmax, ...] view. RNG streams
        are keyed by partner id, so results equal the masked path exactly."""
        cfg = self.cfg
        e = state.epoch
        P, n_max = stacked.x.shape[0], stacked.x.shape[1]
        ids, active, pids, flat_x, flat_y, slot_sizes, perms = \
            self._slot_binding(stacked, active_ids, rng)
        # partner-level faults: slot activity = binding activity x the
        # partner's dropout schedule (gathered by bound partner id, so a
        # slot bound to a dropped partner behaves exactly like a padding
        # slot from its drop epoch on: zero gradients, zero aggregation
        # weight, survivors renormalized). Stragglers select their pass's
        # start params from the TrainState's rolling stale buffer.
        faulted = self._partner_faulted
        act_mask = active * jnp.take(self._drop_active(e, P), pids) \
            if faulted else active
        stragglers = faulted and bool(cfg.partner_straggler_delays)
        if stragglers:
            delay_arr = jnp.asarray(cfg.partner_straggler_delays, jnp.int32)
            D = max(cfg.partner_straggler_delays)

        def mb_body(carry, mb_i):
            if stragglers:
                params, vl_h, va_h, p_h, stale = carry
            else:
                params, vl_h, va_h, p_h = carry
            vl, va = self._maybe_val_eval(params, val, mb_i, es_col=0)
            vl_h = vl_h.at[e, mb_i].set(vl)
            va_h = va_h.at[e, mb_i].set(va)

            rng_mb = jax.random.fold_in(jax.random.fold_in(rng, 1), mb_i)

            def one(pid, act, perm_p, size_p):
                r = jax.random.fold_in(rng_mb, pid)
                if stragglers:
                    d = jnp.take(delay_arr, pid)
                    start = jax.tree_util.tree_map(
                        lambda g, st: jnp.where(
                            d == 0, g,
                            jnp.take(st, jnp.clip(d - 1, 0, D - 1), axis=0)),
                        params, stale)
                else:
                    start = params
                p, _, ls, ac = self._partner_pass(
                    start, flat_x, flat_y, perm_p, size_p, act, mb_i, r,
                    row_offset=pid * n_max, n_max=n_max)
                return p, ls, ac

            new_params, losses, accs = jax.vmap(one)(pids, act_mask, perms,
                                                     slot_sizes)

            need_pval = cfg.record_partner_val or cfg.aggregator == "local-score"
            if need_pval:
                pvl, pva = jax.vmap(lambda pp: self.evaluate(pp, val))(new_params)
            else:
                pvl = jnp.full(ids.shape, jnp.nan)
                pva = jnp.full(ids.shape, jnp.nan)
            # scatter slot metrics into the [P]-indexed history; unused
            # slots are dropped via an out-of-bounds row
            scatter_rows = jnp.where(ids >= 0, pids, P)
            p_h = p_h.at[:, scatter_rows, e, mb_i].set(
                jnp.stack([losses, accs, pvl, pva]), mode="drop")

            w = aggregation_weights(cfg.aggregator, act_mask, slot_sizes,
                                    jnp.nan_to_num(pva),
                                    deterministic=cfg.deterministic_reduce)
            agg = aggregate(new_params, w,
                            deterministic=cfg.deterministic_reduce)
            if faulted:
                # zero survivors this round: keep the global params
                agg = tree_where(jnp.sum(act_mask) > 0, agg, params)
            if stragglers:
                stale = self._push_stale(stale, params)
                return (agg, vl_h, va_h, p_h, stale), None
            return (agg, vl_h, va_h, p_h), None

        if stragglers:
            (params, vl_h, va_h, p_h, stale), _ = lax.scan(
                mb_body, (state.params, state.val_loss_h, state.val_acc_h,
                          state.partner_h, state.stale),
                jnp.arange(cfg.minibatch_count))
            return state._replace(params=params, val_loss_h=vl_h,
                                  val_acc_h=va_h, partner_h=p_h, stale=stale)
        (params, vl_h, va_h, p_h), _ = lax.scan(
            mb_body, (state.params, state.val_loss_h, state.val_acc_h,
                      state.partner_h),
            jnp.arange(cfg.minibatch_count))
        return state._replace(params=params, val_loss_h=vl_h, val_acc_h=va_h,
                              partner_h=p_h)

    def _seq_epoch(self, state: TrainState, stacked, val: EvalSet,
                   coal_mask, rng) -> TrainState:
        cfg = self.cfg
        P = stacked.x.shape[0]
        e = state.epoch
        perms = self._epoch_perms(jax.random.fold_in(rng, 0), stacked.mask)
        partner_stack = broadcast(state.params, P)

        def mb_body(carry, mb_i):
            params, partner_stack, vl_h, va_h, p_h = carry
            vl, va = self._maybe_val_eval(params, val, mb_i,
                                          es_col=cfg.minibatch_count - 1)
            vl_h = vl_h.at[e, mb_i].set(vl)
            va_h = va_h.at[e, mb_i].set(va)

            rng_mb = jax.random.fold_in(jax.random.fold_in(rng, 1), mb_i)
            # Random visit order with active partners first
            order_keys = jax.random.uniform(jax.random.fold_in(rng_mb, 0), (P,)) \
                + (1.0 - coal_mask) * 1e3
            order = jnp.argsort(order_keys).astype(jnp.int32)
            opt_state0 = self.opt.init(params)

            def partner_body(carry2, pos):
                params, opt_state, partner_stack, p_h = carry2
                p = order[pos]
                act = coal_mask[p]
                x_p = jnp.take(stacked.x, p, axis=0)
                y_p = jnp.take(stacked.y, p, axis=0)
                perm_p = jnp.take(perms, p, axis=0)
                size_p = jnp.take(stacked.sizes, p, axis=0)
                r = jax.random.fold_in(rng_mb, pos + 1)
                new_params, new_opt, ls, ac = self._partner_pass(
                    params, x_p, y_p, perm_p, size_p, act, mb_i, r,
                    opt_state=opt_state)
                params = tree_where(act > 0, new_params, params)
                opt_state = tree_where(act > 0, new_opt, opt_state)
                partner_stack = jax.tree_util.tree_map(
                    lambda leaf, newp: leaf.at[p].set(
                        jnp.where(act > 0, newp, leaf[p])),
                    partner_stack, params)
                if cfg.record_partner_val or cfg.aggregator == "local-score":
                    pvl, pva = self.evaluate(params, val)
                else:
                    pvl, pva = jnp.nan, jnp.nan
                vals = jnp.where(act > 0,
                                 jnp.stack([ls, ac, pvl, pva]),
                                 p_h[:, p, e, mb_i])
                p_h = p_h.at[:, p, e, mb_i].set(vals)
                return (params, opt_state, partner_stack, p_h), None

            (params, _, partner_stack, p_h), _ = lax.scan(
                partner_body, (params, opt_state0, partner_stack, p_h),
                jnp.arange(P))

            if cfg.approach == "seqavg":
                w = aggregation_weights(cfg.aggregator, coal_mask, stacked.sizes,
                                        jnp.nan_to_num(p_h[3, :, e, mb_i]),
                                        deterministic=cfg.deterministic_reduce)
                params = aggregate(partner_stack, w,
                                   deterministic=cfg.deterministic_reduce)
            return (params, partner_stack, vl_h, va_h, p_h), None

        (params, partner_stack, vl_h, va_h, p_h), _ = lax.scan(
            mb_body, (state.params, partner_stack, state.val_loss_h,
                      state.val_acc_h, state.partner_h),
            jnp.arange(cfg.minibatch_count))

        if cfg.approach == "seq-with-final-agg":
            w = aggregation_weights(cfg.aggregator, coal_mask, stacked.sizes,
                                    jnp.nan_to_num(p_h[3, :, e, cfg.minibatch_count - 1]),
                                    deterministic=cfg.deterministic_reduce)
            params = aggregate(partner_stack, w,
                               deterministic=cfg.deterministic_reduce)
        return state._replace(params=params, val_loss_h=vl_h, val_acc_h=va_h,
                              partner_h=p_h)

    def _seq_slot_epoch(self, state: TrainState, stacked, val: EvalSet,
                        active_ids, rng) -> TrainState:
        """seq-family epoch over `slot_count` partner slots: the partner
        scan visits K bound slots instead of all P partners, so a size-k
        coalition costs k sequential passes, not P (the inactive visits the
        masked path spends on no-op passes vanish).

        Bit-equality with `_seq_epoch`: the visit order is an active-first
        permutation, so active partners occupy scan positions 0..|S|-1 in
        both paths — and the pass rng is keyed by POSITION (`pos + 1`), so
        the order keys must come from the masked path's full-width [P]
        uniform draw (gathered per slot, not redrawn at width K) for the
        relative order of the active partners to be identical. Epoch
        permutations are keyed by global partner id (`_slot_binding`), and
        padded `-1` slots sort last with zero aggregation weight, exactly
        like the masked path's inactive tail."""
        cfg = self.cfg
        e = state.epoch
        P, n_max = stacked.x.shape[0], stacked.x.shape[1]
        ids, active, pids, flat_x, flat_y, slot_sizes, perms = \
            self._slot_binding(stacked, active_ids, rng)
        K = ids.shape[0]
        partner_stack = broadcast(state.params, K)   # slot-indexed

        def mb_body(carry, mb_i):
            params, partner_stack, vl_h, va_h, p_h, _ = carry
            vl, va = self._maybe_val_eval(params, val, mb_i,
                                          es_col=cfg.minibatch_count - 1)
            vl_h = vl_h.at[e, mb_i].set(vl)
            va_h = va_h.at[e, mb_i].set(va)

            rng_mb = jax.random.fold_in(jax.random.fold_in(rng, 1), mb_i)
            # the masked path's [P] order-key draw, gathered per slot: the
            # active slots' relative order (and therefore their scan
            # positions, which key the pass rngs) matches exactly
            order_keys = jax.random.uniform(jax.random.fold_in(rng_mb, 0),
                                            (P,))
            slot_keys = jnp.take(order_keys, pids) + (1.0 - active) * 1e3
            slot_order = jnp.argsort(slot_keys).astype(jnp.int32)    # [K]
            opt_state0 = self.opt.init(params)
            pva_slots0 = jnp.full((K,), jnp.nan, jnp.float32)

            def partner_body(carry2, pos):
                params, opt_state, partner_stack, p_h, pva_slots = carry2
                s = slot_order[pos]
                pid = jnp.take(pids, s)
                act = jnp.take(active, s)
                perm_p = jnp.take(perms, s, axis=0)
                size_p = jnp.take(slot_sizes, s, axis=0)
                r = jax.random.fold_in(rng_mb, pos + 1)
                new_params, new_opt, ls, ac = self._partner_pass(
                    params, flat_x, flat_y, perm_p, size_p, act, mb_i, r,
                    opt_state=opt_state, row_offset=pid * n_max, n_max=n_max)
                params = tree_where(act > 0, new_params, params)
                opt_state = tree_where(act > 0, new_opt, opt_state)
                partner_stack = jax.tree_util.tree_map(
                    lambda leaf, newp: leaf.at[s].set(
                        jnp.where(act > 0, newp, leaf[s])),
                    partner_stack, params)
                if cfg.record_partner_val or cfg.aggregator == "local-score":
                    pvl, pva = self.evaluate(params, val)
                else:
                    pvl, pva = jnp.nan, jnp.nan
                # scatter into the [P]-indexed history; unused slots drop
                # via an out-of-bounds row (same convention as the fedavg
                # slot epoch)
                row = jnp.where(act > 0, pid, P)
                p_h = p_h.at[:, row, e, mb_i].set(
                    jnp.stack([ls, ac,
                               jnp.asarray(pvl, jnp.float32),
                               jnp.asarray(pva, jnp.float32)]), mode="drop")
                pva_slots = pva_slots.at[s].set(
                    jnp.where(act > 0, jnp.asarray(pva, jnp.float32),
                              jnp.nan))
                return (params, opt_state, partner_stack, p_h, pva_slots), None

            (params, _, partner_stack, p_h, pva_slots), _ = lax.scan(
                partner_body,
                (params, opt_state0, partner_stack, p_h, pva_slots0),
                jnp.arange(K))

            if cfg.approach == "seqavg":
                w = aggregation_weights(cfg.aggregator, active, slot_sizes,
                                        jnp.nan_to_num(pva_slots),
                                        deterministic=cfg.deterministic_reduce)
                params = aggregate(partner_stack, w,
                                   deterministic=cfg.deterministic_reduce)
            return (params, partner_stack, vl_h, va_h, p_h, pva_slots), None

        pva_init = jnp.full((K,), jnp.nan, jnp.float32)
        (params, partner_stack, vl_h, va_h, p_h, pva_last), _ = lax.scan(
            mb_body, (state.params, partner_stack, state.val_loss_h,
                      state.val_acc_h, state.partner_h, pva_init),
            jnp.arange(cfg.minibatch_count))

        if cfg.approach == "seq-with-final-agg":
            # pva_last is the final minibatch's per-slot val accuracy — the
            # slot view of the masked path's p_h[3, :, e, MB-1] column
            w = aggregation_weights(cfg.aggregator, active, slot_sizes,
                                    jnp.nan_to_num(pva_last),
                                    deterministic=cfg.deterministic_reduce)
            params = aggregate(partner_stack, w,
                               deterministic=cfg.deterministic_reduce)
        return state._replace(params=params, val_loss_h=vl_h, val_acc_h=va_h,
                              partner_h=p_h)

    def _single_epoch(self, state: TrainState, stacked, val: EvalSet,
                      coal_mask, rng) -> TrainState:
        """One epoch of single-partner training: `mb*gup` persistent-optimizer
        steps over the lone active partner's shuffled data
        (reference SinglePartnerLearning, multi_partner_learning.py:230-275)."""
        cfg = self.cfg
        e = state.epoch
        # the lone active partner's row
        p = jnp.argmax(coal_mask).astype(jnp.int32)
        x_p = jnp.take(stacked.x, p, axis=0)
        y_p = jnp.take(stacked.y, p, axis=0)
        size_p = jnp.take(stacked.sizes, p, axis=0)
        mask_p = jnp.take(stacked.mask, p, axis=0)
        n_max = x_p.shape[0]
        keys = jax.random.uniform(jax.random.fold_in(rng, 0), (n_max,)) \
            + (1.0 - mask_p) * 1e9
        perm = jnp.argsort(keys).astype(jnp.int32)
        steps = cfg.minibatch_count * cfg.gradient_updates_per_pass
        sb_cap = max((n_max + steps - 1) // steps, 1)
        sb = (size_p + steps - 1) // steps

        def step(carry, g):
            params, opt_state, sums = carry
            ar = jnp.arange(sb_cap, dtype=jnp.int32)
            local = g * sb + ar
            valid = ((ar < sb) & (local < size_p)).astype(jnp.float32)
            idx = perm[jnp.clip(local, 0, n_max - 1)]
            x = jnp.take(x_p, idx, axis=0)
            y = jnp.take(y_p, idx, axis=0)
            params, opt_state, loss, acc, cnt = self._sgd_step(
                params, opt_state, x, y, valid, jax.random.fold_in(rng, g + 1))
            sums = (sums[0] + loss * cnt, sums[1] + acc * cnt, sums[2] + cnt)
            return (params, opt_state, sums), None

        (params, opt_state, sums), _ = lax.scan(
            step, (state.params, state.opt_state, (0.0, 0.0, 0.0)),
            jnp.arange(steps))
        if cfg.partner_drop_epochs is not None:
            # partner-level dropout: from the partner's drop epoch on, its
            # solo training simply stops — params AND optimizer state are
            # frozen (the persistent Adam state would otherwise keep
            # coasting on momentum with zero gradients). The epoch's v-eval
            # below then scores the pre-drop model, every epoch after.
            drop_p = jnp.take(jnp.asarray(cfg.partner_drop_epochs, jnp.int32),
                              p)
            act_e = jnp.where(drop_p == 0, True, e + 1 < drop_p)
            params = tree_where(act_e, params, state.params)
            opt_state = tree_where(act_e, opt_state, state.opt_state)
        if cfg.record_val_history or cfg.is_early_stopping:
            vl, va = self.evaluate(params, val)
        else:
            vl = va = jnp.full((), jnp.nan, jnp.float32)
        denom = jnp.maximum(sums[2], 1.0)
        vl_h = state.val_loss_h.at[e, 0].set(vl)
        va_h = state.val_acc_h.at[e, 0].set(va)
        p_h = state.partner_h
        p_h = p_h.at[:, 0, e, 0].set(jnp.stack([sums[0] / denom, sums[1] / denom, vl, va]))
        return state._replace(params=params, opt_state=opt_state,
                              val_loss_h=vl_h, val_acc_h=va_h, partner_h=p_h)

    # ------------------------------------------------------------------
    # epoch + early stopping + chunk driver
    # ------------------------------------------------------------------

    def _early_stop_flag(self, state: TrainState) -> jax.Array:
        cfg = self.cfg
        e = state.epoch
        if not cfg.is_early_stopping:
            return jnp.zeros((), bool)
        if cfg.approach == "single":
            # Keras EarlyStopping semantics handled in run_epoch via best/wait.
            return state.es_wait >= cfg.patience
        col = 0 if cfg.approach in ("fedavg", "lflip") else cfg.minibatch_count - 1
        cur = state.val_loss_h[e, col]
        past = state.val_loss_h[jnp.maximum(e - cfg.patience, 0), col]
        return (e >= cfg.patience) & (cur > past)

    def run_epoch(self, state: TrainState, stacked, val: EvalSet,
                  coal_mask, rng, streams=None) -> TrainState:
        """One epoch with done-freezing; safe inside scan/vmap."""
        cfg = self.cfg
        rng = jax.random.fold_in(rng, state.epoch)
        if cfg.slot_count is not None:
            if cfg.approach == "fedavg":
                new = self._fedavg_slot_epoch(state, stacked, val, coal_mask,
                                              rng)
            else:
                new = self._seq_slot_epoch(state, stacked, val, coal_mask,
                                           rng)
        elif cfg.approach in ("fedavg", "lflip"):
            new = self._fedavg_epoch(state, stacked, val, coal_mask, rng,
                                     streams=streams)
        elif cfg.approach == "single":
            new = self._single_epoch(state, stacked, val, coal_mask, rng)
        else:
            new = self._seq_epoch(state, stacked, val, coal_mask, rng)

        if cfg.approach == "lflip":
            # end-of-epoch theta snapshot (reference overwrites
            # history.theta[epoch][p] each minibatch, so the epoch's final
            # value is what survives — multi_partner_learning.py:482-484)
            new = new._replace(theta_h=new.theta_h.at[new.epoch].set(new.theta))

        # single-partner Keras-style ES bookkeeping
        if cfg.approach == "single":
            vl = new.val_loss_h[new.epoch, 0]
            improved = vl < new.best_val_loss
            new = new._replace(
                best_val_loss=jnp.where(improved, vl, new.best_val_loss),
                es_wait=jnp.where(improved, 0, new.es_wait + 1))

        stop = self._early_stop_flag(new)
        advanced = new._replace(
            epoch=new.epoch + 1,
            nb_epochs_done=new.nb_epochs_done + 1,
            done=new.done | stop | (new.epoch + 1 >= cfg.epoch_count))
        # freeze everything if this coalition had already stopped
        return tree_where(state.done, state, advanced)

    def epoch_chunk(self, state: TrainState, stacked, val: EvalSet,
                    coal_mask, rng, n_epochs: int,
                    streams_all=None) -> TrainState:
        if self.cfg.deterministic_reduce:
            # same trace-time unroll as the deterministic minibatch loop:
            # epoch bodies inside a lax.scan compile per-topology on this
            # toolchain; unrolled they compile stably (n_epochs is a
            # static argument already). `streams_all` (the hoisted
            # [E, ...] permutation/key stacks) slices per epoch here.
            for i in range(n_epochs):
                streams = (None if streams_all is None else
                           jax.tree_util.tree_map(lambda a: a[i],
                                                  streams_all))
                state = self.run_epoch(state, stacked, val, coal_mask,
                                       jax.random.fold_in(rng, i),
                                       streams=streams)
            return state

        def body(s, i):
            return self.run_epoch(s, stacked, val, coal_mask,
                                  jax.random.fold_in(rng, i)), None
        state, _ = lax.scan(body, state, jnp.arange(n_epochs))
        return state

    def finalize(self, state: TrainState, test: EvalSet) -> tuple[jax.Array, jax.Array]:
        """(test_loss, test_accuracy) of the final global model — the
        characteristic-function value (reference history.score,
        multi_partner_learning.py:158-169)."""
        return self.evaluate(state.params, test)
