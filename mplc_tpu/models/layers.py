"""Minimal pure-functional NN layers (init + apply) for the model zoo.

Deliberately not a port of Keras: layers are plain functions over explicit
parameter pytrees, so whole models can be stacked along a leading partner
axis and driven by `vmap`/`scan`/`shard_map`. Initializers match Keras
defaults (glorot-uniform kernels, zero biases, uniform(-0.05, 0.05)
embeddings) so training dynamics stay comparable to the reference models
(/root/reference/mplc/dataset.py:167-200, :457-479, :546-567, :695-722).

Convolutions use NHWC layout and run through `lax.conv_general_dilated`,
which XLA tiles onto the TPU MXU; pooling uses `lax.reduce_window`.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def _glorot_uniform(rng: jax.Array, shape: tuple[int, ...], fan_in: int, fan_out: int) -> jax.Array:
    limit = np.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, jnp.float32, -limit, limit)


# ---------------------------------------------------------------------------
# Dense
# ---------------------------------------------------------------------------

def dense_init(rng: jax.Array, in_dim: int, out_dim: int) -> dict:
    return {
        "w": _glorot_uniform(rng, (in_dim, out_dim), in_dim, out_dim),
        "b": jnp.zeros((out_dim,), jnp.float32),
    }


def dense(params: dict, x: jax.Array) -> jax.Array:
    return x @ params["w"] + params["b"]


# ---------------------------------------------------------------------------
# Conv2D (NHWC), Conv1D (NWC)
# ---------------------------------------------------------------------------

def conv2d_init(rng: jax.Array, kh: int, kw: int, cin: int, cout: int) -> dict:
    fan_in = kh * kw * cin
    fan_out = kh * kw * cout
    return {
        "w": _glorot_uniform(rng, (kh, kw, cin, cout), fan_in, fan_out),
        "b": jnp.zeros((cout,), jnp.float32),
    }


def conv2d(params: dict, x: jax.Array, padding: str = "VALID") -> jax.Array:
    out = lax.conv_general_dilated(
        x, params["w"], window_strides=(1, 1), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out + params["b"]


def conv1d_init(rng: jax.Array, k: int, cin: int, cout: int) -> dict:
    fan_in = k * cin
    fan_out = k * cout
    return {
        "w": _glorot_uniform(rng, (k, cin, cout), fan_in, fan_out),
        "b": jnp.zeros((cout,), jnp.float32),
    }


def conv1d(params: dict, x: jax.Array, padding: str = "SAME") -> jax.Array:
    out = lax.conv_general_dilated(
        x, params["w"], window_strides=(1,), padding=padding,
        dimension_numbers=("NWC", "WIO", "NWC"))
    return out + params["b"]


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

def max_pool_2d(x: jax.Array, window: int = 2) -> jax.Array:
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, window, window, 1),
        padding="VALID")


def max_pool_1d(x: jax.Array, window: int = 2) -> jax.Array:
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, 1),
        window_strides=(1, window, 1),
        padding="VALID")


def global_avg_pool_2d(x: jax.Array) -> jax.Array:
    return jnp.mean(x, axis=(1, 2))


# ---------------------------------------------------------------------------
# Embedding / dropout
# ---------------------------------------------------------------------------

def embedding_init(rng: jax.Array, vocab: int, dim: int) -> dict:
    return {"table": jax.random.uniform(rng, (vocab, dim), jnp.float32, -0.05, 0.05)}


def embedding(params: dict, tokens: jax.Array) -> jax.Array:
    return jnp.take(params["table"], tokens.astype(jnp.int32), axis=0)


def dropout(rng: jax.Array, x: jax.Array, rate: float, train: bool) -> jax.Array:
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)
