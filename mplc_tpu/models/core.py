"""The Model contract: a bundle of pure functions.

The reference couples models to datasets via Keras factories
(`Dataset.generate_new_model`, /root/reference/mplc/dataset.py:79-81). The
TPU-native equivalent is a frozen bundle of pure functions: `init` builds a
parameter pytree, `apply` maps (params, batch) -> logits. Because params are
plain pytrees, a fleet of per-partner or per-coalition model replicas is just
the same pytree with a stacked leading axis — `vmap` does the rest, and
weight "communication" is a masked reduction over that axis
(see mplc_tpu/ops/aggregation.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import optax


@dataclasses.dataclass(frozen=True)
class Model:
    """A pure-functional model family.

    Attributes:
        name: model family tag.
        init: rng -> params pytree (float32 leaves).
        apply: (params, x, train, rng, compute_dtype) -> logits (float32).
        loss_kind: "categorical" (softmax CE over one-hot labels) or
            "binary" (sigmoid CE over a single logit).
        num_outputs: logits dimensionality (1 for binary).
        make_optimizer: () -> optax.GradientTransformation.
    """

    name: str
    init: Callable[[jax.Array], dict]
    apply: Callable[..., jax.Array]
    loss_kind: str
    num_outputs: int
    make_optimizer: Callable[[], optax.GradientTransformation]

    def label_dim(self) -> int:
        """Width of the label array fed to the loss (one-hot width, or 1)."""
        return 1 if self.loss_kind == "binary" else self.num_outputs


def adam_like_keras(learning_rate: float = 1e-3) -> optax.GradientTransformation:
    # Keras Adam defaults use eps=1e-7 (vs optax 1e-8); matched for parity.
    return optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-7)


def rmsprop_like_keras(learning_rate: float = 1e-4) -> optax.GradientTransformation:
    # Reference CIFAR10 CNN compiles RMSprop(lr=1e-4, decay=1e-6)
    # (/root/reference/mplc/dataset.py:192-196). Keras "decay" is a lr schedule;
    # at the step counts involved its effect is negligible, so plain rmsprop.
    return optax.rmsprop(learning_rate, decay=0.9, eps=1e-7)
