"""The five built-in model families, re-implemented TPU-first.

Architecture parity targets (reference, Keras):
  - MNIST CNN:   /root/reference/mplc/dataset.py:457-479
  - CIFAR10 CNN: /root/reference/mplc/dataset.py:167-200
  - IMDB Embedding+Conv1D: /root/reference/mplc/dataset.py:546-567
  - ESC50 CNN:   /root/reference/mplc/dataset.py:695-722
  - Titanic logistic regression (sklearn shim in the reference,
    /root/reference/mplc/dataset.py:302-394): here a 1-layer sigmoid model
    trained by SGD like every other family, keeping the metric contract
    (log-loss + accuracy) without the sklearn detour.

All `apply` functions take `compute_dtype` so activations/matmuls can run in
bfloat16 on the MXU while parameters and logits stay float32.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from . import layers as L
from .core import Model, adam_like_keras, rmsprop_like_keras


def _split(rng, n):
    return jax.random.split(rng, n)


def _cast(tree, dtype):
    return jax.tree_util.tree_map(lambda t: t.astype(dtype), tree)


# ---------------------------------------------------------------------------
# MNIST CNN: conv3x3x32 -> conv3x3x64 -> maxpool2 -> dense128 -> dense10
# ---------------------------------------------------------------------------

def _mnist_init(rng: jax.Array) -> dict:
    r1, r2, r3, r4 = _split(rng, 4)
    return {
        "c1": L.conv2d_init(r1, 3, 3, 1, 32),
        "c2": L.conv2d_init(r2, 3, 3, 32, 64),
        "d1": L.dense_init(r3, 12 * 12 * 64, 128),
        "d2": L.dense_init(r4, 128, 10),
    }


def _mnist_apply(params, x, train=False, rng=None, compute_dtype=jnp.float32):
    p = _cast(params, compute_dtype)
    h = x.astype(compute_dtype)
    h = jax.nn.relu(L.conv2d(p["c1"], h))
    h = jax.nn.relu(L.conv2d(p["c2"], h))
    h = L.max_pool_2d(h)
    h = h.reshape(h.shape[0], -1)
    h = jax.nn.relu(L.dense(p["d1"], h))
    return L.dense(p["d2"], h).astype(jnp.float32)


# ---------------------------------------------------------------------------
# CIFAR10 CNN: [conv32 same, conv32, pool, drop.25] x2 (64), dense512, drop.5
# ---------------------------------------------------------------------------

def _cifar_init(rng: jax.Array) -> dict:
    r1, r2, r3, r4, r5, r6 = _split(rng, 6)
    return {
        "c1": L.conv2d_init(r1, 3, 3, 3, 32),
        "c2": L.conv2d_init(r2, 3, 3, 32, 32),
        "c3": L.conv2d_init(r3, 3, 3, 32, 64),
        "c4": L.conv2d_init(r4, 3, 3, 64, 64),
        "d1": L.dense_init(r5, 6 * 6 * 64, 512),
        "d2": L.dense_init(r6, 512, 10),
    }


def _cifar_apply(params, x, train=False, rng=None, compute_dtype=jnp.float32):
    p = _cast(params, compute_dtype)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    k1, k2, k3 = _split(rng, 3)
    h = x.astype(compute_dtype)
    h = jax.nn.relu(L.conv2d(p["c1"], h, padding="SAME"))
    h = jax.nn.relu(L.conv2d(p["c2"], h))
    h = L.max_pool_2d(h)
    h = L.dropout(k1, h, 0.25, train)
    h = jax.nn.relu(L.conv2d(p["c3"], h, padding="SAME"))
    h = jax.nn.relu(L.conv2d(p["c4"], h))
    h = L.max_pool_2d(h)
    h = L.dropout(k2, h, 0.25, train)
    h = h.reshape(h.shape[0], -1)
    h = jax.nn.relu(L.dense(p["d1"], h))
    h = L.dropout(k3, h, 0.5, train)
    return L.dense(p["d2"], h).astype(jnp.float32)


# ---------------------------------------------------------------------------
# IMDB: embed(5000,32) -> conv1d(32,k3,same) -> maxpool -> dense256 -> dense64 -> 1
# ---------------------------------------------------------------------------

IMDB_NUM_WORDS = 5000
IMDB_SEQ_LEN = 500


def _imdb_init(rng: jax.Array) -> dict:
    r1, r2, r3, r4, r5 = _split(rng, 5)
    return {
        "emb": L.embedding_init(r1, IMDB_NUM_WORDS, 32),
        "c1": L.conv1d_init(r2, 3, 32, 32),
        "d1": L.dense_init(r3, (IMDB_SEQ_LEN // 2) * 32, 256),
        "d2": L.dense_init(r4, 256, 64),
        "d3": L.dense_init(r5, 64, 1),
    }


def _imdb_apply(params, x, train=False, rng=None, compute_dtype=jnp.float32):
    p = _cast(params, compute_dtype)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    k1, k2 = _split(rng, 2)
    h = L.embedding(p["emb"], x)
    h = jax.nn.relu(L.conv1d(p["c1"], h, padding="SAME"))
    h = L.max_pool_1d(h)
    h = h.reshape(h.shape[0], -1)
    h = jax.nn.relu(L.dense(p["d1"], h))
    h = L.dropout(k1, h, 0.5, train)
    h = jax.nn.relu(L.dense(p["d2"], h))
    h = L.dropout(k2, h, 0.5, train)
    return L.dense(p["d3"], h).astype(jnp.float32)


# ---------------------------------------------------------------------------
# ESC50: 4x [conv k2, pool2, drop .2] (16/32/64/128) -> GAP -> dense50
# ---------------------------------------------------------------------------

def _esc50_init(rng: jax.Array) -> dict:
    r1, r2, r3, r4, r5 = _split(rng, 5)
    return {
        "c1": L.conv2d_init(r1, 2, 2, 1, 16),
        "c2": L.conv2d_init(r2, 2, 2, 16, 32),
        "c3": L.conv2d_init(r3, 2, 2, 32, 64),
        "c4": L.conv2d_init(r4, 2, 2, 64, 128),
        "d1": L.dense_init(r5, 128, 50),
    }


def _esc50_apply(params, x, train=False, rng=None, compute_dtype=jnp.float32):
    p = _cast(params, compute_dtype)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    ks = _split(rng, 4)
    h = x.astype(compute_dtype)
    for i, name in enumerate(["c1", "c2", "c3", "c4"]):
        h = jax.nn.relu(L.conv2d(p[name], h))
        h = L.max_pool_2d(h)
        h = L.dropout(ks[i], h, 0.2, train)
    h = L.global_avg_pool_2d(h)
    return L.dense(p["d1"], h).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Titanic: logistic regression over 27 features
# ---------------------------------------------------------------------------

TITANIC_NUM_FEATURES = 27


def _titanic_init(rng: jax.Array) -> dict:
    return {"d1": L.dense_init(rng, TITANIC_NUM_FEATURES, 1)}


def _titanic_apply(params, x, train=False, rng=None, compute_dtype=jnp.float32):
    p = _cast(params, compute_dtype)
    return L.dense(p["d1"], x.astype(compute_dtype)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

MNIST_CNN = Model("mnist_cnn", _mnist_init, _mnist_apply, "categorical", 10, adam_like_keras)
CIFAR10_CNN = Model("cifar10_cnn", _cifar_init, _cifar_apply, "categorical", 10,
                    partial(rmsprop_like_keras, 1e-4))
IMDB_CONV1D = Model("imdb_conv1d", _imdb_init, _imdb_apply, "binary", 1, adam_like_keras)
ESC50_CNN = Model("esc50_cnn", _esc50_init, _esc50_apply, "categorical", 50, adam_like_keras)
TITANIC_LOGREG = Model("titanic_logreg", _titanic_init, _titanic_apply, "binary", 1,
                       partial(adam_like_keras, 5e-2))

MODELS = {
    "mnist_cnn": MNIST_CNN,
    "cifar10_cnn": CIFAR10_CNN,
    "imdb_conv1d": IMDB_CONV1D,
    "esc50_cnn": ESC50_CNN,
    "titanic_logreg": TITANIC_LOGREG,
}


# ---------------------------------------------------------------------------
# Analytic forward-pass FLOPs per sample (the MFU-proxy numerator)
# ---------------------------------------------------------------------------

def _conv2d_flops(h_out: int, w_out: int, kh: int, kw: int,
                  cin: int, cout: int) -> int:
    """2 FLOPs (multiply + add) per MAC of a 2-D convolution."""
    return 2 * h_out * w_out * kh * kw * cin * cout


def _dense_flops(n_in: int, n_out: int) -> int:
    return 2 * n_in * n_out


def fwd_flops_per_sample(model_name: str) -> int | None:
    """Analytic forward-pass FLOPs for one sample of the named built-in
    family (matmul/conv MACs x 2; elementwise ops and the embedding gather
    are negligible and excluded). The observability layer multiplies by 3
    for fwd+bwd when turning trained-sample counts into a model-FLOPs rate
    — the conventional conservative training estimate, same convention as
    bench.py's XLA-cost-model line. Returns None for unknown families
    (e.g. test-only custom models), in which case the MFU-proxy row is
    simply omitted."""
    if model_name == "mnist_cnn":
        # 28x28x1: conv3x3->26x26x32, conv3x3->24x24x64, pool -> 12x12x64
        return (_conv2d_flops(26, 26, 3, 3, 1, 32)
                + _conv2d_flops(24, 24, 3, 3, 32, 64)
                + _dense_flops(12 * 12 * 64, 128)
                + _dense_flops(128, 10))
    if model_name == "cifar10_cnn":
        # 32x32x3: conv same 32x32x32, conv 30x30x32, pool 15x15;
        # conv same 15x15x64, conv 13x13x64, pool 6x6
        return (_conv2d_flops(32, 32, 3, 3, 3, 32)
                + _conv2d_flops(30, 30, 3, 3, 32, 32)
                + _conv2d_flops(15, 15, 3, 3, 32, 64)
                + _conv2d_flops(13, 13, 3, 3, 64, 64)
                + _dense_flops(6 * 6 * 64, 512)
                + _dense_flops(512, 10))
    if model_name == "imdb_conv1d":
        # embed gather (no MACs) -> conv1d k3 same over [500, 32] -> pool 250
        return (2 * IMDB_SEQ_LEN * 3 * 32 * 32
                + _dense_flops((IMDB_SEQ_LEN // 2) * 32, 256)
                + _dense_flops(256, 64)
                + _dense_flops(64, 1))
    if model_name == "esc50_cnn":
        # 40x431x1: conv k2 valid + pool2, four stages
        return (_conv2d_flops(39, 430, 2, 2, 1, 16)
                + _conv2d_flops(18, 214, 2, 2, 16, 32)
                + _conv2d_flops(8, 106, 2, 2, 32, 64)
                + _conv2d_flops(3, 52, 2, 2, 64, 128)
                + _dense_flops(128, 50))
    if model_name == "titanic_logreg":
        return _dense_flops(27, 1)
    return None
