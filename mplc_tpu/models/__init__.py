from .core import Model, adam_like_keras, rmsprop_like_keras
from .zoo import (MODELS, MNIST_CNN, CIFAR10_CNN, IMDB_CONV1D, ESC50_CNN,
                  TITANIC_LOGREG)

__all__ = [
    "Model", "adam_like_keras", "rmsprop_like_keras", "MODELS",
    "MNIST_CNN", "CIFAR10_CNN", "IMDB_CONV1D", "ESC50_CNN", "TITANIC_LOGREG",
]
