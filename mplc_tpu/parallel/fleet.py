"""Fleet sweep plane: coalition-axis sharding across OS processes/hosts.

The engine's `coal` mesh axis already shards one process's batches across
its local devices with zero communication; this module is the next rung —
statically partition a WHOLE sweep into W disjoint coalition slices and
run each slice in its own OS process (on its own host, when a fleet
exists), then merge the per-shard results into one sweep with a
machine-checked equality proof. Three rules make the plane trustworthy:

1. **Slice at bucket granularity, never mid-width.** `plan_slices` cuts
   the sweep at the level of the engine's merged slot buckets (the same
   classification `CharacteristicEngine.sweep_plan` uses: singles,
   per-slot-width groups under merge/pow2/exact bucketing, all-dropped
   null coalitions), splitting each bucket contiguously across shards.
   Combined with `CharacteristicEngine.pin_fleet_widths` — which pins
   every shard's batch widths to the FULL sweep's planned widths — every
   shard compiles exactly the same (slot_count, width) programs, so a
   shared persistent compile cache + program-bank manifest serves W-1 of
   the W shards without a single recompile.

2. **Each shard is self-verifying.** A shard runs under
   `MPLC_TPU_DETERMINISTIC_REDUCE=1` (when the spec asks for equality
   proofs) with its own value-provenance ledger
   (`obs/numerics.ValueLedger`) and its own crash-safe journal (the
   engine's checksummed autosave cache). Its LAST act is touching
   `.shardI.done` — the same completion-marker convention
   `scripts/merge_shards.py` established for the grid sharder, so a csv
   present without its marker is never mistaken for a finished shard.

3. **The merge is verified, not assumed.** The coordinator refuses
   partial merges (missing markers), refuses fingerprint mismatches
   (different GAMES), requires the shard slices to be a disjoint cover
   of the requested sweep, and — handed a reference ledger (e.g. the
   1-shard run's) — asserts zero-ulp, tau-b == 1.0 equality through
   `obs/numerics.diff_ledgers`. Linearity you can trust, not assume.

Cross-shard service state (`MPLC_TPU_FLEET_STATE_DIR`): a sharded
`SweepService` deployment publishes each process's queue depth /
admission state into the shared state dir (`publish_shard_state`), and
`cluster_view` aggregates them — the cross-shard queue view the
admission governor's /healthz block and overload hints read, where
previously the governor saw only one process's queue.

CLI:
  python -m mplc_tpu.parallel.fleet --worker SPEC.json --shard I/W \
      --out DIR [--no-ledger]
  python -m mplc_tpu.parallel.fleet --selfcheck [--shards W]
The selfcheck runs a tiny deterministic-reduce sweep at 1 shard and at W
shards (real subprocesses) and exits non-zero unless `diff_ledgers`
reports zero drift and tau-b == 1.0 — the CI fleet smoke.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import re
import subprocess
import sys
import tempfile
import time

from .. import constants

logger = __import__("logging").getLogger("mplc_tpu")

# knob names (constants.ENV_KNOBS registers all three workload-class)
FLEET_SHARDS_ENV = constants.FLEET_SHARDS_ENV
FLEET_STATE_DIR_ENV = constants.FLEET_STATE_DIR_ENV
FLEET_SHARD_ID_ENV = constants.FLEET_SHARD_ID_ENV
# observability-plane knobs (sidecar-class): the coordinator injects the
# first two into every worker env so trace records are correlatable and
# clock-rebaseable; neither changes a single computed number
FLEET_RUN_ID_ENV = constants.FLEET_RUN_ID_ENV
FLEET_COORD_TS_ENV = constants.FLEET_COORD_TS_ENV


class FleetError(RuntimeError):
    """Base class for fleet-plane failures."""


class FleetMergeError(FleetError):
    """The per-shard results cannot be merged into one sweep: missing
    completion markers (a shard still running or crashed), overlapping
    or non-covering slices, or fingerprint mismatches (different
    games)."""


# ---------------------------------------------------------------------------
# sweep spec: everything a worker process needs to rebuild the same game
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetSpec:
    """A self-contained sweep description, JSON round-trippable so a
    worker process reconstructs bit-identically the game the coordinator
    described (the engine's data digest catches any divergence)."""
    dataset: str = "titanic"
    partners: int = 3
    epochs: int = 2
    dtype: str = "float32"
    minibatch_count: int = 2
    gradient_updates_per_pass: int = 3
    seed: int = 0
    # None = the full powerset sweep (contrib.shapley.powerset_order)
    subsets: "list | None" = None
    # equality mode: shards run under MPLC_TPU_DETERMINISTIC_REDUCE=1 so
    # the merged ledger is bit-comparable across shard counts/topologies
    deterministic: bool = True
    # pin every shard's bucket widths to the full sweep's plan (identical
    # programs across shards -> shared bank/manifest serves W-1 shards)
    pin_widths: bool = True

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "FleetSpec":
        doc = json.loads(text)
        doc.pop("amounts", None)  # legacy field tolerance
        return cls(**doc)

    def all_subsets(self) -> list:
        if self.subsets is not None:
            return [tuple(sorted(int(i) for i in s)) for s in self.subsets]
        from ..contrib.shapley import powerset_order
        return list(powerset_order(self.partners))

    def build_scenario(self):
        """The bench-shaped scenario (bench._amounts proportions), built
        identically in coordinator and every worker."""
        from ..scenario import Scenario
        n = self.partners
        if n == 3:
            amounts = [0.4, 0.3, 0.3]
        else:
            raw = [float(i + 1) for i in range(n)]
            amounts = [x / sum(raw) for x in raw]
        sc = Scenario(partners_count=n, amounts_per_partner=amounts,
                      dataset_name=self.dataset,
                      multi_partner_learning_approach="fedavg",
                      aggregation_weighting="data-volume",
                      epoch_count=self.epochs,
                      minibatch_count=self.minibatch_count,
                      gradient_updates_per_pass_count=(
                          self.gradient_updates_per_pass),
                      is_early_stopping=False, compute_dtype=self.dtype,
                      experiment_path=tempfile.gettempdir(),
                      is_dry_run=True, seed=self.seed)
        sc.instantiate_scenario_partners()
        sc.split_data(is_logging_enabled=False)
        sc.compute_batch_sizes()
        sc.data_corruption()
        return sc


# ---------------------------------------------------------------------------
# slice planning: bucket-granular, deterministic, disjoint cover
# ---------------------------------------------------------------------------

def plan_slices(engine, subsets, n_shards: int) -> list:
    """Partition `subsets` into `n_shards` disjoint slices, slicing at
    the level of the engine's slot buckets (the same classification
    `sweep_plan`/evaluate use) so no bucket is split mid-width: every
    shard receives a contiguous chunk of EACH bucket, and — with
    `pin_fleet_widths` — runs it at the full sweep's batch width.
    Deterministic in (subsets order, bucketing mode, n_shards); the
    slices' union is exactly the stable-unique subset list."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    keys = list(dict.fromkeys(
        tuple(sorted(int(i) for i in s)) for s in subsets))
    dropped = getattr(engine, "_forever_dropped", frozenset())
    if dropped:
        lens = {k: len(engine._effective_subset(k)) for k in keys}
    else:
        lens = {k: len(k) for k in keys}
    nulls = [k for k in keys if lens[k] == 0]    # stored v=0, no dispatch
    singles = [k for k in keys if lens[k] == 1]
    multis = [k for k in keys if lens[k] > 1]
    buckets = []
    if nulls:
        buckets.append(nulls)
    if singles:
        buckets.append(singles)
    if multis:
        if getattr(engine, "_use_slots", False):
            buckets.extend(group for _w, group in engine._slot_buckets(multis))
        else:
            buckets.append(multis)
    slices = [[] for _ in range(n_shards)]
    for bucket in buckets:
        n = len(bucket)
        for i in range(n_shards):
            slices[i].extend(bucket[i * n // n_shards:
                                    (i + 1) * n // n_shards])
    return slices


# ---------------------------------------------------------------------------
# per-shard execution (shared by the in-process path and the CLI worker)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _env_overlay(overrides: dict):
    """Temporarily set/unset environment keys (None = unset). The engine
    reads its mode knobs at construction time, so the in-process shard
    path needs exactly this window; the subprocess path passes a real
    environment instead."""
    saved = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _shard_paths(out_dir: str, shard: int) -> dict:
    return {
        "result": os.path.join(out_dir, f"result_shard{shard}.json"),
        "cache": os.path.join(out_dir, f"cache_shard{shard}.json"),
        "ledger": os.path.join(out_dir, f"ledger_shard{shard}.json"),
        "done": os.path.join(out_dir, f".shard{shard}.done"),
    }


def run_shard(spec: FleetSpec, shard: int, shards: int, out_dir: str,
              ledger: bool = True) -> dict:
    """Execute one shard's slice to completion: build the game, pin the
    full sweep's bucket widths, evaluate the slice under the spec's
    reduction mode with a per-shard value ledger + crash-safe journal
    (the engine's checksummed autosave cache), and write
    `result_shardI.json` / `cache_shardI.json` / `ledger_shardI.json`.
    Touches `.shardI.done` LAST — the merge refuses shards without it."""
    if not 0 <= shard < shards:
        raise ValueError(f"shard index {shard} outside 0..{shards - 1}")
    os.makedirs(out_dir, exist_ok=True)
    paths = _shard_paths(out_dir, shard)
    # stale artifacts from a previous run into the same dir must not
    # survive: a leftover marker could bless a half-written result
    # (main.py's grid-shard rule), and a leftover ledger/result from an
    # earlier run would be merged as if THIS run produced it (e.g. a
    # ledger=False rerun silently inheriting the old ledger's values)
    for key in ("done", "result", "ledger"):
        with contextlib.suppress(OSError):
            os.remove(paths[key])
    # clock handshake: the coordinator stamped its own clock into the
    # worker env at spawn; we echo it back beside our own clock readings
    # (start here, end at result build) so fleet_trace_merge can rebase
    # this shard's span stream onto the coordinator clock (midpoint rule)
    worker_start_ts = time.time()
    coord_ts = None
    with contextlib.suppress(TypeError, ValueError):
        raw = os.environ.get(FLEET_COORD_TS_ENV)
        coord_ts = float(raw) if raw else None
    run_id = os.environ.get(FLEET_RUN_ID_ENV)
    from ..obs import trace as obs_trace
    shard_span = obs_trace.start_span("fleet.shard_run", shard=shard,
                                      shards=shards, run=run_id or "")
    t0 = time.perf_counter()
    env = {"MPLC_TPU_DETERMINISTIC_REDUCE": "1" if spec.deterministic
           else None,
           "MPLC_TPU_NUMERICS_LEDGER": paths["ledger"] if ledger else None}
    from ..obs import metrics as obs_metrics

    def _counters():
        snap = obs_metrics.snapshot().get("counters", {})
        return {k: snap.get(k, 0) for k in
                ("bank.hits", "bank.compiles", "trainer.compiles",
                 "engine.batches")}

    before = _counters()
    from ..utils import compile_cache_entries
    cache_dir = os.environ.get(constants.COMPILE_CACHE_DIR_ENV)
    cache_before = (compile_cache_entries(cache_dir)
                    if cache_dir else None)
    with _env_overlay(env):
        sc = spec.build_scenario()
        from ..contrib.engine import CharacteristicEngine
        engine = CharacteristicEngine(sc)
    all_subsets = spec.all_subsets()
    if spec.pin_widths:
        engine.pin_fleet_widths(all_subsets)
    # cross-process program reuse accounting: how many of the FULL
    # sweep's programs the shared bank manifest already held when this
    # shard started (every one of them deserializes from the persistent
    # compile cache instead of recompiling — the fleet's
    # "W-1 shards compile nothing" claim, measured per shard)
    plan = engine.sweep_plan(all_subsets)
    manifest_hits = 0
    if engine.program_bank is not None and plan:
        held = engine.program_bank.persistent_keys()
        manifest_hits = sum(
            1 for pipe, slot, width in plan
            if engine.program_bank.program_key(pipe, slot, width) in held)
    my_slice = plan_slices(engine, all_subsets, shards)[shard]
    engine.autosave_path = paths["cache"]   # per-shard crash journal
    # program warm-up OUTSIDE the timed sweep, mirroring bench
    # _warm_engine's skip path: acquire every planned program now — a
    # manifest-held program deserializes from the shared persistent
    # cache, the prime shard compiles — so the timed sweep pays
    # dispatch+compute only, the same timing-excludes-compilation
    # discipline every bench config uses. The warm-up seconds are
    # reported (warmup_s), never hidden.
    t_warm = time.perf_counter()
    if engine.program_bank is not None:
        for pipe, slot, width in plan:
            engine.program_bank.acquire(pipe, slot, width)
    warmup_s = time.perf_counter() - t_warm
    # the sweep proper is timed separately from shard STARTUP (scenario
    # build, data generation, engine construction): startup happens once
    # per resident worker and is excluded from every bench config's
    # timed region by the warm-up discipline, so the fleet's scaling
    # number must not smear it into the per-shard sweep time — both are
    # reported, neither is hidden
    t_sweep = time.perf_counter()
    engine.evaluate(my_slice)
    sweep_s = time.perf_counter() - t_sweep
    if engine.numerics_ledger is not None:
        engine.numerics_ledger.save()
    engine.save_cache(paths["cache"])
    after = _counters()
    wall = time.perf_counter() - t0
    shard_span.end()   # root span: the flow-link target in the timeline
    worker_end_ts = time.time()
    result = {
        "shard": shard,
        "shards": shards,
        "spec": dataclasses.asdict(spec),
        # the game's engine fingerprint, so the coordinator can stamp
        # the merged cache without rebuilding the scenario + engine
        "fingerprint": engine._fingerprint(),
        "subsets": [list(s) for s in my_slice],
        "values": [[list(s), float(engine.charac_fct_values[s])]
                   for s in my_slice],
        "wallclock_s": wall,
        "sweep_s": sweep_s,
        "warmup_s": warmup_s,
        "setup_s": wall - sweep_s - warmup_s,
        "devices": _local_device_count(),
        "deterministic": bool(spec.deterministic),
        "counters": {k: after[k] - before[k] for k in after},
        "programs_planned": len(plan),
        "manifest_hits": manifest_hits,
        "compile_cache_new_entries": (
            (compile_cache_entries(cache_dir) or 0) - (cache_before or 0)
            if cache_dir and cache_before is not None else None),
        "widths": sorted({w for (_p, _s), w in
                          (engine._fleet_widths or {}).items()})
        if engine._fleet_widths else [],
        # fleet trace context + clock echo: the coordinator's spawn-time
        # clock reading (coord_spawn_ts) echoed beside this worker's own
        # start/end readings — with the coordinator's done-seen time
        # (fleet_trace_manifest.json) these four timestamps give
        # scripts/fleet_trace_merge.py a midpoint clock-offset estimate
        # per shard, robust to cross-host skew
        "fleet": {"run_id": run_id, "shard_id":
                  os.environ.get(FLEET_SHARD_ID_ENV)},
        "clock": {"coord_spawn_ts": coord_ts,
                  "worker_start_ts": worker_start_ts,
                  "worker_end_ts": worker_end_ts},
        # this process's full metrics snapshot (shared log2 buckets):
        # what the fleet collector's serverless path merges into the
        # cluster rollup. Meaningful per-shard in subprocess fleets
        # (fresh registry per worker); inproc shards share one registry,
        # so their snapshots are cumulative, not disjoint.
        "metrics": obs_metrics.snapshot(),
    }
    _atomic_json(paths["result"], result)
    # LAST act: the completion marker (crash before this line = no merge)
    with open(paths["done"], "w") as f:
        f.write(str(int(time.time())))
    return result


def _local_device_count() -> int:
    try:
        import jax
        return len(jax.devices())
    except Exception:
        return 0


def _atomic_json(path: str, doc: dict) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# merging: disjoint-cover check, ledger union, rebuilt increments
# ---------------------------------------------------------------------------

def merge_ledgers(docs: list) -> dict:
    """Union W shard ledgers (to_doc() dicts) into one merged ledger doc.
    Refuses fingerprint mismatches (different games) and overlapping
    subset keys (a slice bug — two shards trained the same coalition)."""
    if not docs:
        raise FleetMergeError("no shard ledgers to merge")
    fps = {d.get("engine_fingerprint") for d in docs}
    if len(fps) != 1:
        raise FleetMergeError(
            f"shard ledgers carry {len(fps)} distinct engine fingerprints "
            f"({sorted(str(f)[:16] for f in fps)}) — these are different "
            "games and must not be merged")
    entries: dict = {}
    for i, d in enumerate(docs):
        for k, e in (d.get("entries") or {}).items():
            if k in entries:
                raise FleetMergeError(
                    f"subset {k} appears in more than one shard ledger "
                    f"(shard {i} overlaps an earlier slice)")
            entries[k] = e
    meta = dict(docs[0].get("meta") or {})
    meta.update(fleet_shards=len(docs), merged=True)
    return {"schema": docs[0].get("schema", 1),
            "engine_fingerprint": docs[0].get("engine_fingerprint"),
            "meta": meta, "entries": entries}


def _rebuild_increments(values: dict, partners_count: int) -> list:
    """The engine's marginal-increment bookkeeping, recomputed over the
    MERGED memo (per-shard increment dicts are incomplete: a pair split
    across shards contributes to neither side's bookkeeping)."""
    inc = [dict() for _ in range(partners_count)]
    for subset, v in values.items():
        sset = set(subset)
        for i in range(partners_count):
            if i in sset:
                without = tuple(sorted(sset - {i}))
                if without in values:
                    inc[i][without] = v - values[without]
    return inc


def write_cache_doc(path: str, fingerprint: dict, values: dict,
                    partners_count: int) -> None:
    """Persist a merged memo in the engine's checksummed cache format
    (`CharacteristicEngine.load_cache` accepts it), increments rebuilt
    over the merged value set."""
    import hashlib
    payload = {
        "fingerprint": fingerprint,
        "first_charac_fct_calls_count": len(values),
        "charac_fct_values": [[list(k), v] for k, v in values.items()],
        "increments_values": [
            [[list(k), v] for k, v in d.items()]
            for d in _rebuild_increments(values, partners_count)],
    }
    body = json.dumps(payload)
    digest = hashlib.sha256(body.encode()).hexdigest()
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write('{"payload_sha256": "%s", %s' % (digest, body[1:]))
    os.replace(tmp, path)


@dataclasses.dataclass
class FleetResult:
    values: dict                 # {subset tuple: v(S)} over the whole sweep
    ledger: "dict | None"        # merged ledger doc (None = ledger off)
    shard_reports: list          # per-shard result_shardI.json docs
    wallclock_s: float           # coordinator wall: spawn -> merge done
    per_shard_wall_s: list       # each shard's own total wall-clock
    out_dir: str
    diff: "dict | None" = None   # diff_ledgers vs the reference, if given
    # each shard's sweep-only wall-clock (startup — scenario/data/engine
    # build, once per resident worker — reported separately in the shard
    # reports as setup_s); max() over this is the fleet's critical path
    # under the bench's timing-excludes-warm-up discipline
    per_shard_sweep_s: "list | None" = None


def merge_shard_results(spec: FleetSpec, shards: int, out_dir: str,
                        force: bool = False) -> tuple:
    """Read + validate the W shards' outputs. Returns
    (values, merged_ledger_doc_or_None, shard_reports). Refuses missing
    `.shardI.done` markers (unless `force`), non-covering or overlapping
    slices, and mismatched ledger fingerprints."""
    missing = [i for i in range(shards)
               if not os.path.exists(_shard_paths(out_dir, i)["done"])]
    if missing and not force:
        raise FleetMergeError(
            f"{out_dir} has no done markers for shards {missing} — those "
            "workers are still running or crashed (result presence is not "
            "completion); force=True to merge anyway")
    reports = []
    values: dict = {}
    ledger_docs = []
    for i in range(shards):
        paths = _shard_paths(out_dir, i)
        if not os.path.exists(paths["result"]):
            if force:
                continue
            raise FleetMergeError(f"shard {i} left no result file "
                                  f"({paths['result']})")
        with open(paths["result"]) as f:
            rep = json.load(f)
        reports.append(rep)
        for s, v in rep["values"]:
            key = tuple(int(x) for x in s)
            if key in values:
                raise FleetMergeError(
                    f"subset {key} evaluated by more than one shard "
                    f"(shard {i} overlaps an earlier slice)")
            values[key] = float(v)
        if os.path.exists(paths["ledger"]):
            with open(paths["ledger"]) as f:
                ledger_docs.append(json.load(f))
    expected = set(spec.all_subsets())
    if not force and set(values) != expected:
        short = sorted(expected - set(values))[:8]
        raise FleetMergeError(
            f"merged shard values do not cover the sweep: "
            f"{len(values)}/{len(expected)} subsets (first missing: "
            f"{short})")
    merged = merge_ledgers(ledger_docs) if ledger_docs else None
    return values, merged, reports


def run_fleet(spec: FleetSpec, shards: int, out_dir: str,
              inproc: bool = False, devices_per_shard: "int | None" = None,
              env: "dict | None" = None,
              per_shard_env: "dict | None" = None,
              ledger: bool = True, timeout: float = 3600.0,
              concurrent: bool = True,
              verify_against: "dict | str | None" = None) -> FleetResult:
    """Run a W-shard fleet sweep and merge it.

    `inproc=True` executes the shards sequentially in this process
    (tests; the slice/merge/equality machinery is identical, only the
    process boundary is skipped). Otherwise each shard is a subprocess
    of this interpreter running `-m mplc_tpu.parallel.fleet --worker`,
    launched concurrently (`concurrent=False` runs them one at a time —
    the honest mode on a host with fewer cores than shards, where
    concurrent workers would only time-slice; per-shard wall-clocks
    then measure each shard's real work and `per_shard_wall_s`'s max is
    the fleet's critical path); `devices_per_shard` forces the CPU-mesh
    size per worker (`--xla_force_host_platform_device_count`), and
    `per_shard_env` ({shard_index: {KEY: value}}) injects per-shard
    knobs (e.g. a fault plan on one shard). `verify_against` (a ledger
    doc or path) asserts the merged ledger diffs CLEAN — zero ulp drift,
    tau-b 1.0 — against the reference and raises FleetMergeError
    otherwise."""
    from ..obs import trace as obs_trace
    os.makedirs(out_dir, exist_ok=True)
    run_id = _mint_run_id()
    manifest = {"run_id": run_id, "shards": shards,
                "coordinator_pid": os.getpid(), "inproc": bool(inproc),
                "spawn_ts": {}, "done_seen_ts": {}}
    coord_records: list = []
    t0 = time.perf_counter()
    try:
        with obs_trace.collect() as coord_records, \
                _env_overlay({FLEET_RUN_ID_ENV: run_id}):
            result = _run_fleet_traced(
                spec, shards, out_dir, inproc, devices_per_shard, env,
                per_shard_env, ledger, timeout, concurrent,
                verify_against, run_id, manifest, t0, obs_trace)
    except FleetError as e:
        # one postmortem artifact per failed run: trace/manifest first
        # (the incident's trace tails read the shard files; a later
        # manual fleet_trace_merge over the out_dir needs both)
        _write_coordinator_trace(out_dir, coord_records)
        _atomic_json(os.path.join(out_dir, "fleet_trace_manifest.json"),
                     manifest)
        _write_incident(out_dir, run_id, shards,
                        reason=("merge_refused"
                                if isinstance(e, FleetMergeError)
                                else "shard_failure"),
                        error=e,
                        failed=getattr(e, "failed_shards", None))
        raise
    _write_coordinator_trace(out_dir, coord_records)
    _atomic_json(os.path.join(out_dir, "fleet_trace_manifest.json"),
                 manifest)
    return result


def _run_fleet_traced(spec, shards, out_dir, inproc, devices_per_shard,
                      env, per_shard_env, ledger, timeout, concurrent,
                      verify_against, run_id, manifest, t0,
                      obs_trace) -> "FleetResult":
    """run_fleet's traced body (split out so the wrapper can write the
    coordinator trace + clock manifest and the incident bundle on BOTH
    exit paths without a try/finally pyramid)."""
    with obs_trace.span("fleet.sweep", shards=shards,
                        inproc=bool(inproc),
                        devices_per_shard=devices_per_shard, run=run_id):
        if inproc:
            for i in range(shards):
                spawn_ts = time.time()
                manifest["spawn_ts"][str(i)] = spawn_ts
                overlay = _shard_obs_env(out_dir, run_id, i, spawn_ts)
                overlay.update((per_shard_env or {}).get(i) or {})
                with _env_overlay(overlay):
                    rep = run_shard(spec, i, shards, out_dir,
                                    ledger=ledger)
                manifest["done_seen_ts"][str(i)] = time.time()
                obs_trace.event("fleet.shard", dur=rep["wallclock_s"],
                                shard=i, shards=shards,
                                wallclock_s=rep["wallclock_s"],
                                coalitions=len(rep["subsets"]))
        else:
            _run_fleet_subprocess(spec, shards, out_dir,
                                  devices_per_shard, env, per_shard_env,
                                  ledger, timeout, concurrent,
                                  run_id=run_id, manifest=manifest)
        values, merged, reports = merge_shard_results(spec, shards, out_dir)
        if merged is not None:
            _atomic_json(os.path.join(out_dir, "ledger_merged.json"),
                         merged)
        if reports:
            # the shard workers already computed the fingerprint —
            # stamping the merged cache must not rebuild the whole
            # scenario + engine in the coordinator
            fp = reports[0].get("fingerprint")
            if fp is None:
                with _env_overlay(
                        {"MPLC_TPU_DETERMINISTIC_REDUCE":
                         "1" if spec.deterministic else None}):
                    fp = _spec_fingerprint(spec)
            if fp is not None:
                write_cache_doc(os.path.join(out_dir, "cache_merged.json"),
                                fp, values, spec.partners)
        diff = None
        if verify_against is not None:
            if merged is None:
                # the caller asked for an equality proof; a run with no
                # ledgers has no bits to compare — that is a refusal,
                # never a silent pass
                raise FleetMergeError(
                    "verify_against given but the fleet run produced no "
                    "merged ledger (ledger=False, or no shard wrote "
                    "one) — nothing was verified")
            if isinstance(verify_against, str):
                with open(verify_against) as f:
                    verify_against = json.load(f)
            from ..obs.numerics import diff_ledgers
            diff = diff_ledgers(verify_against, merged)
            diff.pop("per_subset", None)
            expected_n = len(spec.all_subsets())
            if (diff["drift"] or not diff["comparable"]
                    or diff["common"] != expected_n):
                raise FleetMergeError(
                    f"fleet merge FAILED verification vs the reference "
                    f"ledger: comparable={diff['comparable']} "
                    f"drift={diff['drift']} "
                    f"covered={diff['common']}/{expected_n} subsets "
                    f"ulp={diff['ulp']} tau={diff['kendall_tau']}")
        wall = time.perf_counter() - t0
        obs_trace.event("fleet.merge", shards=shards,
                        coalitions=len(values),
                        verified=verify_against is not None,
                        wallclock_s=wall)
    return FleetResult(values=values, ledger=merged,
                       shard_reports=reports, wallclock_s=wall,
                       per_shard_wall_s=[r["wallclock_s"] for r in reports],
                       out_dir=out_dir, diff=diff,
                       per_shard_sweep_s=[r.get("sweep_s",
                                                r["wallclock_s"])
                                          for r in reports])


def _spec_fingerprint(spec: FleetSpec) -> "dict | None":
    """The engine fingerprint of the spec's game, for the merged cache
    doc. Rebuilds the scenario+engine (cheap for the tiny fleet games;
    the coordinator usually ran a shard in-process anyway and the
    trainer registry caches the compiles). None on any failure — the
    merged cache is a convenience artifact, never worth failing a merge
    that already verified."""
    try:
        sc = spec.build_scenario()
        from ..contrib.engine import CharacteristicEngine
        return CharacteristicEngine(sc)._fingerprint()
    except Exception as e:  # noqa: BLE001 — convenience artifact only
        logger.warning("fleet: merged-cache fingerprint unavailable (%s)", e)
        return None


def worker_env(base: "dict | None" = None,
               devices: "int | None" = None,
               extra: "dict | None" = None) -> dict:
    """A worker subprocess environment: the caller's env with the CPU
    mesh size forced (when `devices` is given) and per-shard overrides
    applied. The force flag REPLACES any inherited one — a worker must
    never silently inherit the parent's 8-device test mesh."""
    env = dict(os.environ if base is None else base)
    if devices is not None:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                            f"count={devices}").strip()
        env["JAX_PLATFORMS"] = "cpu"
    for k, v in (extra or {}).items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = str(v)
    return env


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def worker_argv(spec_path: str, shard: int, shards: int, out_dir: str,
                ledger: bool = True) -> list:
    """The worker CLI invocation — THE one place the subprocess protocol
    (module path + flag shape) lives; the coordinator and the bench's
    compile-prime both build their commands here."""
    return ([sys.executable, "-m", "mplc_tpu.parallel.fleet",
             "--worker", spec_path, "--shard", f"{shard}/{shards}",
             "--out", out_dir] + ([] if ledger else ["--no-ledger"]))


def run_worker_subprocess(spec: FleetSpec, shard: int, shards: int,
                          out_dir: str,
                          devices: "int | None" = None,
                          env: "dict | None" = None,
                          ledger: bool = True,
                          timeout: float = 3600.0) -> None:
    """Run ONE shard worker as a subprocess and wait for it (the bench's
    compile-prime; the coordinator's multi-worker launch shares the same
    argv/env builders). Raises FleetError on a non-zero exit, with the
    worker log tail."""
    os.makedirs(out_dir, exist_ok=True)
    spec_path = os.path.join(out_dir, "fleet_spec.json")
    with open(spec_path, "w") as f:
        f.write(spec.to_json())
    wenv = worker_env(env, devices)
    wenv.setdefault("PYTHONPATH", _repo_root())
    log_path = os.path.join(out_dir, f"worker_shard{shard}.log")
    with open(log_path, "w") as log:
        try:
            rc = subprocess.run(
                worker_argv(spec_path, shard, shards, out_dir, ledger),
                env=wenv, stdout=log, stderr=subprocess.STDOUT,
                cwd=_repo_root(), timeout=timeout).returncode
        except subprocess.TimeoutExpired:
            rc = -9
    if rc != 0:
        tail = ""
        with contextlib.suppress(OSError):
            with open(log_path) as f:
                tail = f.read()[-2000:]
        raise FleetError(
            f"fleet worker shard {shard}/{shards} failed rc={rc}: "
            f"...{tail[-400:]}")


def _run_fleet_subprocess(spec, shards, out_dir, devices_per_shard, env,
                          per_shard_env, ledger, timeout,
                          concurrent=True, run_id=None,
                          manifest=None) -> None:
    from ..obs import trace as obs_trace
    spec_path = os.path.join(out_dir, "fleet_spec.json")
    with open(spec_path, "w") as f:
        f.write(spec.to_json())
    repo_root = _repo_root()
    deadline = time.monotonic() + timeout

    def _spawn(i):
        spawn_ts = time.time()
        if manifest is not None:
            manifest["spawn_ts"][str(i)] = spawn_ts
        # observability env first, caller's per-shard knobs LAST — an
        # explicit per-shard override (a test pointing the trace file
        # elsewhere) must beat the coordinator's defaults
        extra = _shard_obs_env(out_dir, run_id, i, spawn_ts)
        extra.update((per_shard_env or {}).get(i) or {})
        wenv = worker_env(env, devices_per_shard, extra)
        wenv.setdefault("PYTHONPATH", repo_root)
        log_path = os.path.join(out_dir, f"worker_shard{i}.log")
        log = open(log_path, "w")
        return (i, spawn_ts, subprocess.Popen(
            worker_argv(spec_path, i, shards, out_dir, ledger),
            env=wenv, stdout=log, stderr=subprocess.STDOUT,
            cwd=repo_root), log, log_path)

    def _wait(i, spawn_ts, p, log, log_path):
        left = max(1.0, deadline - time.monotonic())
        try:
            rc = p.wait(left)
        except subprocess.TimeoutExpired:
            p.kill()
            rc = -9
        log.close()
        done_ts = time.time()
        if manifest is not None:
            manifest["done_seen_ts"][str(i)] = done_ts
        # dispatch anchor for the merged timeline: backdated to spawn
        # time, so the flow arrow to the shard's root span starts where
        # the coordinator actually handed the work off
        obs_trace.event("fleet.shard", dur=done_ts - spawn_ts, shard=i,
                        shards=shards, wallclock_s=done_ts - spawn_ts,
                        rc=rc)
        if rc == 0:
            return None
        tail = ""
        with contextlib.suppress(OSError):
            with open(log_path) as f:
                tail = f.read()[-2000:]
        return (i, rc, tail)

    failed = []
    if concurrent:
        procs = [_spawn(i) for i in range(shards)]
        failed = [f for f in (_wait(*p) for p in procs) if f is not None]
    else:
        for i in range(shards):
            f = _wait(*_spawn(i))
            if f is not None:
                failed.append(f)
    if failed:
        detail = "; ".join(f"shard {i} rc={rc}: ...{tail[-400:]}"
                           for i, rc, tail in failed)
        err = FleetError(
            f"{len(failed)} fleet worker(s) failed: {detail}")
        err.failed_shards = [i for i, _rc, _tail in failed]
        raise err


def _mint_run_id() -> str:
    """A collision-resistant fleet run id (hex, no wall-clock coupling):
    the correlation key stamped into every coordinator AND worker trace
    record for one run_fleet call."""
    import secrets
    return f"fleet-{secrets.token_hex(6)}"


def _shard_obs_env(out_dir: str, run_id: str, shard: int,
                   spawn_ts: float) -> dict:
    """The observability overlay injected beside the ledger/reduce env:
    trace context (run id + shard id, stamped on every record by
    obs/trace._emit), the coordinator's spawn-time clock reading (echoed
    back in the result JSON for the clock-offset handshake), a per-shard
    trace file and a per-shard flight-recorder dir — both inside the
    fleet out_dir, where the merge script and the incident bundler
    expect them. Chrome conversion is left to the coordinator: one
    merged timeline, not W partial ones."""
    return {
        FLEET_RUN_ID_ENV: run_id,
        FLEET_SHARD_ID_ENV: f"shard{shard}",
        FLEET_COORD_TS_ENV: repr(spawn_ts),
        "MPLC_TPU_TRACE_FILE":
            os.path.join(out_dir, f"trace_shard{shard}.jsonl"),
        "MPLC_TPU_FLIGHT_RECORDER_DIR":
            os.path.join(out_dir, f"flight_shard{shard}"),
        "MPLC_TPU_CHROME_TRACE_FILE": None,
    }


def _write_coordinator_trace(out_dir: str, records: list) -> None:
    """Persist the coordinator's own span stream (fleet.sweep,
    fleet.shard dispatch events, fleet.merge) as trace_coordinator.jsonl.
    Records stamped with a `fleet_shard` are dropped: on the inproc path
    the collector saw the shards' records too, and those already live in
    the per-shard trace files — the merge script must not see them
    twice."""
    try:
        path = os.path.join(out_dir, "trace_coordinator.jsonl")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            for r in records:
                if "fleet_shard" not in r:
                    f.write(json.dumps(r) + "\n")
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError) as e:
        logger.warning("fleet: coordinator trace write failed: %s", e)


def _tail_lines(path: str, n: int = 200) -> list:
    try:
        with open(path) as f:
            return f.readlines()[-n:]
    except OSError:
        return []


def _ledger_digest(path: str) -> "dict | None":
    """A small content digest of one shard's value-provenance ledger —
    enough for a postmortem to pin WHICH game/values the shard claimed
    without shipping the whole ledger into the bundle."""
    try:
        import hashlib
        with open(path, "rb") as f:
            body = f.read()
        doc = json.loads(body)
        return {"path": path, "sha256": hashlib.sha256(body).hexdigest(),
                "entries": len(doc.get("entries") or {}),
                "engine_fingerprint": doc.get("engine_fingerprint"),
                "reduction_mode": (doc.get("meta") or {}).get(
                    "reduction_mode")}
    except (OSError, ValueError):
        return None


def _write_incident(out_dir: str, run_id: str, shards: int, reason: str,
                    error: BaseException,
                    failed: "list | None") -> "str | None":
    """Gather ONE timestamped postmortem dir for a failed fleet run:
    per failed shard its flight-recorder dumps, trace tail, worker-log
    tail and ledger digest, plus the cluster snapshot — instead of W
    scattered artifacts an operator has to correlate by hand at 3am.
    Never raises; returns the incident dir (or None)."""
    try:
        from ..obs import metrics as obs_metrics
        from ..obs import trace as obs_trace
        if not failed:
            # merge refusals don't always name a shard: blame the shards
            # without completion markers, else keep every shard's story
            failed = [i for i in range(shards) if not os.path.exists(
                _shard_paths(out_dir, i)["done"])] or list(range(shards))
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        inc_dir = os.path.join(
            out_dir, f"incident_{stamp}_{run_id.split('-')[-1]}")
        os.makedirs(inc_dir, exist_ok=True)
        bundle = {"run_id": run_id, "reason": reason,
                  "error": str(error)[:4000], "ts": time.time(),
                  "shards": shards, "failed_shards": sorted(failed),
                  "shard_artifacts": {}}
        import shutil
        for i in sorted(failed):
            art: dict = {}
            fdir = os.path.join(out_dir, f"flight_shard{i}")
            dumps = []
            if os.path.isdir(fdir):
                for name in sorted(os.listdir(fdir)):
                    if name.startswith("mplc_flight_"):
                        with contextlib.suppress(OSError):
                            shutil.copy2(os.path.join(fdir, name),
                                         os.path.join(inc_dir, name))
                            dumps.append(name)
            art["flight_dumps"] = dumps
            tail = _tail_lines(
                os.path.join(out_dir, f"trace_shard{i}.jsonl"))
            if tail:
                tail_name = f"trace_tail_shard{i}.jsonl"
                with open(os.path.join(inc_dir, tail_name), "w") as f:
                    f.writelines(tail)
                art["trace_tail"] = tail_name
                art["trace_tail_records"] = len(tail)
            log_tail = _tail_lines(
                os.path.join(out_dir, f"worker_shard{i}.log"), 40)
            if log_tail:
                art["log_tail"] = "".join(log_tail)[-2000:]
            art["ledger_digest"] = _ledger_digest(
                _shard_paths(out_dir, i)["ledger"])
            bundle["shard_artifacts"][str(i)] = art
        from ..obs import fleet_view
        bundle["cluster"] = fleet_view.cluster_snapshot(
            out_dir=out_dir,
            state_dir=os.environ.get(FLEET_STATE_DIR_ENV))
        _atomic_json(os.path.join(inc_dir, "incident.json"), bundle)
        obs_metrics.counter("fleet.incidents").inc()
        obs_trace.event("fleet.incident", run=run_id, reason=reason,
                        failed_shards=len(failed), path=inc_dir)
        logger.warning("fleet: incident bundle written to %s", inc_dir)
        return inc_dir
    except Exception as e:  # noqa: BLE001 — postmortems must not mask
        logger.error("fleet: incident bundle failed: %s", e)
        return None


# ---------------------------------------------------------------------------
# cross-shard service state (the admission governor's fleet view)
# ---------------------------------------------------------------------------

_publish_warned = False


def publish_shard_state(state_dir: str, shard_id: str,
                        payload: dict) -> None:
    """Atomically publish one service shard's queue/admission snapshot
    into the shared fleet state dir. Never raises — a full disk must not
    take down the service whose state it merely mirrors — but failures
    are COUNTED (`fleet.state_publish_errors`, surfaced in /varz) and
    warned once per process, mirroring sample_device_memory: a fleet
    whose state publishing silently stopped looks exactly like a healthy
    shard that went quiet, and the cluster view would flag it stale with
    nobody knowing why."""
    global _publish_warned
    try:
        os.makedirs(state_dir, exist_ok=True)
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", str(shard_id))
        _atomic_json(os.path.join(state_dir, f"shard_{safe}.json"),
                     {**payload, "shard": str(shard_id),
                      "ts": time.time()})
    except Exception as e:  # noqa: BLE001 — mirror, never a crash
        from ..obs import metrics as obs_metrics
        obs_metrics.counter("fleet.state_publish_errors").inc()
        if not _publish_warned:
            _publish_warned = True
            logger.warning(
                "fleet: shard-state publish to %r failed (%s); further "
                "failures are counted in fleet.state_publish_errors "
                "without logging", state_dir, e)


def cluster_view(state_dir: str, stale_sec: "float | None" = None,
                 include_metrics: bool = False) -> dict:
    """Aggregate every shard's published state: per-shard rows (stale
    ones flagged, not dropped — a wedged shard's last word is evidence)
    plus cluster totals the admission governor and /healthz expose.
    `least_loaded` names the live shard with the shallowest queue — the
    redirect hint an overloaded shard hands back to fleet routers; a
    STALE shard (state file older than `stale_sec`, default
    `MPLC_TPU_FLEET_STALE_SEC` or 30 s) is excluded from the live set
    and can never be recommended — a dead shard's last published queue
    depth was probably 0, which is exactly the bait a naive
    least-loaded rule would take. A shard that published `closed: true`
    (shutting down — it may still be draining, but accepts nothing) is
    excluded the same way, so a router is never redirected at a closing
    service."""
    if stale_sec is None:
        from .. import constants as _c
        stale_sec = _c._env_nonneg_float(_c.FLEET_STALE_SEC_ENV, 30.0)
    shards = {}
    now = time.time()
    try:
        names = sorted(os.listdir(state_dir))
    except OSError:
        names = []
    for name in names:
        if not (name.startswith("shard_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(state_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        # clamp: a publisher whose clock runs AHEAD of ours (cross-host
        # skew) must read as freshly published (age 0, live), not as a
        # negative age that could flap stale under a naive abs() rule
        age = max(0.0, now - float(doc.get("ts") or 0))
        doc["age_sec"] = age
        doc["stale"] = age > stale_sec
        if not include_metrics:
            # the embedded per-shard metrics snapshot (the collector's
            # serverless source) stays OUT of the default view: the
            # /healthz fleet block is unauthenticated and tenant-labeled
            # series must never ride it
            doc.pop("metrics", None)
        shards[str(doc.get("shard") or name)] = doc
    live = {k: d for k, d in shards.items()
            if not d["stale"] and not d.get("closed")}
    depth = sum(int(d.get("queue_depth") or 0) for d in live.values())
    pending = sum(int(d.get("jobs_pending") or 0) for d in live.values())
    least = min(live, key=lambda k: int(live[k].get("queue_depth") or 0),
                default=None)
    return {"shards": shards, "live_shards": len(live),
            "stale_shards": sum(1 for d in shards.values() if d["stale"]),
            "cluster_queue_depth": depth,
            "cluster_jobs_pending": pending,
            "least_loaded": least}


# ---------------------------------------------------------------------------
# CLI: worker + selfcheck
# ---------------------------------------------------------------------------

def _cli_worker(args) -> int:
    m = re.fullmatch(r"(\d+)/(\d+)", args.shard)
    if not m:
        print(f"--shard must be I/W, got {args.shard!r}", file=sys.stderr)
        return 2
    shard, shards = int(m.group(1)), int(m.group(2))
    # mirror tests/conftest.py: an ambient sitecustomize can pin the jax
    # platform config at startup, so an env-var override alone is
    # ignored — force the config to the env's choice before backend init
    platform = os.environ.get("JAX_PLATFORMS")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform.split(",")[0])
    with open(args.spec) as f:
        spec = FleetSpec.from_json(f.read())
    try:
        rep = run_shard(spec, shard, shards, args.out,
                        ledger=not args.no_ledger)
    except BaseException as e:  # noqa: BLE001 — incl. InjectedCrash
        # last act of a dying worker: a flight-recorder postmortem into
        # the per-shard flight dir the coordinator injected, so the
        # fleet incident bundle always has this shard's final records
        # even when the failure was a simulated hard kill
        from ..obs import flight
        flight.dump("fleet_worker_crash",
                    extra={"shard": shard, "shards": shards,
                           "error": repr(e)[:500]})
        raise
    print(json.dumps({"shard": shard, "coalitions": len(rep["subsets"]),
                      "wallclock_s": rep["wallclock_s"]}))
    return 0


def _cli_selfcheck(args) -> int:
    """The CI fleet smoke: a tiny deterministic titanic sweep at 1 shard
    then at `--shards` shards (real worker subprocesses), merged ledgers
    diffed — exit 0 only on zero ulp drift and tau-b == 1.0."""
    from ..obs.numerics import diff_ledgers
    spec = FleetSpec()  # titanic, 3 partners, 2 epochs, deterministic
    with tempfile.TemporaryDirectory(prefix="mplc_fleet_smoke_") as tmp:
        env = worker_env(devices=1,
                         extra={"MPLC_TPU_SYNTH_SCALE":
                                os.environ.get("MPLC_TPU_SYNTH_SCALE",
                                               "0.02"),
                                "BENCH_TELEMETRY_FILE": None})
        t0 = time.perf_counter()
        ref = run_fleet(spec, 1, os.path.join(tmp, "w1"), env=env,
                        devices_per_shard=1, timeout=args.timeout)
        got = run_fleet(spec, args.shards, os.path.join(tmp, "w"), env=env,
                        devices_per_shard=1, timeout=args.timeout)
        diff = diff_ledgers(ref.ledger, got.ledger)
        ok = (diff["comparable"] and not diff["drift"]
              and diff["kendall_tau"] == 1.0
              and diff["common"] == len(spec.all_subsets()))
        obs = None
        if args.obs_dir:
            # CI fleet-smoke artifacts: ONE merged Perfetto timeline and
            # ONE aggregated /fleet/varz-shaped snapshot from the real
            # W-shard subprocess run, both asserted to carry one entry
            # per shard before the selfcheck claims success
            from ..obs import fleet_view
            os.makedirs(args.obs_dir, exist_ok=True)
            merged = fleet_view.merge_fleet_traces(got.out_dir)
            trace_path = os.path.join(args.obs_dir, "fleet_trace.json")
            _atomic_json(trace_path, merged["trace"])
            snap = fleet_view.cluster_snapshot(out_dir=got.out_dir)
            varz_path = os.path.join(args.obs_dir, "fleet_varz.json")
            _atomic_json(varz_path, snap)
            obs = {"trace": trace_path, "varz": varz_path,
                   "shard_tracks": merged["shard_tracks"],
                   "flow_links": merged["flow_links"],
                   "snapshot_shards": len(snap.get("shards") or {})}
            ok = (ok and merged["shard_tracks"] == args.shards
                  and merged["flow_links"] == args.shards
                  and obs["snapshot_shards"] == args.shards)
        print(json.dumps({
            "shards": args.shards, "subsets": diff["common"],
            "comparable": diff["comparable"], "drift": diff["drift"],
            "max_ulp": diff["ulp"]["max"],
            "kendall_tau": diff["kendall_tau"],
            "wallclock_s": round(time.perf_counter() - t0, 1),
            "obs": obs,
            "ok": ok}))
        if not ok:
            print(f"[fleet] selfcheck FAILED: {args.shards}-shard merged "
                  "ledger is not bit-identical to the 1-shard run",
                  file=sys.stderr)
            return 1
    return 0


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--worker", dest="spec", default=None,
                    help="run as a shard worker over this FleetSpec JSON")
    ap.add_argument("--shard", default=None, help="I/W (worker mode)")
    ap.add_argument("--out", default=None, help="shared output dir")
    ap.add_argument("--no-ledger", action="store_true")
    ap.add_argument("--selfcheck", action="store_true",
                    help="run the 1-vs-W-shard equality smoke and exit "
                         "non-zero on any drift")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=1200.0)
    ap.add_argument("--obs-dir", default=None,
                    help="selfcheck: also write the merged Perfetto "
                         "trace + aggregated fleet varz snapshot here "
                         "and fail unless both carry one entry per "
                         "shard")
    args = ap.parse_args(argv)
    if args.spec:
        if not (args.shard and args.out):
            ap.error("--worker requires --shard I/W and --out DIR")
        return _cli_worker(args)
    if args.selfcheck:
        return _cli_selfcheck(args)
    ap.error("one of --worker/--selfcheck is required")


if __name__ == "__main__":
    sys.exit(main())
