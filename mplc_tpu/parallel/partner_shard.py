"""Partner-axis sharding: federated training with partners spread over chips.

The reference holds every partner in one process and "communicates" weights
through a Python list average (/root/reference/mplc/mpl_utils.py:90-102).
Stacking partners on a leading axis already turns that into one fused
reduction (ops/aggregation.py); this module adds the second mesh dimension:
for large partner counts (or large per-partner data) the stacked `[P, ...]`
tensors are sharded over a `part` mesh axis with `shard_map`, each device
trains its local partner shard with the same vmapped kernel, and the
per-round aggregation becomes ONE `psum` over ICI per pytree leaf — the
framework's cross-chip weight communication.

Training-identical guarantee: every per-partner RNG (epoch shuffles, dropout,
lflip draws) is keyed by GLOBAL partner index (mpl/engine.py `_epoch_perms`,
`_fedavg_epoch`), so a partner-sharded run produces the same training
trajectory as the unsharded one up to reduction order.

Composes with coalition parallelism: a 2-D `[coal, part]` mesh
(parallel/mesh.py `make_2d_mesh`) shards the coalition batch over `coal` and
partners over `part`; the coalition axis still needs no communication.
"""

from __future__ import annotations

from functools import partial

import jax

try:
    from jax import shard_map as _shard_map_raw
except ImportError:  # older JAX
    from jax.experimental.shard_map import shard_map as _shard_map_raw

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.partition import StackedPartners
from ..mpl.engine import EvalSet, MplTrainer, TrainState


def shard_map_norep(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across JAX API versions
    (new API: check_vma; old API: check_rep)."""
    try:
        return _shard_map_raw(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)
    except TypeError:
        return _shard_map_raw(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)


def train_state_specs(axis: str, lflip: bool = False) -> TrainState:
    """PartitionSpec pytree-prefix for a TrainState whose partner-indexed
    leaves (theta, partner history) are sharded over `axis`. theta/theta_h
    only carry a partner dimension under lflip — other approaches hold
    rank-1 `(0,)` placeholders, which must take rank-compatible specs."""
    r = P()
    theta = P(axis) if lflip else P()
    theta_h = P(None, axis) if lflip else P()
    # stale: the straggler-fault params buffer — always the empty pytree
    # here (partner faults and 2-D partner sharding are mutually
    # exclusive, TrainConfig.__post_init__), so the spec is a no-leaf
    # placeholder like the non-lflip theta.
    return TrainState(params=r, opt_state=r, theta=theta,
                      theta_h=theta_h, epoch=r, done=r,
                      nb_epochs_done=r, best_val_loss=r, es_wait=r,
                      val_loss_h=r, val_acc_h=r, partner_h=P(None, axis),
                      stale=r)


def stacked_specs(axis: str) -> StackedPartners:
    s = P(axis)
    return StackedPartners(x=s, y=s, mask=s, sizes=s)


class PartnerShardedTrainer:
    """Runs an `MplTrainer` (fedavg/lflip, cfg.partner_axis set) with the
    partner axis sharded over `mesh`'s `axis` dimension.

    The public methods mirror MplTrainer's (init_state / epoch_chunk /
    finalize) but operate on GLOBAL arrays; shard_map splits them. The
    global partner count must be divisible by the mesh axis size (pad with
    empty partners — mask 0, size 0 — to round up; padded slots contribute
    zero weight everywhere).
    """

    def __init__(self, trainer: MplTrainer, mesh: Mesh, axis: str = "part"):
        cfg = trainer.cfg
        if cfg.partner_axis != axis:
            raise ValueError(
                f"trainer.cfg.partner_axis={cfg.partner_axis!r} must equal the "
                f"mesh axis {axis!r} (build the TrainConfig with partner_axis)")
        self.trainer = trainer
        self.mesh = mesh
        self.axis = axis
        self.n_shards = mesh.shape[axis]
        self._st = train_state_specs(axis, lflip=cfg.approach == "lflip")
        self._sp = stacked_specs(axis)
        self._jits = {}

    def data_shardings(self):
        """(stacked_sharding, replicated) NamedShardings for device_put."""
        return (jax.tree_util.tree_map(
                    lambda spec: NamedSharding(self.mesh, spec), self._sp),
                NamedSharding(self.mesh, P()))

    def init_state(self, rng: jax.Array, partners_count: int) -> TrainState:
        if partners_count % self.n_shards:
            raise ValueError(
                f"global partner count {partners_count} not divisible by "
                f"{self.n_shards} shards — pad with empty partners")
        local = partners_count // self.n_shards
        key = ("init", partners_count)
        if key not in self._jits:
            # no-donation by policy: the rng is the only input and callers
            # reuse it for the epoch chunk's training streams
            f = shard_map_norep(lambda r: self.trainer.init_state(r, local),
                                mesh=self.mesh, in_specs=(P(),),
                                out_specs=self._st)
            self._jits[key] = jax.jit(f)
        return self._jits[key](rng)

    def epoch_chunk(self, state: TrainState, stacked: StackedPartners,
                    val: EvalSet, coal_mask: jax.Array, rng: jax.Array,
                    n_epochs: int) -> TrainState:
        from ..mpl.engine import buffer_donation_enabled
        don = buffer_donation_enabled()
        hoist = self.trainer._det_hoist_streams()
        key = ("run", n_epochs, don)
        if key not in self._jits:
            if hoist:
                # deterministic-reduce: the hoisted permutation/key
                # stacks enter as data, partner-sliced over the mesh axis
                # (obs/numerics.py — in-program stream generation beside
                # the aggregation collective breaks bit-identity)
                stream_specs = (P(None, self.axis, None),
                                P(None, None, self.axis, None))
                f = shard_map_norep(
                    partial(self.trainer._epoch_chunk_streams,
                            n_epochs=n_epochs),
                    mesh=self.mesh,
                    in_specs=(self._st, self._sp, P(), P(self.axis), P(),
                              stream_specs),
                    out_specs=self._st)
            else:
                f = shard_map_norep(
                    partial(self.trainer.epoch_chunk, n_epochs=n_epochs),
                    mesh=self.mesh,
                    in_specs=(self._st, self._sp, P(), P(self.axis), P()),
                    out_specs=self._st)
            # same donation policy as the trainer's own state-carrying
            # jits: the input state is dead after every chunk call
            self._jits[key] = jax.jit(
                f, donate_argnums=(0,) if don else ())
        if hoist:
            streams = self.trainer.jit_gen_streams(
                rng, n_epochs, stacked.mask, batched=False,
                start_epoch=state.epoch)
            return self._jits[key](state, stacked, val, coal_mask, rng,
                                   streams)
        return self._jits[key](state, stacked, val, coal_mask, rng)

    def finalize(self, state: TrainState, test: EvalSet):
        """Global params are replicated after aggregation; evaluate locally."""
        if "fin" not in self._jits:
            # no-donation by policy: callers read state.params and the
            # histories AFTER finalize (tests/test_partner_shard.py)
            self._jits["fin"] = jax.jit(self.trainer.finalize)
        return self._jits["fin"](state, test)
