"""Device meshes and shardings for coalition / partner parallelism.

The reference has no distributed backend at all (SURVEY.md §2.3); this
module is where the TPU framework defines its scale-out story:

  - `coal` axis: the primary parallel axis. Independent coalition trainings
    (or scenario-grid cells) shard over it; they share nothing until their
    scalar scores are gathered, so it rides ICI with essentially zero
    communication and scales linearly in chips.
  - `part` axis (optional 2-D mesh): shards the partner dimension of the
    stacked data/params inside one coalition training for very large P;
    the masked aggregation reduction then becomes a `psum` over `part`.

All helpers degrade gracefully to single-device (bench on one chip, tests on
a CPU with XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class CoalitionSharding:
    mesh: Mesh
    batch_sharding: NamedSharding      # shard leading (coalition) axis
    replicated: NamedSharding

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size


def make_mesh(devices=None, axis_name: str = "coal") -> Mesh:
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (axis_name,))


def coalition_sharding(devices=None) -> CoalitionSharding | None:
    """Sharding spec for a batch of coalition trainings; None on 1 device."""
    devices = jax.devices() if devices is None else devices
    if len(devices) <= 1:
        return None
    mesh = make_mesh(devices)
    return CoalitionSharding(
        mesh=mesh,
        batch_sharding=NamedSharding(mesh, P("coal")),
        replicated=NamedSharding(mesh, P()),
    )


def make_2d_mesh(coal: int, part: int, devices=None) -> Mesh:
    """[coal, part] mesh: coalition batch x partner sharding.

    Raises ValueError (not assert — asserts vanish under `python -O`,
    and a silently mis-shaped mesh would train the wrong partition) when
    the requested grid does not tile the device list exactly."""
    devices = jax.devices() if devices is None else list(devices)
    if coal * part != len(devices):
        raise ValueError(
            f"mesh {coal}x{part} needs {coal * part} devices, have "
            f"{len(devices)}")
    return Mesh(np.asarray(devices).reshape(coal, part), ("coal", "part"))


def make_multihost_mesh(part: int = 1, devices=None) -> Mesh:
    """N-host x local [coal, part] mesh for the fleet plane: the `coal`
    axis SPANS hosts (coalition batches are zero-communication, so the
    axis rides across the slow inter-host fabric for free) while `part`
    stays INTRA-host — the per-round partner `psum`/all-gather never
    leaves a host's ICI domain. On an N x 8 fleet with part=2 this is a
    [4N, 2] mesh: 4N-way coalition parallelism, 2-way partner sharding
    inside each host.

    Devices are grouped by `process_index` (a host in the multi-process
    runtime; one group on a single-process CPU/test mesh) and ordered by
    id within a host, so the mesh layout is deterministic across
    processes. ValueErrors: uneven per-host device counts, or `part` not
    dividing the per-host count."""
    devices = jax.devices() if devices is None else list(devices)
    by_host: dict = {}
    for d in devices:
        by_host.setdefault(getattr(d, "process_index", 0), []).append(d)
    counts = {h: len(ds) for h, ds in by_host.items()}
    if len(set(counts.values())) != 1:
        raise ValueError(
            f"multi-host mesh needs the same device count on every host, "
            f"got {counts}")
    local = next(iter(counts.values()))
    if part < 1 or local % part:
        raise ValueError(
            f"part={part} must be >= 1 and divide the per-host device "
            f"count ({local}); hosts={sorted(by_host)}")
    rows = []
    for h in sorted(by_host):
        host_devs = sorted(by_host[h], key=lambda d: d.id)
        rows.append(np.asarray(host_devs, dtype=object).reshape(
            local // part, part))
    return Mesh(np.concatenate(rows, axis=0), ("coal", "part"))
