"""Device meshes and shardings for coalition / partner parallelism.

The reference has no distributed backend at all (SURVEY.md §2.3); this
module is where the TPU framework defines its scale-out story:

  - `coal` axis: the primary parallel axis. Independent coalition trainings
    (or scenario-grid cells) shard over it; they share nothing until their
    scalar scores are gathered, so it rides ICI with essentially zero
    communication and scales linearly in chips.
  - `part` axis (optional 2-D mesh): shards the partner dimension of the
    stacked data/params inside one coalition training for very large P;
    the masked aggregation reduction then becomes a `psum` over `part`.

All helpers degrade gracefully to single-device (bench on one chip, tests on
a CPU with XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class CoalitionSharding:
    mesh: Mesh
    batch_sharding: NamedSharding      # shard leading (coalition) axis
    replicated: NamedSharding

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size


def make_mesh(devices=None, axis_name: str = "coal") -> Mesh:
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (axis_name,))


def coalition_sharding(devices=None) -> CoalitionSharding | None:
    """Sharding spec for a batch of coalition trainings; None on 1 device."""
    devices = jax.devices() if devices is None else devices
    if len(devices) <= 1:
        return None
    mesh = make_mesh(devices)
    return CoalitionSharding(
        mesh=mesh,
        batch_sharding=NamedSharding(mesh, P("coal")),
        replicated=NamedSharding(mesh, P()),
    )


def make_2d_mesh(coal: int, part: int, devices=None) -> Mesh:
    """[coal, part] mesh: coalition batch x partner sharding."""
    devices = jax.devices() if devices is None else devices
    assert coal * part == len(devices), (
        f"mesh {coal}x{part} needs {coal * part} devices, have {len(devices)}")
    return Mesh(np.asarray(devices).reshape(coal, part), ("coal", "part"))
