from .mesh import (CoalitionSharding, coalition_sharding, make_mesh,
                   make_2d_mesh)

__all__ = ["CoalitionSharding", "coalition_sharding", "make_mesh", "make_2d_mesh"]
