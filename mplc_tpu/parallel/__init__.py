from .mesh import (CoalitionSharding, coalition_sharding, make_mesh,
                   make_2d_mesh, make_multihost_mesh)
from .partner_shard import PartnerShardedTrainer

__all__ = ["CoalitionSharding", "coalition_sharding", "make_mesh",
           "make_2d_mesh", "make_multihost_mesh", "PartnerShardedTrainer"]
