"""Observability for the coalition engine: structured tracing (`trace`),
a process-global metrics registry (`metrics`) and run reports (`report`).

Zero dependencies beyond the stdlib; everything is host-side and adds no
device syncs to the instrumented hot paths. Tracing emits JSONL when
`MPLC_TPU_TRACE_FILE` is set (no-op otherwise); `report.sweep_report`
turns collected spans into the compile/dispatch/harvest split, memo hit
rate, padding waste and per-bucket throughput.
"""

from . import metrics, report, trace
from .report import format_report, sweep_report, write_report
from .trace import collect, event, span, start_span

__all__ = ["metrics", "report", "trace", "span", "start_span", "event",
           "collect", "sweep_report", "format_report", "write_report"]
