"""Observability for the coalition engine: structured tracing (`trace`),
a process-global metrics registry (`metrics`), run reports (`report`),
live telemetry endpoints (`export`), Chrome-trace conversion
(`chrome_trace`) and the crash flight recorder (`flight`).

Zero dependencies beyond the stdlib; everything is host-side and adds no
device syncs to the instrumented hot paths. Tracing emits JSONL when
`MPLC_TPU_TRACE_FILE` is set (a bounded in-memory ring for the flight
recorder is always on); `report.sweep_report` turns collected spans into
the compile/dispatch/harvest split, memo hit rate, padding waste,
per-bucket throughput and per-tenant SLO quantiles; `export` serves
/metrics (Prometheus), /healthz and /varz from a stdlib HTTP thread when
`MPLC_TPU_METRICS_PORT` is set.
"""

from . import chrome_trace, export, flight, metrics, report, trace
from .report import format_report, sweep_report, write_report
from .trace import collect, event, span, start_span

__all__ = ["chrome_trace", "export", "flight", "metrics", "report",
           "trace", "span", "start_span", "event", "collect",
           "sweep_report", "format_report", "write_report"]
