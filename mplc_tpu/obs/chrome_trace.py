"""Chrome trace-event export: span JSONL -> Perfetto-loadable JSON.

The span JSONL sink (`MPLC_TPU_TRACE_FILE`) records the engine's
compile/dispatch/harvest overlap, but as flat lines it answers nothing
visually. This module converts it into the Chrome trace-event format
(the JSON object form: `{"traceEvents": [...]}`) that
https://ui.perfetto.dev loads directly:

  - every record becomes a complete ("X") slice on a per-thread track
    (`pid` 1, `tid` = the recording thread id, named via "M" metadata
    events); zero-duration events are widened to 1 us so they render and
    can anchor flows. Sampled device fences (`engine.device_fence`,
    obs/devcost.py) get their own "device" process track (pid 2), so
    measured device time separates visually from host spans;
  - timestamps are rebased to the trace's first record and expressed in
    microseconds (the format's unit);
  - FLOW events (ph "s"/"f") draw arrows linking the recovery machinery
    to the work it recovered: `engine.retry` / `engine.fault` records
    (which carry the batch `ordinal`) to the next `engine.batch` of the
    same ordinal on the same thread, `engine.degrade` records to the
    next batch on the thread (the re-bucketed dispatch), and
    `service.job_fault` records to the job's next `service.slice` (the
    requeue). A retry storm is one glance instead of a grep.

`read_jsonl` tolerates torn lines — the signature of a process killed
mid-append (the atexit flush in obs/trace.py prevents them on clean
exits) — counting and reporting them instead of dying on byte 10^7 of a
10^7+1-byte trace.

CLI wrapper: scripts/trace_to_perfetto.py. Live export: setting
`MPLC_TPU_CHROME_TRACE_FILE` converts the trace automatically at
interpreter exit (hook in obs/trace.py).
"""

from __future__ import annotations

import json
import os
import warnings

CHROME_TRACE_ENV = "MPLC_TPU_CHROME_TRACE_FILE"

# record-name -> flow-arrow label for the recovery links drawn below
_FLOW_SOURCES = {"engine.retry": "retry", "engine.fault": "fault",
                 "engine.degrade": "degrade",
                 "service.job_fault": "requeue"}

# records that represent MEASURED DEVICE time (the sampled fences,
# obs/devcost.py) rather than host-side spans: drawn on their own
# "device" process track (pid 2) so the enqueue-vs-device-vs-harvest
# split the report totals is visually inspectable on the timeline
_DEVICE_ROWS = {"engine.device_fence"}


def read_jsonl(path: str) -> tuple[list, int]:
    """(records, torn_lines): every parseable record of a span JSONL
    trace, in file order. Unparseable or schema-less lines (torn tail
    from a hard kill, truncated flush) are counted, not fatal."""
    records = []
    torn = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict) or "name" not in rec:
                    raise ValueError("not a span record")
            except ValueError:
                torn += 1
                continue
            records.append(rec)
    return records, torn


def _attrs(rec: dict) -> dict:
    return rec.get("attrs") or {}


def to_chrome(records: list) -> dict:
    """Chrome trace-event JSON (object form) from span records."""
    events = []
    if records:
        t0 = min(float(r.get("ts") or 0.0) for r in records)
    else:
        t0 = 0.0

    tids = []  # (pid, tid) in file-discovery order
    slices = []  # (rec, ts_us, dur_us) in file order, for flow targets
    for rec in records:
        tid = int(rec.get("thread") or 0)
        name = rec.get("name", "?")
        pid = 2 if name in _DEVICE_ROWS else 1
        if (pid, tid) not in tids:
            tids.append((pid, tid))
        ts_us = (float(rec.get("ts") or 0.0) - t0) * 1e6
        dur_us = max(float(rec.get("dur") or 0.0) * 1e6, 1.0)
        events.append({
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": pid,
            "tid": tid,
            "args": {**_attrs(rec), "span_id": rec.get("id"),
                     "parent_span": rec.get("parent")},
        })
        slices.append((rec, ts_us, dur_us))

    # thread tracks: name them, keep file-discovery order stable
    for i, (pid, tid) in enumerate(tids):
        prefix = "device" if pid == 2 else "thread"
        events.append({"name": "thread_name", "ph": "M", "ts": 0, "pid": pid,
                       "tid": tid, "args": {"name": f"{prefix}-{tid}"}})
        events.append({"name": "thread_sort_index", "ph": "M", "ts": 0,
                       "pid": pid, "tid": tid, "args": {"sort_index": i}})
    if any(pid == 2 for pid, _ in tids):
        events.append({"name": "process_name", "ph": "M", "ts": 0, "pid": 1,
                       "tid": 0, "args": {"name": "host"}})
        events.append({"name": "process_name", "ph": "M", "ts": 0, "pid": 2,
                       "tid": 0, "args": {"name": "device (fenced samples)"}})

    flows = _flow_events(slices)
    events.extend(flows)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "mplc_tpu span JSONL",
                      "records": len(records), "flows": len(flows) // 2},
    }


def _flow_events(slices: list) -> list:
    """ph "s"/"f" pairs for the recovery links (module docstring). Flow
    binding rule: the start event sits just inside the source slice, the
    finish (`bp: "e"`) just inside the target slice — both slices exist
    because zero-duration records were widened to 1 us.

    Targets are pre-indexed by key so a fault-heavy trace converts in one
    forward pass (a per-source rescan of all later records is quadratic
    in record count): "the NEXT matching record after position i" is a
    `bisect` into that key's position list."""
    import bisect

    # key -> ([file positions], [slice tuples]), positions ascending
    batch_by_tid_ord: dict = {}   # (tid, ordinal) — retry/fault targets
    batch_by_tid: dict = {}       # tid             — degrade targets
    slice_by_job: dict = {}       # job             — requeue targets
    for i, entry in enumerate(slices):
        rec = entry[0]
        a = _attrs(rec)
        if rec.get("name") == "engine.batch":
            tid = int(rec.get("thread") or 0)
            for key, idx in (((tid, a.get("ordinal")), batch_by_tid_ord),
                             ((tid,), batch_by_tid)):
                pos, items = idx.setdefault(key, ([], []))
                pos.append(i)
                items.append(entry)
        elif rec.get("name") == "service.slice":
            pos, items = slice_by_job.setdefault(a.get("job"), ([], []))
            pos.append(i)
            items.append(entry)

    def next_after(index: dict, key, i):
        hit = index.get(key)
        if hit is None:
            return None
        pos, items = hit
        j = bisect.bisect_right(pos, i)
        return items[j] if j < len(items) else None

    out = []
    flow_id = 0
    for i, (rec, ts_us, _dur) in enumerate(slices):
        label = _FLOW_SOURCES.get(rec.get("name"))
        if label is None:
            continue
        a = _attrs(rec)
        tid = int(rec.get("thread") or 0)
        if rec.get("name") == "service.job_fault":
            # the requeue link: this job's next scheduling quantum
            target = next_after(slice_by_job, a.get("job"), i)
        elif a.get("ordinal") is not None:
            # retry/fault carry the batch ordinal
            target = next_after(batch_by_tid_ord, (tid, a["ordinal"]), i)
        else:
            # degrade (an OOM re-bucket) links to whatever batch
            # dispatches next on the thread
            target = next_after(batch_by_tid, (tid,), i)
        if target is None:
            continue
        nrec, nts, ndur = target
        flow_id += 1
        out.append({"name": label, "cat": "flow", "ph": "s", "id": flow_id,
                    "ts": ts_us + 0.5, "pid": 1, "tid": tid})
        out.append({"name": label, "cat": "flow", "ph": "f", "bp": "e",
                    "id": flow_id, "ts": nts + min(0.5, ndur / 2),
                    "pid": 1, "tid": int(nrec.get("thread") or 0)})
    return out


def convert(in_path: str, out_path: str | None = None) -> dict:
    """Read a span JSONL trace, write Chrome trace-event JSON (atomic
    temp + rename), return a summary dict: {out, records, events, flows,
    torn_lines}."""
    records, torn = read_jsonl(in_path)
    doc = to_chrome(records)
    if torn:
        doc["otherData"]["torn_lines"] = torn
        warnings.warn(
            f"{in_path}: {torn} unparseable line(s) skipped (torn tail "
            "from a hard kill, or a non-span line); the converted trace "
            "covers every intact record", stacklevel=2)
    if out_path is None:
        base = in_path[:-6] if in_path.endswith(".jsonl") else in_path
        out_path = base + ".chrome.json"
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{out_path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return {"out": out_path, "records": len(records),
            "events": len(doc["traceEvents"]),
            "flows": doc["otherData"]["flows"], "torn_lines": torn}
