"""Sweep reports: aggregate trace records + metrics into a per-run JSON
sidecar and a human-readable summary.

`sweep_report(records)` consumes the span/event records collected during a
run (`obs.trace.collect()`, or a parsed JSONL trace file) and derives the
quantities every perf PR needs as a measured before/after:

  - wall-clock split: compile vs prep vs dispatch vs harvest inside the
    engine's evaluate() time (compile happens *inside* the first
    dispatch/harvest of each program, so the components are reported raw,
    not disjoint); `prep` is the whole-call host-side batch construction —
    coalition arrays, rng fold words, batch-invariant device placements —
    done once per bucket before its dispatch loop;
  - memo hit/miss counts and hit rate (from engine.evaluate span attrs);
  - padding waste: padded slots / total batch slots over the whole run;
  - per-(slot_count, width) bucket throughput: coalitions and epochs per
    span-second (span-sum, which under MPLC_TPU_PIPELINE_BATCHES counts
    overlapped batches twice — a utilization view, not a wall-clock one);
  - per-executable compile counts/seconds and per-estimator durations;
  - a compute/intensity row: training samples and partner passes summed
    from the engine.batch events, and — when the caller supplies the
    model's forward FLOPs per sample (models/zoo.fwd_flops_per_sample or
    the XLA cost model) — a model-FLOPs rate over the evaluate wall-clock
    plus an MFU proxy against a supplied peak-FLOPs figure (a HOST-side
    proxy: dispatch is async, so the denominator is host wall-clock);
    when the stream carries XLA cost truth the row additionally gains
    `mfu_xla` — Compiled.cost_analysis() flops over measured device time
    where fenced samples exist;
  - a device_time row (MPLC_TPU_DEVICE_FENCE_RATE, obs/devcost.py):
    measured device-step-seconds from the sampled fences, the
    per-coalition extrapolated device-seconds figure, and the
    enqueue/device/harvest host-overhead split;
  - a roofline row: per-program achieved FLOP/s vs peak and bytes/s vs
    HBM bandwidth with arithmetic intensity, from the program bank's
    per-bundle cost analysis;
  - a resilience row: transient retries and backoff seconds
    (engine.retry events), OOM cap halvings and the CPU-path flip
    (engine.degrade), batches/coalitions that ran on the degraded CPU
    rung, and injected-fault counts (engine.fault) — so every recorded
    number says whether it was earned on a clean or a degraded run;
  - a trust row (seed-ensemble sweeps only): per-partner Shapley
    confidence intervals and the Kendall-tau rank-stability score from
    the `contrib.trust` event — so a reported ranking says how much the
    seeds agree on it;
  - a service row (multi-tenant sweep-service runs): job outcomes
    (completed/quarantined/cancelled/recovered), the cross-tenant
    packed-batch count, and per-tenant fair-share cost attribution from
    the `service.slice` spans' batch accounting;
  - a live row (live-contributivity-tier runs): query counts and memo
    hits, reconstruction evaluations and DPVS-pruned coalitions, rounds
    appended/resident and journal-restored games, fresh-query latency
    quantiles and per-method counts from the `live.query` events —
    mirroring the `live.query_sec` histogram and per-tenant
    rounds-resident gauge the /metrics endpoint exports;
  - an slo row (service runs): per-tenant latency quantiles — queue wait
    (submit -> first quantum) and time-to-first-value from the terminal
    `service.job` events, slice-duration p50/p95/p99 from the
    `service.slice` spans — plus deadline misses and re-queued attempts
    (`service.job_fault`), mirroring the live per-tenant histograms the
    /metrics endpoint exports (obs/export.py);
  - a router row (fleet-router runs): jobs routed through the front,
    redirect resubmits, sticky-pin breaks, shard failovers with the
    journal-seeded jobs they resubmitted, budget exhaustions, and
    end-to-end routing-latency quantiles from the `router.submit`
    spans — mirroring the live `router.*` counters and the
    `router.route_sec` histogram.

The report is derived from SPANS of the collected region only, so callers
get a clean per-run view without resetting the process-global metrics
registry; the registry snapshot can be attached for cumulative context.
"""

from __future__ import annotations

import json
import os


def _attrs(rec: dict) -> dict:
    return rec.get("attrs") or {}


def _pctl(values: list, q: float) -> float | None:
    """Nearest-rank percentile of a small sample (exact, no buckets —
    the report works from the collected region's full duration lists,
    unlike the live /metrics histograms)."""
    if not values:
        return None
    vals = sorted(values)
    rank = max(1, -(-int(q * 100) * len(vals) // 100))  # ceil without math
    return vals[min(rank, len(vals)) - 1]


def sweep_report(records: list, metrics_snapshot: dict | None = None,
                 flops_per_sample: float | None = None,
                 peak_flops: float | None = None,
                 hbm_bytes_per_s: float | None = None) -> dict:
    """Aggregate a list of trace records (dicts) into the sweep report.

    `flops_per_sample` (the model's analytic/XLA-measured forward FLOPs for
    ONE training sample) turns the summed trained-sample count into a
    model-FLOPs rate (fwd+bwd ~ 3x fwd, padded rows and val/test evals
    excluded — a conservative lower bound on the device rate);
    `peak_flops` (the attached fleet's aggregate peak) additionally yields
    `mfu_proxy` = achieved / peak — a HOST-side proxy. When the record
    stream carries XLA cost truth (per-batch `flops`/`bytes_accessed`
    attrs from program-bank bundles) and/or sampled device fences
    (`device_sec` attrs, MPLC_TPU_DEVICE_FENCE_RATE), the report
    additionally derives `mfu_xla`, a `device_time` row (true
    device-step seconds, host-overhead split, the fenced-extrapolation
    device-seconds figure) and a per-program `roofline` row (achieved
    FLOP/s vs `peak_flops`, bytes/s vs `hbm_bytes_per_s`, arithmetic
    intensity). Record streams without those attrs — every pre-devcost
    sidecar — produce exactly the old schema."""
    evaluate_s = prep_s = dispatch_s = harvest_s = compile_s = 0.0
    compile_overlapped_s = bank_wait_s = 0.0
    bank_compiles = bank_compiles_overlapped = 0
    hbm = None
    requested = missing = 0
    compiles: dict = {}
    buckets: dict = {}
    batches = coalitions = padding = epochs = 0
    samples = partner_passes = 0
    estimators = []
    fits = []
    retries = 0
    backoff_s = 0.0
    cap_halvings = cpu_fallbacks = ladder_exhausted = 0
    cpu_batches = cpu_coalitions = 0
    faults_injected = 0
    svc_tenants: dict = {}
    svc_jobs: dict = {}
    svc_slice_durs: dict = {}   # tenant -> [slice seconds]
    svc_job_faults: dict = {}   # tenant -> failed-attempt count
    trust = None
    per_method: dict = {}
    live_queries: list = []         # (dur, attrs) of live.query events
    live_appends = live_recovers = 0
    live_evictions = live_ingested = 0
    live_restores: list = []        # restore_s of live.restore events
    # adaptive query planner (contrib/planner.py): every contrib.plan /
    # live.plan event is one method="auto" resolution
    plans: list = []
    # numeric-truth plane (obs/numerics.py): audit/drift events and the
    # last ledger-persist event
    num_audits = num_drift = 0
    num_max_ulp = 0
    num_mode = None
    num_ledger = None
    recon_batches = recon_coalitions = 0
    recon_s = 0.0
    recorded = None
    # device-time truth (obs/devcost.py): fenced device-step samples and
    # XLA-modeled per-batch cost, when the stream carries them
    fence_samples: list = []        # measured device_sec per fenced batch
    fenced_coalitions = 0
    fence_interval = None
    flops_total = bytes_total = 0.0
    costed_batches = 0
    costed_span_s = 0.0
    fenced_flops = fenced_flops_sec = 0.0
    roof: dict = {}                 # (slot_count, width) -> cost buckets
    # fleet-router events (service/router.py): counts mirror the live
    # router.* counters; route_durs mirrors the router.route_sec histogram
    rtr = {"routed": 0, "resubmits": 0, "repins": 0, "failovers": 0,
           "failover_jobs": 0, "budget_exhausted": 0}
    rtr_route_durs: list = []

    for rec in records:
        name = rec.get("name")
        dur = float(rec.get("dur") or 0.0)
        a = _attrs(rec)
        if name == "engine.evaluate":
            evaluate_s += dur
            requested += int(a.get("requested", 0))
            missing += int(a.get("missing", 0))
            m = a.get("method")
            if m:
                # per-estimator memo attribution (mixed-method runs):
                # hits = requested - misses within THIS method's calls
                d = per_method.setdefault(m, {"requested": 0, "misses": 0})
                d["requested"] += int(a.get("requested", 0))
                d["misses"] += int(a.get("missing", 0))
        elif name == "engine.prep":
            prep_s += dur
        elif name == "engine.dispatch":
            dispatch_s += dur
        elif name == "engine.harvest":
            harvest_s += dur
        elif name == "trainer.compile":
            compile_s += dur
            fn = a.get("fn", "?")
            c = compiles.setdefault(fn, {"count": 0, "seconds": 0.0})
            c["count"] += 1
            c["seconds"] += dur
        elif name == "bank.compile":
            # AOT program-bank compiles: background (overlapped=True) ones
            # ran CONCURRENTLY with execution and are reported separately
            # — they never extended the sweep's wall-clock; foreground
            # ones (the first bucket) are serial compile time like any
            # jit-inline compile
            bank_compiles += 1
            if a.get("overlapped"):
                bank_compiles_overlapped += 1
                compile_overlapped_s += dur
            else:
                compile_s += dur
            fn = f"bank[slots={a.get('slot_count')},w={a.get('width')}]"
            c = compiles.setdefault(fn, {"count": 0, "seconds": 0.0})
            c["count"] += 1
            c["seconds"] += dur
        elif name == "bank.wait":
            # serial stall behind the background compile worker: wall-
            # clock that DID block the sweep even though the compile
            # itself is booked as overlapped (wall vs CPU views of the
            # same work — kept separate so the compile row stays honest)
            bank_wait_s += dur
            compile_s += dur
        elif name == "engine.hbm":
            # one snapshot per evaluate() call; the last one wins (like
            # the trust row) — the per-coalition footprint model and the
            # donation cap uplift don't change mid-run except down the
            # OOM ladder, where the latest view is exactly the right one
            hbm = dict(a)
        elif name == "engine.batch":
            k = (a.get("slot_count"), int(a.get("width", 0)))
            b = buckets.setdefault(k, {"batches": 0, "coalitions": 0,
                                       "padding": 0, "epochs": 0,
                                       "seconds": 0.0})
            b["batches"] += 1
            b["coalitions"] += int(a.get("coalitions", 0))
            b["padding"] += int(a.get("padding", 0))
            b["epochs"] += int(a.get("epochs", 0))
            b["seconds"] += dur
            batches += 1
            coalitions += int(a.get("coalitions", 0))
            padding += int(a.get("padding", 0))
            epochs += int(a.get("epochs", 0))
            samples += int(a.get("samples", 0))
            partner_passes += int(a.get("partner_passes", 0))
            if a.get("degraded") == "cpu":
                cpu_batches += 1
                cpu_coalitions += int(a.get("coalitions", 0))
            if a.get("eval_only"):
                # reconstructed-coalition eval batch (retrain-free
                # estimators): rides the same buckets but trains nothing
                recon_batches += 1
                recon_coalitions += int(a.get("coalitions", 0))
                recon_s += dur
            dsec = a.get("device_sec")
            fl = a.get("flops")
            if dsec is not None:
                fence_samples.append(float(dsec))
                fenced_coalitions += int(a.get("coalitions", 0))
            if fl:
                flops_total += float(fl)
                bytes_total += float(a.get("bytes_accessed") or 0.0)
                costed_batches += 1
                costed_span_s += dur
                rb = roof.setdefault(k, {
                    "batches": 0, "flops": 0.0, "bytes": 0.0,
                    "span_s": 0.0, "fenced_s": 0.0, "fenced_flops": 0.0,
                    "fenced_bytes": 0.0})
                rb["batches"] += 1
                rb["flops"] += float(fl)
                rb["bytes"] += float(a.get("bytes_accessed") or 0.0)
                rb["span_s"] += dur
                if dsec is not None:
                    rb["fenced_s"] += float(dsec)
                    rb["fenced_flops"] += float(fl)
                    rb["fenced_bytes"] += float(a.get("bytes_accessed")
                                                or 0.0)
                    fenced_flops += float(fl)
                    fenced_flops_sec += float(dsec)
        elif name == "engine.device_fence":
            # the fence's own event carries the sampling config; the
            # per-batch samples are aggregated off engine.batch above
            if a.get("interval"):
                fence_interval = int(a["interval"])
        elif name == "recon.record":
            # the grand-coalition recording run (one per engine); the last
            # event wins, like the trust row
            recorded = {**a, "seconds": dur}
        elif name == "engine.retry":
            retries += 1
            backoff_s += float(a.get("backoff_sec", 0.0))
        elif name == "engine.degrade":
            # every halve/fallback event is one rung down the ladder (the
            # last rung flips the engine onto the per-batch CPU path);
            # `ladder_exhausted` is the 2-D dead end — the classified
            # terminal error where no CPU rung exists — and is NOT a rung
            if a.get("action") == "ladder_exhausted":
                ladder_exhausted += 1
            else:
                cap_halvings += 1
                if a.get("action") == "cpu_fallback":
                    cpu_fallbacks += 1
        elif name == "engine.fault":
            faults_injected += 1
        elif name == "service.slice":
            # one scheduling quantum of the sweep service: per-tenant
            # batch/sample accounting for fair-share cost attribution
            t = svc_tenants.setdefault(a.get("tenant", "?"), {
                "slices": 0, "failed_slices": 0, "batches": 0,
                "coalitions": 0, "epochs": 0, "samples": 0,
                "packed_batches": 0, "seconds": 0.0,
                "device_seconds": 0.0})
            # metered device-seconds billed to this quantum
            # (scheduler._meter_quantum; absent on pre-devcost streams)
            t["device_seconds"] += float(a.get("device_sec") or 0.0)
            if a.get("outcome"):
                # the replacement event for a cancelled/faulted quantum
                # (its real span was cancelled, never emitted): its
                # device billing counts above, but slice counts,
                # span-seconds and the slo quantiles must keep mirroring
                # the live service.slice_sec histogram — which observes
                # only SUCCESSFUL quanta
                t["failed_slices"] += 1
                continue
            t["slices"] += 1
            t["batches"] += int(a.get("batches", 0))
            t["coalitions"] += int(a.get("coalitions", 0))
            t["epochs"] += int(a.get("epochs", 0))
            t["samples"] += int(a.get("samples", 0))
            t["packed_batches"] += int(a.get("packed_batches", 0))
            t["seconds"] += dur
            svc_slice_durs.setdefault(a.get("tenant", "?"), []).append(dur)
        elif name == "service.job":
            # terminal job event (completed / quarantined / cancelled)
            svc_jobs[a.get("job", "?")] = a
        elif name == "service.job_fault" and a.get("requeued"):
            # only RE-QUEUED attempts count as retries (the quarantining
            # final attempt does not) — same rule as the live
            # service.job_retries counter this row mirrors
            tn = a.get("tenant", "?")
            svc_job_faults[tn] = svc_job_faults.get(tn, 0) + 1
        elif name == "router.submit":
            rtr["routed"] += 1
            # a zero-duration event whose route_s attr carries the
            # measured submit->accept latency (redirects + backoff
            # included), mirroring the router.route_sec histogram
            rtr_route_durs.append(float(a.get("route_s") or dur))
        elif name == "router.redirect":
            rtr["resubmits"] += 1
        elif name == "router.repin":
            rtr["repins"] += 1
        elif name == "router.failover":
            rtr["failovers"] += 1
            rtr["failover_jobs"] += int(a.get("resubmitted", 0))
        elif name == "router.exhausted":
            rtr["budget_exhausted"] += 1
        elif name == "numerics.audit":
            num_audits += 1
            num_max_ulp = max(num_max_ulp, int(a.get("max_ulp") or 0))
            num_mode = a.get("reduction_mode") or num_mode
        elif name == "numerics.drift":
            num_drift += 1
        elif name == "numerics.ledger":
            # one persist per evaluate(); the last event carries the
            # final entry count
            num_ledger = dict(a)
        elif name in ("contrib.plan", "live.plan"):
            plans.append(dict(a))
        elif name == "live.query":
            live_queries.append((dur, a))
        elif name == "live.append":
            live_appends += 1
        elif name == "live.recover":
            live_recovers += 1
        elif name == "live.evict":
            live_evictions += 1
        elif name == "live.restore":
            live_restores.append(float(a.get("restore_s") or 0.0))
        elif name == "live.ingest":
            live_ingested += 1
        elif name == "contrib.trust":
            # one trust row per sweep; the last event wins (a re-run of
            # the estimator within one collected region supersedes)
            trust = dict(a)
        elif name == "contributivity":
            estimators.append({"method": a.get("method", "?"), "seconds": dur})
        elif name == "mpl.fit":
            fits.append({"approach": a.get("approach", "?"), "seconds": dur})

    slots_total = coalitions + padding
    hits = requested_unique_hits = max(requested - missing, 0)
    per_width = []
    for (slot_count, width), b in sorted(
            buckets.items(), key=lambda kv: (kv[0][0] is None,
                                             kv[0][0] or 0, kv[0][1])):
        s = b["seconds"]
        per_width.append({
            "slot_count": slot_count, "width": width, **b,
            "coalitions_per_s": b["coalitions"] / s if s else None,
            "epochs_per_s": b["epochs"] / s if s else None,
        })

    # compute/intensity: model-FLOPs rate over the engine's evaluate
    # wall-clock (falling back to the bucket span-sum for record sets
    # collected without an evaluate span). Training compute only — padded
    # rows and val/test evals are excluded, so the true device rate is
    # strictly higher; the point is a comparable, attributable proxy.
    basis_s = evaluate_s or sum(b["seconds"] for b in buckets.values())
    compute = {
        "train_samples": samples,
        "partner_passes": partner_passes,
        "samples_per_s": samples / basis_s if basis_s else None,
        "flops_per_sample_fwd": flops_per_sample,
        "model_flops": None,
        "model_flops_per_s": None,
        "peak_flops": peak_flops,
        "mfu_proxy": None,
    }
    if flops_per_sample and samples:
        compute["model_flops"] = 3.0 * flops_per_sample * samples
        if basis_s:
            compute["model_flops_per_s"] = compute["model_flops"] / basis_s
            if peak_flops:
                compute["mfu_proxy"] = \
                    compute["model_flops_per_s"] / peak_flops
    # XLA-derived utilization (obs/devcost.py): modeled flops come from
    # Compiled.cost_analysis() instead of the hand-derived analytic
    # estimate, and — when fenced samples exist — the denominator is
    # measured DEVICE time instead of host span. Supersedes mfu_proxy
    # when present; the analytic proxy stays rendered as the fallback.
    if flops_total:
        compute["model_flops_xla"] = flops_total
        if fenced_flops_sec:
            compute["xla_flops_per_s"] = fenced_flops / fenced_flops_sec
            compute["mfu_xla_basis"] = "device_fenced"
        elif costed_span_s:
            compute["xla_flops_per_s"] = flops_total / costed_span_s
            compute["mfu_xla_basis"] = "host_span"
        else:
            compute["xla_flops_per_s"] = None
            compute["mfu_xla_basis"] = None
        compute["mfu_xla"] = (compute["xla_flops_per_s"] / peak_flops
                              if compute["xla_flops_per_s"] and peak_flops
                              else None)

    report = {
        "wallclock": {
            "evaluate_s": evaluate_s,
            "compile_s": compile_s,
            # program-bank compiles that ran on the background thread
            # while earlier buckets executed — spent CPU, not wall-clock
            "compile_overlapped_s": compile_overlapped_s,
            "prep_s": prep_s,
            "dispatch_s": dispatch_s,
            "harvest_s": harvest_s,
        },
        "compute": compute,
        "memo": {
            "requested": requested,
            "hits": hits,
            "misses": missing,
            "hit_rate": requested_unique_hits / requested if requested else None,
            # per-estimator memo attribution lands below, only when at
            # least one engine.evaluate span carried a method — old
            # (method-less) record streams keep the exact old schema
        },
        "batches": {
            "count": batches,
            "coalitions": coalitions,
            "padding": padding,
            "pad_waste_fraction": padding / slots_total if slots_total else None,
            "epochs_trained": epochs,
        },
        "resilience": {
            "retries": retries,
            "backoff_s": backoff_s,
            "cap_halvings": cap_halvings,
            "cpu_degraded": cpu_fallbacks > 0,
            "cpu_batches": cpu_batches,
            "cpu_coalitions": cpu_coalitions,
            # 2-D ladder dead ends (LadderExhaustedError raised): the
            # sweep could not make progress at any cap and had no CPU
            # rung — under the service this quarantines one tenant's job
            "ladder_exhausted": ladder_exhausted,
            "faults_injected": faults_injected,
        },
        "per_width": per_width,
        "compiles": compiles,
        "estimators": estimators,
    }
    if bank_compiles or bank_wait_s:
        report["program_bank"] = {
            "compiles": bank_compiles,
            "compiles_overlapped": bank_compiles_overlapped,
            "overlapped_s": compile_overlapped_s,
            # wall-clock the sweep spent BLOCKED on the background
            # worker (already included in wallclock.compile_s)
            "waited_s": bank_wait_s,
        }
    if hbm is not None:
        # the donation/HBM view: modeled per-coalition footprint, the
        # buffer-donation saving, and the coalition-cap autotune before
        # vs after donation (the knob headroom donation buys)
        report["hbm"] = {
            "param_bytes": hbm.get("param_bytes"),
            "slot_count": hbm.get("slot_count"),
            "donation": hbm.get("donation"),
            "per_coalition_bytes": hbm.get("per_coalition_bytes"),
            "donated_bytes_per_coalition":
                hbm.get("donated_bytes_per_coalition"),
            "cap_before_donation": hbm.get("cap_before_donation"),
            "cap_after_donation": hbm.get("cap_after_donation"),
            "cap_effective": hbm.get("cap_effective"),
            "hbm_bytes_limit": hbm.get("hbm_bytes_limit"),
            "peak_in_use_bytes": hbm.get("peak_in_use_bytes"),
        }
    if per_method:
        report["memo"]["per_method"] = {
            m: {"requested": d["requested"],
                "hits": max(d["requested"] - d["misses"], 0),
                "misses": d["misses"],
                "hit_rate": (max(d["requested"] - d["misses"], 0)
                             / d["requested"]
                             if d["requested"] else None)}
            for m, d in sorted(per_method.items())}
    if recon_batches or recorded is not None:
        # retrain-free runs only: recorded-update memory, reconstruction
        # throughput, and the eval-vs-train pass split that PROVES the
        # asymptotic claim (training passes only from the recording run)
        report["reconstruction"] = {
            "recorded_rounds": (recorded or {}).get("rounds"),
            "recorded_partners": (recorded or {}).get("partners"),
            "recorded_update_bytes": (recorded or {}).get("memory_bytes"),
            "recording_seconds": (recorded or {}).get("seconds"),
            "recording_partner_passes":
                (recorded or {}).get("training_passes"),
            "reconstructions": recon_coalitions,
            "recon_batches": recon_batches,
            "reconstructions_per_s":
                recon_coalitions / recon_s if recon_s else None,
            "train_partner_passes": partner_passes,
            "train_batches": batches - recon_batches,
        }
    if fence_samples or flops_total:
        # device-time truth: fenced device-step samples (the measured
        # side) and the host-overhead split. The extrapolation rule is
        # per-COALITION (batch widths vary): device_s ≈ fenced seconds ×
        # TRAINING coalitions / fenced coalitions — eval-only
        # reconstruction coalitions cost orders of magnitude less and
        # are excluded from the training-rate extrapolation (their count
        # is reported separately). With fences off but XLA cost known, a
        # peak figure yields the cost-model estimate instead (an
        # optimistic lower bound — assumes peak-rate execution).
        fs = sorted(fence_samples)
        # eval-only reconstruction AND CPU-degraded-rung coalitions are
        # excluded: both run at rates wildly different from a fenced
        # device training batch (the CPU rung no longer fences at all)
        train_coalitions = coalitions - recon_coalitions - cpu_coalitions
        if fenced_coalitions and train_coalitions > 0:
            device_s = (sum(fence_samples) * train_coalitions
                        / fenced_coalitions)
            basis = "fenced"
        elif flops_total and peak_flops:
            device_s = flops_total / peak_flops
            basis = "cost_model"
        else:
            device_s, basis = None, None
        report["device_time"] = {
            "fence_interval": fence_interval,
            "fenced_batches": len(fence_samples),
            "fenced_coalitions": fenced_coalitions,
            "device_step_s": {
                "count": len(fs),
                "sum": sum(fs),
                "mean": sum(fs) / len(fs) if fs else None,
                "p50": _pctl(fs, 0.50),
                "p95": _pctl(fs, 0.95),
                "max": fs[-1] if fs else None,
            },
            "device_s": device_s,
            "basis": basis,
            # eval-only reconstruction / CPU-degraded coalitions
            # excluded from the training-rate extrapolation above
            # (billed at host span by the meter)
            "eval_coalitions_excluded": recon_coalitions,
            "degraded_coalitions_excluded": cpu_coalitions,
            # the host-overhead split the fences make meaningful:
            # enqueue (dispatch spans) vs device (above) vs harvest
            "enqueue_s": dispatch_s,
            "harvest_s": harvest_s,
            "prep_s": prep_s,
        }
    if roof:
        # per-program roofline: XLA-modeled flops/bytes per bundle
        # execution against the fleet's peak FLOP/s and HBM bandwidth.
        # Achieved rates use measured fenced device time when the
        # program has samples, the (pipelining-inflated) host span
        # otherwise — the basis says which.
        rows = []
        for (slot_count, width), rb in sorted(
                roof.items(), key=lambda kv: (kv[0][0] is None,
                                              kv[0][0] or 0, kv[0][1])):
            if rb["fenced_s"]:
                ach_f = rb["fenced_flops"] / rb["fenced_s"]
                ach_b = rb["fenced_bytes"] / rb["fenced_s"]
                basis = "device_fenced"
            elif rb["span_s"]:
                ach_f = rb["flops"] / rb["span_s"]
                ach_b = rb["bytes"] / rb["span_s"]
                basis = "host_span"
            else:
                ach_f = ach_b = basis = None
            rows.append({
                "slot_count": slot_count, "width": width,
                "batches": rb["batches"],
                "flops_per_batch": rb["flops"] / rb["batches"],
                "bytes_per_batch": rb["bytes"] / rb["batches"],
                "arithmetic_intensity": (rb["flops"] / rb["bytes"]
                                         if rb["bytes"] else None),
                "achieved_flops_per_s": ach_f,
                "achieved_bytes_per_s": ach_b,
                "basis": basis,
                "mfu": (ach_f / peak_flops
                        if ach_f and peak_flops else None),
                "hbm_fraction": (ach_b / hbm_bytes_per_s
                                 if ach_b and hbm_bytes_per_s else None),
            })
        report["roofline"] = {"peak_flops": peak_flops,
                              "hbm_peak_bytes_per_s": hbm_bytes_per_s,
                              "programs": rows}
    if (live_queries or live_appends or live_recovers or live_evictions
            or live_restores or live_ingested):
        # the live contributivity tier's view: fresh-query latency (memo
        # hits kept separate — they answer in microseconds and would
        # flatter the quantiles), evaluation/pruning totals, and the
        # resident-round level the latest query saw
        fresh = sorted(d for d, a in live_queries if not a.get("memo_hit"))
        per_m: dict = {}
        for _d, a in live_queries:
            m = a.get("method", "?")
            per_m[m] = per_m.get(m, 0) + 1
        report["live"] = {
            "queries": len(live_queries),
            "memo_hits": sum(1 for _d, a in live_queries
                             if a.get("memo_hit")),
            "evaluations": sum(int(a.get("evaluations") or 0)
                               for _d, a in live_queries),
            "pruned_coalitions": sum(int(a.get("pruned") or 0)
                                     for _d, a in live_queries),
            "rounds_appended": live_appends,
            "recovered_games": live_recovers,
            # the residency tier (live/residency.py): evictions seen in
            # the collected region, restores + their WAL-replay latency
            # quantiles (ingested counts the POST /live/<t>/round path)
            "evictions": live_evictions,
            "restores": len(live_restores),
            "restore_s": {
                "count": len(live_restores),
                "p50": _pctl(sorted(live_restores), 0.50),
                "p95": _pctl(sorted(live_restores), 0.95),
                "max": max(live_restores) if live_restores else None,
            },
            "rounds_ingested": live_ingested,
            "rounds_resident": (int(live_queries[-1][1].get("rounds", 0))
                                if live_queries else None),
            "per_method": per_m,
            "query_s": {
                "count": len(fresh),
                "p50": _pctl(fresh, 0.50),
                "p95": _pctl(fresh, 0.95),
                "max": fresh[-1] if fresh else None,
            },
        }
    if plans:
        # the adaptive-planner row: how many method="auto" requests
        # resolved, to which concrete estimators, and the last resolved
        # plan in full (its reason is the routing-table row that fired)
        routed: dict = {}
        for p in plans:
            m = p.get("method", "?")
            routed[m] = routed.get(m, 0) + 1
        report["planner"] = {
            "auto_queries": len(plans),
            "routed": routed,
            "last": plans[-1],
        }
    if svc_tenants or svc_jobs:
        # the multi-tenant service view: job outcomes, the cross-tenant
        # program-packing win, and fair-share cost attribution — each
        # tenant's share of the service's metered DEVICE-seconds
        # (obs/devcost.py; span-seconds kept as host_share, and the
        # cost_share falls back to it for pre-devcost record streams)
        total_s = sum(t["seconds"] for t in svc_tenants.values())
        total_dev = sum(t.get("device_seconds", 0.0)
                        for t in svc_tenants.values())
        by_status: dict = {}
        for a in svc_jobs.values():
            s = a.get("status", "?")
            by_status[s] = by_status.get(s, 0) + 1
        report["service"] = {
            "jobs": len(svc_jobs),
            "completed": by_status.get("completed", 0),
            "quarantined": by_status.get("quarantined", 0),
            "cancelled": by_status.get("cancelled", 0),
            # overload-governor sheds: a classified outcome of its own,
            # never folded into cancelled/quarantined
            "shed": by_status.get("shed", 0),
            "recovered": sum(1 for a in svc_jobs.values()
                             if a.get("recovered")),
            "cross_tenant_packed_batches": sum(
                t["packed_batches"] for t in svc_tenants.values()),
            # cost_share bills by metered DEVICE-seconds when the stream
            # carries them (what the accelerator actually did for each
            # tenant), falling back to the old span-seconds share for
            # pre-devcost streams; host_share is always the span view
            "cost_basis": ("device_seconds"
                           if any(t.get("device_seconds")
                                  for t in svc_tenants.values())
                           else "host_span"),
            "per_tenant": {
                name: {**t,
                       "host_share": (t["seconds"] / total_s
                                      if total_s else None),
                       "cost_share": (
                           t.get("device_seconds", 0.0) / total_dev
                           if total_dev else
                           (t["seconds"] / total_s if total_s else None))}
                for name, t in sorted(svc_tenants.items())},
        }
        # the per-tenant SLO view: exact quantiles over the collected
        # region (the live /metrics endpoint serves the same series as
        # log-bucket histograms). Old record streams (pre-SLO
        # service.job events) simply have empty latency lists.
        slo: dict = {}
        tenants = (set(svc_slice_durs) | set(svc_job_faults)
                   | {a.get("tenant", "?") for a in svc_jobs.values()})
        for tn in sorted(tenants):
            jobs = [a for a in svc_jobs.values()
                    if a.get("tenant", "?") == tn]
            qw = [a["queue_wait_sec"] for a in jobs
                  if a.get("queue_wait_sec") is not None]
            ttfv = [a["ttfv_sec"] for a in jobs
                    if a.get("ttfv_sec") is not None]
            sl = svc_slice_durs.get(tn, [])
            slo[tn] = {
                "jobs": len(jobs),
                "queue_wait_s": {"p50": _pctl(qw, 0.50),
                                 "p95": _pctl(qw, 0.95),
                                 "max": max(qw) if qw else None},
                "ttfv_s": {"p50": _pctl(ttfv, 0.50),
                           "p95": _pctl(ttfv, 0.95),
                           "max": max(ttfv) if ttfv else None},
                "slice_s": {"count": len(sl),
                            "p50": _pctl(sl, 0.50),
                            "p95": _pctl(sl, 0.95),
                            "p99": _pctl(sl, 0.99)},
                "deadline_misses": sum(
                    1 for a in jobs if a.get("deadline_missed")),
                "retries": svc_job_faults.get(tn, 0),
            }
        report["slo"] = slo
    if rtr["routed"] or rtr["resubmits"] or rtr["failovers"]:
        # the fleet-router row: how the front spread work over shards and
        # what it cost to keep jobs alive through redirects and deaths —
        # runs without a router produce no row at all
        report["router"] = {
            **rtr,
            "route_s": {"p50": _pctl(rtr_route_durs, 0.50),
                        "p95": _pctl(rtr_route_durs, 0.95),
                        "p99": _pctl(rtr_route_durs, 0.99)},
        }
    if num_audits or num_drift or num_ledger is not None:
        # the numeric-truth row: reduction audits run, order divergences
        # localized (with the worst ulp distance), and the ledger's
        # persisted size — old record streams produce no row at all
        report["numerics"] = {
            "audits": num_audits,
            "drift_events": num_drift,
            "max_ulp": num_max_ulp,
            "reduction_mode": (num_mode
                               or (num_ledger or {}).get("reduction_mode")),
            "ledger_entries": (num_ledger or {}).get("entries"),
            "ledger_path": (num_ledger or {}).get("path"),
        }
    if trust is not None:
        report["trust"] = trust
    if fits:
        report["fits"] = fits
    if metrics_snapshot is not None:
        report["metrics"] = metrics_snapshot
    return report


def format_report(report: dict) -> str:
    """Human-readable summary table of a sweep_report() dict."""
    w = report["wallclock"]
    m = report["memo"]
    b = report["batches"]
    lines = ["sweep report:"]
    line = (
        f"  wall-clock  evaluate={w['evaluate_s']:.2f}s  "
        f"compile={w['compile_s']:.2f}s  prep={w.get('prep_s', 0.0):.2f}s  "
        f"dispatch={w['dispatch_s']:.2f}s  "
        f"harvest={w['harvest_s']:.2f}s")
    if w.get("compile_overlapped_s"):
        line += f"  compile_overlapped={w['compile_overlapped_s']:.2f}s"
    lines.append(line)
    pb = report.get("program_bank")
    if pb is not None:
        line = (f"  bank        compiles={pb['compiles']}  "
                f"overlapped={pb['compiles_overlapped']} "
                f"({pb['overlapped_s']:.2f}s off the serial path)")
        if pb.get("waited_s"):
            line += f"  waited={pb['waited_s']:.2f}s"
        lines.append(line)
    hr = m["hit_rate"]
    lines.append(
        f"  memo        requested={m['requested']}  hits={m['hits']}  "
        f"misses={m['misses']}  hit_rate="
        + (f"{hr:.1%}" if hr is not None else "n/a"))
    for meth, d in (m.get("per_method") or {}).items():
        mhr = d.get("hit_rate")
        lines.append(
            f"    memo[{meth}]  requested={d['requested']}  "
            f"hits={d['hits']}  misses={d['misses']}  hit_rate="
            + (f"{mhr:.1%}" if mhr is not None else "n/a"))
    pw = b["pad_waste_fraction"]
    lines.append(
        f"  batches     n={b['count']}  coalitions={b['coalitions']}  "
        f"padding={b['padding']}  pad_waste="
        + (f"{pw:.1%}" if pw is not None else "n/a")
        + f"  epochs={b['epochs_trained']}")
    h = report.get("hbm")
    if h is not None:
        # the donation story in one line: what one coalition costs, what
        # donation saved, and the cap headroom it bought
        per = h.get("per_coalition_bytes")
        saved = h.get("donated_bytes_per_coalition")
        peak = h.get("peak_in_use_bytes")
        lines.append(
            "  hbm         per_coalition="
            + (f"{per / 1e6:.1f}MB" if per is not None else "n/a")
            + "  donated_saving="
            + (f"{saved / 1e6:.1f}MB" if saved else "0")
            + f"  cap {h.get('cap_before_donation', '?')}"
              f"->{h.get('cap_after_donation', '?')}"
              f" (effective {h.get('cap_effective', '?')})"
            + "  peak_in_use="
            + (f"{peak / 1e6:.1f}MB" if peak is not None else "n/a"))
    r = report.get("resilience")
    if r is not None:
        # rendered even when all-zero: a clean run should SAY it was clean
        line = (f"  resilience  retries={r['retries']}  "
                f"backoff={r['backoff_s']:.2f}s  "
                f"cap_halvings={r['cap_halvings']}  "
                f"cpu_batches={r['cpu_batches']}")
        if r.get("cpu_coalitions"):
            line += f"  cpu_coalitions={r['cpu_coalitions']}"
        if r.get("ladder_exhausted"):
            line += f"  ladder_exhausted={r['ladder_exhausted']}"
        if r.get("faults_injected"):
            line += f"  faults_injected={r['faults_injected']}"
        lines.append(line)
    nm = report.get("numerics")
    if nm is not None:
        # the numeric-truth row: reduction mode, audits run, localized
        # order divergences (worst ulp distance), ledger size
        line = (f"  numerics    mode={nm.get('reduction_mode') or '?'}  "
                f"audits={nm['audits']}  drift_events={nm['drift_events']}"
                f"  max_ulp={nm['max_ulp']}")
        if nm.get("ledger_entries") is not None:
            line += f"  ledger_entries={nm['ledger_entries']}"
        lines.append(line)
    svc = report.get("service")
    if svc is not None:
        # the multi-tenant service view: outcomes + the packing win, then
        # one fair-share line per tenant
        line = (
            f"  service     jobs={svc['jobs']}  "
            f"completed={svc['completed']}  "
            f"quarantined={svc['quarantined']}  "
            f"cancelled={svc['cancelled']}  "
            f"recovered={svc['recovered']}  "
            f"packed_batches={svc['cross_tenant_packed_batches']}")
        if svc.get("shed"):
            line += f"  shed={svc['shed']}"
        lines.append(line)
        for name, t in (svc.get("per_tenant") or {}).items():
            share = t.get("cost_share")
            host = t.get("host_share")
            line = (
                f"    tenant[{name}]  slices={t['slices']}  "
                f"batches={t['batches']}  coalitions={t['coalitions']}  "
                f"samples={t['samples']}  span={t['seconds']:.2f}s")
            if t.get("device_seconds"):
                line += f"  device={t['device_seconds']:.2f}s"
            line += ("  share="
                     + (f"{share:.1%}" if share is not None else "n/a"))
            if (host is not None and share is not None
                    and svc.get("cost_basis") == "device_seconds"):
                line += f" (host={host:.1%})"
            lines.append(line)
    slo = report.get("slo")
    if slo:
        def _q(d, k):
            v = d.get(k)
            return f"{v:.3f}" if v is not None else "n/a"
        for name, s in sorted(slo.items()):
            qw, tf, sl = s["queue_wait_s"], s["ttfv_s"], s["slice_s"]
            lines.append(
                f"  slo[{name}]  jobs={s['jobs']}  "
                f"queue_wait p50/p95={_q(qw, 'p50')}/{_q(qw, 'p95')}s  "
                f"ttfv p50={_q(tf, 'p50')}s  "
                f"slice p50/p95/p99={_q(sl, 'p50')}/{_q(sl, 'p95')}/"
                f"{_q(sl, 'p99')}s  "
                f"deadline_misses={s['deadline_misses']}  "
                f"retries={s['retries']}")
    rt = report.get("router")
    if rt is not None:
        rq = rt.get("route_s") or {}

        def _rq(k):
            v = rq.get(k)
            return f"{v:.3f}" if v is not None else "n/a"
        lines.append(
            f"  router      routed={rt['routed']}  "
            f"resubmits={rt['resubmits']}  repins={rt['repins']}  "
            f"failovers={rt['failovers']}"
            + (f" (jobs={rt['failover_jobs']})"
               if rt.get("failover_jobs") else "")
            + f"  exhausted={rt['budget_exhausted']}  "
            f"route p50/p95/p99={_rq('p50')}/{_rq('p95')}/{_rq('p99')}s")
    lv = report.get("live")
    if lv is not None:
        q = lv.get("query_s") or {}

        def _s(v):
            return f"{v:.3f}s" if v is not None else "n/a"
        lines.append(
            f"  live        queries={lv['queries']}  "
            f"memo_hits={lv['memo_hits']}  "
            f"evaluations={lv['evaluations']}  "
            f"pruned={lv['pruned_coalitions']}  "
            f"rounds={lv.get('rounds_resident') if lv.get('rounds_resident') is not None else '?'}"
            + (f"  recovered={lv['recovered_games']}"
               if lv.get("recovered_games") else "")
            + (f"  evicted/restored={lv['evictions']}/{lv['restores']}"
               if lv.get("evictions") or lv.get("restores") else "")
            + (f"  ingested={lv['rounds_ingested']}"
               if lv.get("rounds_ingested") else "")
            + f"  query p50/p95={_s(q.get('p50'))}/{_s(q.get('p95'))}")
    pl = report.get("planner")
    if pl is not None:
        last = pl.get("last") or {}
        routed = ", ".join(f"{m}x{c}"
                           for m, c in sorted(pl["routed"].items()))
        lines.append(
            f"  planner     auto={pl['auto_queries']}  routed=[{routed}]"
            f"  last={last.get('method', '?')}"
            f" (est {last.get('est_evals', '?')} evals"
            f" ~{last.get('est_cost_sec', 0.0):.2f}s,"
            f" basis {last.get('cost_basis', '?')})")
    rc = report.get("reconstruction")
    if rc is not None:
        mem = rc.get("recorded_update_bytes")
        rps = rc.get("reconstructions_per_s")
        lines.append(
            f"  reconstruct rounds={rc.get('recorded_rounds') or '?'}  "
            "update_mem="
            + (f"{mem / 1e6:.1f}MB" if mem is not None else "n/a")
            + f"  reconstructions={rc.get('reconstructions', 0)}  recons/s="
            + (f"{rps:.1f}" if rps is not None else "n/a")
            + f"  passes train/eval={rc.get('train_partner_passes', 0)}/0"
            + f"  batches train/eval={rc.get('train_batches', 0)}"
              f"/{rc.get('recon_batches', 0)}")
    t = report.get("trust")
    if t is not None:
        # the answer-trust view — how wide the per-partner CIs are and how
        # stable the ranking is. `source` tells seed volatility
        # (seed_ensemble) from one run's sampling noise (mc_blocks, the
        # retrain-free estimators); pre-source rows render without it.
        line = (f"  trust       ensemble={t.get('ensemble', '?')}  "
                + (f"source={t['source']}  " if t.get("source") else "")
                + f"kendall_tau="
                + (f"{t['kendall_tau']:.3f}"
                   if t.get("kendall_tau") is not None else "n/a"))
        mean = t.get("mean") or []
        lo = t.get("ci_low") or []
        hi = t.get("ci_high") or []
        if mean and len(lo) == len(mean) and len(hi) == len(mean):
            pct = int(round(100 * t.get("alpha", 0.95)))
            cells = [f"p{i}: {m:.3f}±{(h - l) / 2:.3f}"
                     for i, (m, l, h) in enumerate(zip(mean, lo, hi))]
            line += f"  ci{pct}=[" + ", ".join(cells) + "]"
        lines.append(line)
    c = report.get("compute") or {}
    if c.get("train_samples"):
        sps = c.get("samples_per_s")
        line = (f"  compute     samples={c['train_samples']}  "
                f"partner_passes={c['partner_passes']}  samples/s="
                + (f"{sps:.0f}" if sps is not None else "n/a"))
        fps = c.get("model_flops_per_s")
        if fps is not None:
            line += ("  model_flops/s=" +
                     (f"{fps / 1e12:.2f}T" if fps >= 1e12 else
                      f"{fps / 1e9:.2f}G" if fps >= 1e9 else
                      f"{fps / 1e6:.2f}M"))
            mfu = c.get("mfu_proxy")
            line += ("  mfu_proxy=" + (f"{mfu:.2%}" if mfu is not None
                                       else "n/a"))
        mx = c.get("mfu_xla")
        if mx is not None:
            # the XLA-derived figure supersedes the analytic proxy (both
            # stay rendered; the basis says whether the denominator was
            # measured device time or host span)
            line += (f"  mfu_xla={mx:.2%}"
                     + (f" [{c['mfu_xla_basis']}]"
                        if c.get("mfu_xla_basis") else ""))
        lines.append(line)
    dt = report.get("device_time")
    if dt is not None:
        st = dt.get("device_step_s") or {}
        line = (f"  device      fenced={dt.get('fenced_batches', 0)} "
                f"batches ({dt.get('fenced_coalitions', 0)} coalitions"
                + (f", 1/{dt['fence_interval']}"
                   if dt.get("fence_interval") else "") + ")")
        if st.get("count"):
            mean = st.get("mean")
            p95 = st.get("p95")
            line += ("  step mean="
                     + (f"{mean:.3f}s" if mean is not None else "n/a")
                     + "  p95="
                     + (f"{p95:.3f}s" if p95 is not None else "n/a"))
        ds = dt.get("device_s")
        if ds is not None:
            line += (f"  device_s~{ds:.2f}"
                     + (f" [{dt['basis']}]" if dt.get("basis") else ""))
        line += (f"  enqueue={dt.get('enqueue_s', 0.0):.2f}s  "
                 f"harvest={dt.get('harvest_s', 0.0):.2f}s")
        lines.append(line)
    rl = report.get("roofline")
    if rl and rl.get("programs"):
        def _rate(v, unit):
            if v is None:
                return "n/a"
            return (f"{v / 1e12:.2f}T{unit}" if v >= 1e12 else
                    f"{v / 1e9:.2f}G{unit}" if v >= 1e9 else
                    f"{v / 1e6:.2f}M{unit}")
        for r in rl["programs"]:
            ai = r.get("arithmetic_intensity")
            line = (f"  roofline    ({str(r['slot_count']):>4}, "
                    f"{r['width']:4d})  "
                    f"flops/batch={_rate(r.get('flops_per_batch'), 'F')}  "
                    "AI="
                    + (f"{ai:.1f}F/B" if ai is not None else "n/a")
                    + "  achieved="
                    + _rate(r.get("achieved_flops_per_s"), "F/s"))
            if r.get("mfu") is not None:
                line += f" ({r['mfu']:.1%} peak)"
            if r.get("hbm_fraction") is not None:
                line += (f"  bytes="
                         + _rate(r.get("achieved_bytes_per_s"), "B/s")
                         + f" ({r['hbm_fraction']:.1%} HBM)")
            if r.get("basis"):
                line += f" [{r['basis']}]"
            lines.append(line)
    if report["per_width"]:
        lines.append("  throughput per bucket (slots, width): "
                     "batches  coal  epochs  span-s  coal/s")
        for r in report["per_width"]:
            cps = r["coalitions_per_s"]
            lines.append(
                f"    ({str(r['slot_count']):>4}, {r['width']:4d})      "
                f"{r['batches']:4d}  {r['coalitions']:5d}  {r['epochs']:5d}  "
                f"{r['seconds']:7.2f}  "
                + (f"{cps:6.2f}" if cps is not None else "   n/a"))
    for fn, c in sorted(report["compiles"].items()):
        lines.append(f"  compile     {fn}: {c['count']}x  {c['seconds']:.2f}s")
    for e in report["estimators"]:
        lines.append(f"  estimator   {e['method']}: {e['seconds']:.2f}s")
    return "\n".join(lines)


def write_report(path: str, report: dict) -> None:
    """Atomic JSON sidecar write (temp + rename, like the engine's
    cache autosave)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, default=str)
    os.replace(tmp, path)
