"""Fleet observability plane: one view of a W-shard run.

PR 15 made execution fleet-scale (parallel/fleet.py shards one sweep
across OS processes/hosts); this module makes the RESULTING system
observable as one thing instead of W disjoint ones. Three instruments:

  FleetCollector      merges every shard's metrics into a cluster
                      snapshot. Sources, all optional and composable:
                      HTTP peers (each shard's token-authenticated
                      /varz), the published fleet state dir (serverless
                      service shards — scheduler._publish_fleet_state
                      embeds its metrics snapshot there), and a fleet
                      sweep out_dir (each worker's result_shardI.json
                      carries its final snapshot). Because every
                      histogram shares metrics.LOG_BUCKET_BOUNDS, the
                      merge (`metrics.merge_snapshots`) is EXACT: the
                      cluster p99 of `service.queue_wait_sec{tenant=t0}`
                      equals the quantile over the pooled raw samples at
                      bucket granularity — not an average of per-shard
                      quantiles, which is statistically meaningless.
                      Served as /fleet/metrics + /fleet/varz by
                      obs/export.py, with the PR-12 tenant redaction
                      applied to every aggregated row.

  merge_fleet_traces  one Perfetto timeline from a fleet out_dir: the
                      coordinator's span stream plus every shard's,
                      each shard REBASED onto the coordinator clock
                      (midpoint rule over the 4-timestamp handshake:
                      coordinator spawn/done-seen vs worker start/end,
                      NTP-style — symmetric spawn/teardown latency
                      cancels, cross-host skew does not survive) and
                      drawn as its own track group (one pid per shard),
                      with flow arrows linking each `fleet.shard`
                      dispatch event to that shard's `fleet.shard_run`
                      root span. CLI: scripts/fleet_trace_merge.py.

  cluster_snapshot    the one-call convenience the incident bundler and
                      the fleet selfcheck use.

Everything here is read-side: no instrument in this module changes a
computed number, and a missing source degrades to an error row, never an
exception into the caller.
"""

from __future__ import annotations

import json
import os
import re
import time

from . import metrics as obs_metrics
from . import trace as obs_trace

logger = __import__("logging").getLogger("mplc_tpu")

# comma-separated host:port (or http://...) peers the collector scrapes;
# sidecar-class knob (constants.ENV_KNOBS) — observability only
FLEET_PEERS_ENV = "MPLC_TPU_FLEET_PEERS"

# the SLO histograms the cluster rollup surfaces as first-class quantile
# rows (everything else still merges — these just get the shortcut view)
_SLO_HISTOGRAMS = ("service.queue_wait_sec",
                   "service.time_to_first_value_sec",
                   "service.slice_sec", "live.query_sec")

_KEY_RE = re.compile(r"^(?P<name>[^{]+)\{(?P<labels>.*)\}$")
_TENANT_IN_KEY_RE = re.compile(r"tenant=([^},]*)")


def _parse_key(key: str) -> tuple:
    """(base name, labels dict) for a registry `name{k=v,...}` key."""
    m = _KEY_RE.match(key)
    if m is None:
        return key, {}
    labels = {}
    for part in m.group("labels").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return m.group("name"), labels


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# the collector
# ---------------------------------------------------------------------------

class FleetCollector:
    """Scrape/read every shard's metrics into one cluster snapshot.

    `peers`      list of `host:port` (or full `http://` URLs): each is
                 GET `<peer>/varz` with the operator bearer token — the
                 collector is an OPERATOR instrument; per-tenant
                 redaction happens when the AGGREGATE is served, not on
                 the shard hop.
    `state_dir`  the fleet state dir (`MPLC_TPU_FLEET_STATE_DIR`):
                 rows ride `cluster_view`'s stale rule as per-shard
                 freshness flags, and shards that embedded a metrics
                 snapshot in their published state contribute to the
                 merge without any HTTP surface (serverless mode).
    `out_dir`    a fleet sweep output dir: result_shardI.json snapshots
                 (subprocess fleets — each worker had its own registry).
    """

    def __init__(self, peers: "list | None" = None,
                 token: "str | None" = None,
                 state_dir: "str | None" = None,
                 out_dir: "str | None" = None,
                 stale_sec: float = 30.0, timeout_s: float = 5.0):
        self.peers = list(peers or [])
        self.token = token
        self.state_dir = state_dir
        self.out_dir = out_dir
        self.stale_sec = float(stale_sec)
        self.timeout_s = float(timeout_s)

    # -- per-source readers -------------------------------------------------

    def _scrape_peer(self, peer: str) -> dict:
        import urllib.request
        url = peer if "://" in peer else f"http://{peer}"
        req = urllib.request.Request(url.rstrip("/") + "/varz")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                doc = json.loads(r.read().decode())
            row = {"source": "http", "peer": peer, "ok": True,
                   "fresh": True, "pid": doc.get("pid"),
                   "metrics": doc.get("metrics")}
            sched = doc.get("scheduler")
            if isinstance(sched, dict):
                for k in ("queue_depth", "jobs_pending", "closed"):
                    if k in sched:
                        row[k] = sched[k]
            return row
        except Exception as e:  # noqa: BLE001 — a dead peer is a row
            return {"source": "http", "peer": peer, "ok": False,
                    "fresh": False, "error": str(e)[:200]}

    def _state_rows(self) -> tuple:
        from ..parallel import fleet as _fleet
        view = _fleet.cluster_view(self.state_dir,
                                   stale_sec=self.stale_sec,
                                   include_metrics=True)
        rows = {}
        for sid, doc in (view.get("shards") or {}).items():
            rows[sid] = {"source": "state_dir", "ok": True,
                         "fresh": not doc.get("stale"),
                         "age_sec": doc.get("age_sec"),
                         "queue_depth": doc.get("queue_depth"),
                         "jobs_pending": doc.get("jobs_pending"),
                         "closed": doc.get("closed"),
                         "metrics": doc.get("metrics")}
        return rows, view

    def _result_rows(self) -> dict:
        rows = {}
        try:
            names = sorted(os.listdir(self.out_dir))
        except OSError:
            return rows
        for name in names:
            m = re.fullmatch(r"result_shard(\d+)\.json", name)
            if not m:
                continue
            doc = _read_json(os.path.join(self.out_dir, name))
            if not isinstance(doc, dict):
                continue
            i = int(m.group(1))
            done = os.path.exists(
                os.path.join(self.out_dir, f".shard{i}.done"))
            sid = ((doc.get("fleet") or {}).get("shard_id")
                   or f"shard{i}")
            rows[sid] = {"source": "result", "ok": True, "fresh": done,
                         "shard_index": i,
                         "run_id": (doc.get("fleet") or {}).get("run_id"),
                         "sweep_s": doc.get("sweep_s"),
                         "coalitions": len(doc.get("subsets") or []),
                         "metrics": doc.get("metrics")}
        return rows

    # -- assembly -----------------------------------------------------------

    def collect(self) -> dict:
        """One cluster snapshot: per-shard rows (freshness-flagged),
        the exact merged metrics, per-tenant SLO quantile shortcuts,
        summed device-seconds metering, and the state-dir cluster
        totals when available."""
        with obs_trace.span("fleet.collect",
                            sources=sum(1 for s in (self.peers,
                                                    self.state_dir,
                                                    self.out_dir) if s)):
            shards: dict = {}
            cluster = None
            if self.out_dir:
                shards.update(self._result_rows())
            if self.state_dir:
                rows, cluster = self._state_rows()
                shards.update(rows)
            for peer in self.peers:
                row = self._scrape_peer(peer)
                shards[f"peer:{peer}"] = row
            snaps = []
            for sid, row in shards.items():
                obs_trace.event("fleet.scrape", shard=sid,
                                source=row.get("source"),
                                ok=bool(row.get("ok")))
                snap = row.pop("metrics", None)
                if isinstance(snap, dict):
                    row["has_metrics"] = True
                    snaps.append(snap)
            merged = obs_metrics.merge_snapshots(snaps)
            out = {
                "ts": time.time(),
                "shards": shards,
                "shard_count": len(shards),
                "fresh_shards": sum(1 for r in shards.values()
                                    if r.get("fresh")),
                "merged_sources": len(snaps),
                "merged": merged,
                "slo": _slo_quantiles(merged),
            }
            out.update(_device_seconds(merged))
            if cluster is not None:
                out["cluster"] = {k: v for k, v in cluster.items()
                                  if k != "shards"}
        return out

    def fleet_varz(self) -> dict:
        """The /fleet/varz body."""
        return {"pid": os.getpid(), "collector": {
            "peers": list(self.peers), "state_dir": self.state_dir,
            "out_dir": self.out_dir, "stale_sec": self.stale_sec},
            **self.collect()}


def _slo_quantiles(merged: dict) -> dict:
    """Cluster-true quantile shortcuts for the SLO histograms, keyed by
    their full (tenant-labeled) registry keys — the rows an operator
    dashboards without digging bucket arrays out of `merged`."""
    out = {}
    for key, h in (merged.get("histograms") or {}).items():
        base, _labels = _parse_key(key)
        if base in _SLO_HISTOGRAMS and h.get("count"):
            out[key] = {"count": h["count"], "mean": h.get("mean"),
                        "p50": h.get("p50"), "p95": h.get("p95"),
                        "p99": h.get("p99")}
    return out


def _device_seconds(merged: dict) -> dict:
    """Summed device-seconds metering across shards: the fleet bill."""
    per_tenant: dict = {}
    total = 0.0
    for key, v in (merged.get("counters") or {}).items():
        base, labels = _parse_key(key)
        if base == "service.device_seconds":
            total += float(v or 0.0)
            tenant = labels.get("tenant")
            if tenant is not None:
                per_tenant[tenant] = (per_tenant.get(tenant, 0.0)
                                      + float(v or 0.0))
    out: dict = {"device_seconds_total": total}
    if per_tenant:
        out["tenant_device_seconds"] = per_tenant
    return out


# -- module-global collector (what /fleet/* serves) --------------------------

_collector: "FleetCollector | None" = None


def set_collector(c: "FleetCollector | None") -> None:
    global _collector
    _collector = c


def active_collector() -> "FleetCollector | None":
    return _collector


def collector_from_env() -> "FleetCollector | None":
    """A collector from the ambient knobs, or None when no source is
    configured: `MPLC_TPU_FLEET_PEERS` (comma-separated /varz peers,
    scraped with the `MPLC_TPU_METRICS_TOKEN` operator credential) and
    the fleet state dir."""
    from .. import constants
    peers = [p.strip() for p in
             (os.environ.get(FLEET_PEERS_ENV) or "").split(",")
             if p.strip()]
    state_dir = os.environ.get(constants.FLEET_STATE_DIR_ENV)
    if not peers and not state_dir:
        return None
    return FleetCollector(
        peers=peers, state_dir=state_dir,
        token=os.environ.get("MPLC_TPU_METRICS_TOKEN"))


def get_or_create_collector() -> "FleetCollector | None":
    """The installed collector, else one built from env (NOT installed —
    env may change between requests; cheap to rebuild)."""
    return _collector if _collector is not None else collector_from_env()


def cluster_snapshot(out_dir: "str | None" = None,
                     state_dir: "str | None" = None) -> dict:
    """One-call cluster snapshot over whatever sources exist — the
    incident bundler's and the selfcheck's entry point. Never raises."""
    try:
        return FleetCollector(out_dir=out_dir,
                              state_dir=state_dir).collect()
    except Exception as e:  # noqa: BLE001 — postmortem helper
        return {"error": str(e)[:500]}


# ---------------------------------------------------------------------------
# /fleet/metrics rendering (Prometheus text over the MERGED snapshot)
# ---------------------------------------------------------------------------

def fleet_metrics_text(merged: dict) -> str:
    """Prometheus text exposition of a merged snapshot. Series are
    prefixed `mplc_fleet_` so a scraper federating both the per-shard
    /metrics and the aggregate never double-counts a sample."""
    from . import export as _export
    lines = []
    typed: set = set()

    def emit(key, kind, render):
        name, labels = _parse_key(key)
        pname, plabels = _export._prom_parts(name, labels)
        pname = "mplc_fleet_" + pname[len("mplc_"):]
        if pname not in typed:
            typed.add(pname)
            lines.append(f"# TYPE {pname} {kind}")
        render(pname, plabels)

    for key, v in sorted((merged.get("counters") or {}).items()):
        emit(key, "counter", lambda n, la, v=v: lines.append(
            f"{n}{_export._label_str(la)} {_export._fmt(v)}"))
    for key, v in sorted((merged.get("gauges") or {}).items()):
        if v is None:
            continue
        emit(key, "gauge", lambda n, la, v=v: lines.append(
            f"{n}{_export._label_str(la)} {_export._fmt(v)}"))
    for key, h in sorted((merged.get("histograms") or {}).items()):
        bc = h.get("bucket_counts") or []

        def hist(n, la, h=h, bc=bc):
            cum = 0
            for bound, c in zip(obs_metrics.LOG_BUCKET_BOUNDS, bc):
                cum += c
                lines.append(
                    f"{n}_bucket"
                    f"{_export._label_str(dict(la, le=_export._fmt(bound)))}"
                    f" {cum}")
            cum += bc[-1] if len(bc) > len(obs_metrics.LOG_BUCKET_BOUNDS) \
                else 0
            lines.append(f'{n}_bucket'
                         f'{_export._label_str(dict(la, le="+Inf"))} {cum}')
            lines.append(f"{n}_sum{_export._label_str(la)} "
                         f"{_export._fmt(h.get('sum') or 0.0)}")
            lines.append(f"{n}_count{_export._label_str(la)} "
                         f"{int(h.get('count') or 0)}")
        emit(key, "histogram", hist)
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# merged Perfetto timeline
# ---------------------------------------------------------------------------

def _clock_offset(manifest: dict, result: "dict | None",
                  shard: int) -> float:
    """Seconds to ADD to a shard's timestamps to land them on the
    coordinator clock. Midpoint rule over the handshake's four
    timestamps — coordinator spawn (s) / done-seen (d) vs worker start
    (ws) / end (we):

        offset = ((s - ws) + (d - we)) / 2

    With zero skew, spawn->start latency and end->done-seen latency
    enter with opposite signs and cancel to their asymmetry; with skew,
    the skew survives intact (it appears identically in both terms).
    Degrades to the one-sided `s - ws` when the run has no done-seen
    record (crashed shard), and to 0 with no handshake at all."""
    clock = (result or {}).get("clock") or {}
    spawn = (manifest.get("spawn_ts") or {}).get(str(shard))
    if spawn is None:
        spawn = clock.get("coord_spawn_ts")
    done = (manifest.get("done_seen_ts") or {}).get(str(shard))
    ws = clock.get("worker_start_ts")
    we = clock.get("worker_end_ts")
    if spawn is not None and ws is not None:
        if done is not None and we is not None:
            return ((spawn - ws) + (done - we)) / 2.0
        return float(spawn) - float(ws)
    return 0.0


def merge_fleet_traces(out_dir: str) -> dict:
    """One Chrome-trace document from a fleet out_dir: the coordinator
    stream (trace_coordinator.jsonl) on pid 1, each shard's stream
    (trace_shardI.jsonl) rebased onto the coordinator clock and drawn
    as its own process-level track group (pid 10+I, named via
    process_name metadata), plus flow arrows from every `fleet.shard`
    dispatch event to the matching shard's `fleet.shard_run` root span.

    Returns {trace, shard_tracks, flow_links, offsets, records,
    torn_lines}; `trace` loads directly in https://ui.perfetto.dev."""
    from . import chrome_trace
    manifest = _read_json(
        os.path.join(out_dir, "fleet_trace_manifest.json")) or {}
    torn_total = 0
    coord_path = os.path.join(out_dir, "trace_coordinator.jsonl")
    coord_records: list = []
    if os.path.exists(coord_path):
        coord_records, torn = chrome_trace.read_jsonl(coord_path)
        torn_total += torn
        # inproc fleets: the coordinator's collector saw the shards'
        # records too; those live in the per-shard files (the writer
        # drops them, but tolerate older coordinator files)
        coord_records = [r for r in coord_records
                         if "fleet_shard" not in r]
    shard_streams: dict = {}
    offsets: dict = {}
    try:
        names = sorted(os.listdir(out_dir))
    except OSError:
        names = []
    for name in names:
        m = re.fullmatch(r"trace_shard(\d+)\.jsonl", name)
        if not m:
            continue
        i = int(m.group(1))
        records, torn = chrome_trace.read_jsonl(
            os.path.join(out_dir, name))
        torn_total += torn
        result = _read_json(
            os.path.join(out_dir, f"result_shard{i}.json"))
        off = _clock_offset(manifest, result, i)
        offsets[str(i)] = off
        for r in records:
            r["ts"] = float(r.get("ts") or 0.0) + off
        shard_streams[i] = records

    every = coord_records + [r for recs in shard_streams.values()
                             for r in recs]
    t0 = min((float(r.get("ts") or 0.0) for r in every), default=0.0)
    events: list = []
    # (pid, tid, ts_us, dur_us) of every fleet.shard_run root span and
    # every fleet.shard dispatch event, for the flow links
    roots: dict = {}
    dispatches: list = []

    def add_stream(records, pid, label):
        tids = []
        for rec in records:
            tid = int(rec.get("thread") or 0)
            if tid not in tids:
                tids.append(tid)
            ts_us = (float(rec.get("ts") or 0.0) - t0) * 1e6
            dur_us = max(float(rec.get("dur") or 0.0) * 1e6, 1.0)
            name = rec.get("name", "?")
            attrs = rec.get("attrs") or {}
            events.append({
                "name": name, "cat": name.split(".", 1)[0], "ph": "X",
                "ts": ts_us, "dur": dur_us, "pid": pid, "tid": tid,
                "args": {**attrs, "span_id": rec.get("id"),
                         "fleet_run": rec.get("fleet_run"),
                         "fleet_shard": rec.get("fleet_shard")},
            })
            if name == "fleet.shard_run":
                # first root per pid wins (a re-run shard re-roots)
                roots.setdefault(pid, (tid, ts_us, dur_us))
            elif name == "fleet.shard" and pid == 1:
                dispatches.append((attrs.get("shard"), tid, ts_us,
                                   dur_us))
        for i, tid in enumerate(tids):
            events.append({"name": "thread_name", "ph": "M", "ts": 0,
                           "pid": pid, "tid": tid,
                           "args": {"name": f"thread-{tid}"}})
            events.append({"name": "thread_sort_index", "ph": "M",
                           "ts": 0, "pid": pid, "tid": tid,
                           "args": {"sort_index": i}})
        events.append({"name": "process_name", "ph": "M", "ts": 0,
                       "pid": pid, "tid": 0, "args": {"name": label}})
        events.append({"name": "process_sort_index", "ph": "M", "ts": 0,
                       "pid": pid, "tid": 0,
                       "args": {"sort_index": pid}})

    add_stream(coord_records, 1, "fleet coordinator")
    for i in sorted(shard_streams):
        add_stream(shard_streams[i], 10 + i, f"shard {i}")

    flow_links = 0
    for shard, tid, ts_us, dur_us in dispatches:
        try:
            pid = 10 + int(shard)
        except (TypeError, ValueError):
            continue
        root = roots.get(pid)
        if root is None:
            continue
        rtid, rts, rdur = root
        flow_links += 1
        events.append({"name": "fleet.dispatch", "cat": "flow",
                       "ph": "s", "id": flow_links,
                       "ts": ts_us + min(0.5, dur_us / 2),
                       "pid": 1, "tid": tid})
        events.append({"name": "fleet.dispatch", "cat": "flow",
                       "ph": "f", "bp": "e", "id": flow_links,
                       "ts": rts + min(0.5, rdur / 2),
                       "pid": pid, "tid": rtid})

    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "mplc_tpu fleet trace merge",
                      "run_id": manifest.get("run_id"),
                      "shards": len(shard_streams),
                      "records": len(every),
                      "clock_offsets_s": offsets,
                      "flows": flow_links,
                      "torn_lines": torn_total},
    }
    return {"trace": trace, "shard_tracks": len(shard_streams),
            "flow_links": flow_links, "offsets": offsets,
            "records": len(every), "torn_lines": torn_total}
