"""Live telemetry endpoints: a zero-dependency stdlib HTTP thread serving
the metrics registry and service health while a sweep runs.

The batch-shaped obs layer (collect() + sweep_report after the run) is
useless to an operator of a LIVE multi-tenant service: queue depth,
per-tenant SLO quantiles and a stalled worker must be observable while
the process is running. This module starts — only when
`MPLC_TPU_METRICS_PORT` is set — one `ThreadingHTTPServer` daemon thread
with three routes:

  /metrics   Prometheus text exposition (version 0.0.4) rendered from
             `metrics.export_view()`: counters, gauges, and real
             histogram series (`_bucket{le=...}` from the shared log2
             bounds, `_sum`, `_count`) with labels (e.g. `tenant`)
             quoted per the format. Names are prefixed `mplc_` and
             sanitized (dots -> underscores); the bracketed
             per-executable suffix of `trainer.compiles[<fn>]` becomes
             an `item` label.
  /healthz   JSON liveness: 200 when every registered health provider
             reports healthy, 503 otherwise. The sweep service registers
             a provider exposing worker liveness, heartbeat age (flips
             unhealthy when a quantum stalls past
             `service.scheduler.STALL_HEALTHY_SEC` with a job running),
             queue depth and journal status.
  /varz      Full JSON state snapshot: the metrics registry plus every
             registered varz provider (service job table, program-bank
             contents when the bank module is loaded), a numerics block,
             and — when the process participates in a fleet — the
             cross-shard cluster view.
  /fleet/metrics, /fleet/varz
             The AGGREGATED cluster view (obs/fleet_view.py): metrics
             merged exactly across every shard the collector can reach
             (HTTP peers, published state dir), served with the same
             credential model — Prometheus text operator-only, the JSON
             view tenant-redacted per row.

With the env var UNSET nothing happens: no socket, no thread — the
instrumented paths cost exactly what they cost before. A plain port
value binds LOOPBACK only (the endpoints are unauthenticated by
default — tenant names, job tables, error strings); `host:port` opts
into wider exposure explicitly. Setting `MPLC_TPU_METRICS_TOKEN`
additionally gates /metrics and /varz behind bearer credentials — the
master token for the operator, `tenant_token(master, name)`-derived
credentials for tenants, whose /varz view has every other tenant's rows
redacted (`redact_varz`) — the first concrete step toward
mutually-distrusting consortium tenants sharing one telemetry plane.
Port 0 binds an ephemeral port (tests; the bound port is on
`TelemetryServer.port` and in the start-up log line). The server is a
process singleton: the first `start()` wins, later calls return it.

Providers are plain callables returning JSON-ready dicts, registered
under a name (`register_health`/`register_varz`); a provider that raises
is reported as an error entry, never a 500 — the telemetry plane must
not be takeable-down by the thing it observes.
"""

from __future__ import annotations

import hashlib
import hmac
import http.server
import json
import logging
import os
import re
import threading
import time
import urllib.parse
import warnings
import weakref

from . import metrics

logger = logging.getLogger("mplc_tpu")

METRICS_PORT_ENV = "MPLC_TPU_METRICS_PORT"
# Optional bearer token (first step of the ROADMAP secure-contributivity
# item): when set, /metrics and /varz require `Authorization: Bearer`
# credentials (401 otherwise; /healthz stays open for liveness probes).
# The master token is the OPERATOR credential (full unredacted view; the
# only credential /metrics accepts — Prometheus text has no redacted
# rendering); `tenant_token(master, name)` derives one per-tenant
# credential which, presented with `?tenant=<name>`, unlocks /varz with
# every OTHER tenant's rows redacted under HMAC-keyed tags — the viewer
# claim is authenticated, never self-declared. Unset = the loopback
# default behavior, unchanged.
METRICS_TOKEN_ENV = "MPLC_TPU_METRICS_TOKEN"

# Streaming round ingestion (the live tier's decoupled arrival path):
# when set to "1", the server grows `POST /live/<tenant>/round` — one
# live_round wire document per request, fed to the registered service
# sink (SweepService._ingest_live_round). Off by default: a MUTATING
# HTTP surface is an explicit operator decision, unlike the read-only
# routes above.
LIVE_INGEST_ENV = "MPLC_TPU_LIVE_INGEST"

# Routed submission (the fleet router's HTTP peer surface): when set to
# "1", the server grows `POST /router/submit` (one routed job
# submission, fed to the registered ShardServer sink) and
# `GET /router/job?id=` (terminal status + scores + the full v(S)
# table). Off by default for the same reason as live ingestion: a
# MUTATING HTTP surface is an explicit operator decision.
ROUTER_SERVE_ENV = "MPLC_TPU_ROUTER_SERVE"

_lock = threading.Lock()
_server: "TelemetryServer | None" = None
_health_providers: dict = {}
_varz_providers: dict = {}
_live_ingest_sinks: dict = {}
_router_sinks: dict = {}


# -- provider registry --------------------------------------------------------

def register_health(name: str, fn) -> None:
    """Register a health provider: `fn()` returns a JSON-ready dict; a
    `healthy: False` entry flips /healthz to 503. Pass a
    `weakref.WeakMethod` to auto-unregister when the owning object is
    collected (how SweepService registers: a dropped, never-shut-down
    service must not haunt /healthz forever)."""
    with _lock:
        _health_providers[name] = fn


def register_varz(name: str, fn) -> None:
    with _lock:
        _varz_providers[name] = fn


def register_live_ingest(name: str, fn) -> None:
    """Register a streaming-ingestion sink: `fn(tenant, doc)` feeds one
    decoded live_round wire document to the tenant's resident game and
    returns a JSON-ready ack. Same WeakMethod auto-unregister contract
    as the health/varz providers. The POST route only exists when
    `MPLC_TPU_LIVE_INGEST=1`."""
    with _lock:
        _live_ingest_sinks[name] = fn


def register_router(name: str, fn) -> None:
    """Register a routed-submission sink (service/router.ShardServer):
    `fn(op, payload)` handles `op="submit"` (one routed job wire
    document -> ack) and `op="job"` (`{"job": id}` -> status document).
    Same WeakMethod auto-unregister contract as the other registries;
    the /router/* routes only exist when `MPLC_TPU_ROUTER_SERVE=1`."""
    with _lock:
        _router_sinks[name] = fn


def unregister(name: str) -> None:
    with _lock:
        _health_providers.pop(name, None)
        _varz_providers.pop(name, None)
        _live_ingest_sinks.pop(name, None)
        _router_sinks.pop(name, None)


def _call_providers(providers: dict) -> dict:
    out = {}
    for name, fn in sorted(providers.items()):
        if isinstance(fn, weakref.WeakMethod):
            live = fn()
            if live is None:
                unregister(name)  # the owner was collected
                continue
            fn = live
        try:
            out[name] = fn()
        except Exception as e:  # a broken provider must not 500 the route
            out[name] = {"healthy": False, "error": str(e)[:500]}
    return out


def live_ingest(tenant: str, doc: dict) -> dict:
    """Dispatch one live_round wire document to the registered
    ingestion sinks. A tenant's game lives in exactly one service, so a
    sink that doesn't know the tenant raises KeyError and the next is
    tried. Raises LookupError with no sink registered (503), the last
    KeyError when none knows the tenant (404); the sink's ValueError
    (400) and LiveGameFull-with-retry_after_sec (429) propagate."""
    with _lock:
        sinks = dict(_live_ingest_sinks)
    last: "KeyError | None" = None
    for name, fn in sorted(sinks.items()):
        if isinstance(fn, weakref.WeakMethod):
            live = fn()
            if live is None:
                unregister(name)  # the owner was collected
                continue
            fn = live
        try:
            return fn(tenant, doc)
        except KeyError as e:
            last = e
    if last is not None:
        raise last
    raise LookupError("no live ingestion sink registered (is a "
                      "SweepService running in this process?)")


def router_dispatch(op: str, payload) -> dict:
    """Dispatch one routed-submission operation to the registered
    ShardServer sinks. Same contract shape as `live_ingest`: a sink
    that doesn't know the job raises KeyError and the next is tried;
    LookupError with no sink registered (503), the last KeyError when
    none knows the job (404); everything else propagates for the
    handler to classify (429/403/400)."""
    with _lock:
        sinks = dict(_router_sinks)
    last: "KeyError | None" = None
    for name, fn in sorted(sinks.items()):
        if isinstance(fn, weakref.WeakMethod):
            live = fn()
            if live is None:
                unregister(name)  # the owner was collected
                continue
            fn = live
        try:
            return fn(op, payload)
        except KeyError as e:
            last = e
    if last is not None:
        raise last
    raise LookupError("no routed-submission sink registered (is a "
                      "ShardServer running in this process?)")


def health_view() -> tuple[bool, dict]:
    """(healthy, body) for /healthz: healthy iff every provider is."""
    with _lock:
        providers = dict(_health_providers)
    body = _call_providers(providers)
    healthy = all(p.get("healthy", True) is not False
                  for p in body.values() if isinstance(p, dict))
    return healthy, {"healthy": healthy, "ts": time.time(),
                     "pid": os.getpid(), "providers": body}


def varz_view() -> dict:
    """Full JSON snapshot for /varz: metrics registry + varz providers +
    the program bank's contents when its module is already loaded (never
    force-imports jax into a lean process)."""
    with _lock:
        providers = dict(_varz_providers)
    out = {"ts": time.time(), "pid": os.getpid(),
           "metrics": metrics.snapshot()}
    out.update(_call_providers(providers))
    if "program_bank" not in out:
        try:
            import sys
            bank = sys.modules.get("mplc_tpu.contrib.bank")
            if bank is not None:
                out["program_bank"] = bank.bank_stats()
        except Exception as e:
            out["program_bank"] = {"error": str(e)[:200]}
    # numeric-truth plane (obs/numerics.py): process-level view — knob
    # states + the ledger/audit/drift counters. Carries no tenant
    # identities or per-tenant values, so the PR-12 redaction walk has
    # nothing to collapse here; the counters are aggregates by design.
    try:
        from .. import constants as _c
        snap = out["metrics"].get("counters", {}) if isinstance(
            out.get("metrics"), dict) else {}
        import sys as _sys
        _agg = _sys.modules.get("mplc_tpu.ops.aggregation")
        out["numerics"] = {
            "deterministic_reduce":
                os.environ.get(_c.DETERMINISTIC_REDUCE_ENV, "") == "1",
            # False = the optimization_barrier batching rule failed to
            # install and deterministic mode's fence silently no-ops —
            # the cross-topology bit-identity contract is weakened
            # (None = the jax-backed module isn't loaded in this
            # process; like the bank stats, never force-import jax here)
            "fusion_fence_ok": (_agg._BARRIER_OK if _agg is not None
                                else None),
            "audit_enabled":
                os.environ.get(_c.NUMERICS_AUDIT_ENV, "") == "1",
            "ledger_path": os.environ.get(_c.NUMERICS_LEDGER_ENV) or None,
            "ledger_records": snap.get("numerics.ledger_records", 0),
            "audits": snap.get("numerics.audits", 0),
            "drift_events": snap.get("numerics.drift_events", 0),
        }
    except Exception as e:
        out["numerics"] = {"error": str(e)[:200]}
    # fleet block (mirrors the /healthz block PR 15 added there): the
    # cross-shard cluster view + this process's shard identity and its
    # publish-failure counter, present whenever the process participates
    # in a fleet (state dir configured). Shard ids/queue rows are
    # identity-bearing in a consortium — redact_varz hashes them for
    # tenant-scoped viewers (queue depths stay scalars).
    try:
        from .. import constants as _c
        state_dir = os.environ.get(_c.FLEET_STATE_DIR_ENV)
        if state_dir:
            from ..parallel.fleet import cluster_view
            fv = cluster_view(state_dir)
            fv["shard_id"] = os.environ.get(_c.FLEET_SHARD_ID_ENV)
            cnt = out["metrics"].get("counters", {}) if isinstance(
                out.get("metrics"), dict) else {}
            fv["state_publish_errors"] = cnt.get(
                "fleet.state_publish_errors", 0)
            out["fleet"] = fv
    except Exception as e:
        out["fleet"] = {"error": str(e)[:200]}
    return out


# -- tenant credentials + redaction -------------------------------------------

# the per-job table key a redaction walk recognizes, and the row fields a
# non-viewer is still allowed to see (scheduling facts, no identity/work
# detail — enough to reason about queue fairness, nothing about the game)
_REDACTED_ROW_FIELDS = ("status", "priority", "age_sec")
# greedy to the closing brace: a tenant name containing ',' (legal in the
# registry's `name{tenant=...}` keys, which join label pairs with commas)
# must hash in FULL — swallowing a trailing label into the hash
# over-redacts, which is the safe direction; leaking the remainder is not
_TENANT_LABEL_RE = re.compile(r"tenant=([^}]*)")


def tenant_token(master: str, tenant) -> str:
    """The per-tenant bearer credential: HMAC-SHA256(master, tenant).

    A single shared token cannot carry a tenant identity — anyone
    holding it could claim any `?tenant=` and read every other tenant's
    rows. Instead the operator (who holds the master
    `MPLC_TPU_METRICS_TOKEN`) derives one credential per tenant with
    this function and hands each tenant ITS token only: presenting
    `Bearer <tenant_token>` together with `?tenant=<name>` authenticates
    the viewer claim (a tenant cannot forge another tenant's HMAC
    without the master), while the master itself is the operator
    credential with the full, unredacted view."""
    return hmac.new(master.encode(), str(tenant).encode(),
                    hashlib.sha256).hexdigest()


def _opaque_tag(value, key: "str | None" = None,
                prefix: str = "tenant") -> str:
    """Stable opaque tag for a redacted identifier (same input -> same
    tag within and across snapshots, so a viewer can still correlate
    rows without learning the identity). With `key` (the master token,
    supplied by the HTTP handler) the tag is HMAC-keyed, so a viewer
    cannot dictionary-confirm candidate names offline; the unkeyed
    fallback is for direct redact_varz() callers."""
    if key:
        digest = hmac.new(key.encode(), str(value).encode(),
                          hashlib.sha256).hexdigest()
    else:
        digest = hashlib.sha256(str(value).encode()).hexdigest()
    return f"{prefix}-" + digest[:8]


def _tenant_tag(tenant, key: "str | None" = None) -> str:
    return _opaque_tag(tenant, key, "tenant")


def redact_health(doc, key: "str | None" = None):
    """A copy of a /healthz document with caller-supplied job ids
    hashed. Job ids are arbitrary submitter strings (a tenant may well
    encode what the job IS in its id) and /healthz deliberately stays
    unauthenticated for orchestrator probes — so in token mode the
    liveness body must not leak them. Liveness semantics (healthy,
    stall flags, queue depth) are untouched; the operator correlates
    the hashed id via the authenticated /varz."""
    def walk(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k == "running_job" and isinstance(v, str):
                    out[k] = _opaque_tag(v, key, "job")
                elif k == "running_jobs" and isinstance(v, list):
                    out[k] = [_opaque_tag(j, key, "job")
                              if isinstance(j, str) else j for j in v]
                else:
                    out[k] = walk(v)
            return out
        if isinstance(node, list):
            return [walk(x) for x in node]
        return node

    return walk(doc)


def redact_varz(doc, viewer: "str | None" = None,
                key: "str | None" = None):
    """A copy of a /varz document with other tenants' values redacted.

    Applied by the HTTP handler when `MPLC_TPU_METRICS_TOKEN` is set and
    the caller authenticated with a per-tenant credential
    (`tenant_token`): the bearer token gates ACCESS, this gates
    CROSS-TENANT visibility — a consortium partner scraping its own
    numbers must not read the other partners' job tables off the same
    endpoint. Rules:

      - any `jobs` table (dict of rows carrying a `tenant` field): rows
        whose tenant != `viewer` collapse to
        {tenant: <hashed tag>, status, priority, age_sec, redacted: True};
      - dict keys carrying a `tenant=` metric label (the registry's
        `name{tenant=...}` convention) are rewritten with the hashed tag
        unless the label names the viewer;
      - `tenant_device_seconds`-style per-tenant maps: non-viewer keys
        are hashed (values kept — aggregate billing is not an identity);
      - the live tier's `live_games` block (tenant-keyed game rows):
        non-viewer rows collapse to a hashed-tenant tag plus the
        activity scalars, with the journal PATH dropped — a filesystem
        path is operator detail, not a co-tenant's business;
      - fleet views (`shards` row tables, `least_loaded`, `shard_id`,
        `peer`): shard identities/endpoints are deployment topology and
        hash to opaque `shard-` tags, while queue/freshness SCALARS stay
        readable — a tenant may reason about cluster load, never about
        which host is which.

    `key` (the master token) makes the hashed tags HMAC-keyed — see
    `_tenant_tag`."""
    def _redact_key(k: str) -> str:
        def sub(m):
            t = m.group(1)
            return ("tenant=" + t if viewer is not None and t == viewer
                    else "tenant=" + _tenant_tag(t, key))
        return _TENANT_LABEL_RE.sub(sub, k)

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for k, val in node.items():
                if (k == "jobs" and isinstance(val, dict)
                        and any(isinstance(r, dict) and "tenant" in r
                                for r in val.values())):
                    # the row KEY is the caller-supplied job id — itself
                    # an identity/work leak (a tenant may encode what
                    # the job is in its id), so redacted rows get an
                    # opaque job tag too
                    out[k] = {
                        (jid if row.get("tenant") == viewer
                         else _opaque_tag(jid, key, "job")):
                        (dict(row) if row.get("tenant") == viewer
                         else {"tenant": _tenant_tag(row.get("tenant"),
                                                     key),
                               **{f: row.get(f)
                                  for f in _REDACTED_ROW_FIELDS},
                               "redacted": True})
                        for jid, row in val.items()}
                elif (k == "tenant_device_seconds"
                      and isinstance(val, dict)):
                    out[k] = {(t if t == viewer
                               else _tenant_tag(t, key)): v
                              for t, v in val.items()}
                elif (k == "live_games" and isinstance(val, dict)
                      and any(isinstance(r, dict) and "tenant" in r
                              for r in val.values())):
                    out[k] = {
                        (t if t == viewer else _tenant_tag(t, key)):
                        (dict(row) if t == viewer
                         else {"tenant": _tenant_tag(row.get("tenant"),
                                                     key),
                               "rounds_resident":
                                   row.get("rounds_resident"),
                               "round_stamp": row.get("round_stamp"),
                               "queries": row.get("queries"),
                               # residency state stays readable (load
                               # signals, not identity); the journal
                               # PATH is dropped with the rest
                               "resident": row.get("resident"),
                               "last_restore_s":
                                   row.get("last_restore_s"),
                               "redacted": True})
                        for t, row in val.items()}
                elif (k == "shards" and isinstance(val, dict) and val
                      and all(isinstance(r, dict)
                              for r in val.values())):
                    # fleet views (the /varz fleet block, /fleet/varz):
                    # shard ids are deployment topology — hashed for
                    # tenant viewers, while the rows' queue/freshness
                    # SCALARS stay readable (a tenant may reason about
                    # cluster load, not about which host is which)
                    out[k] = {
                        _opaque_tag(s, key, "shard"):
                        {**walk(row),
                         **({"shard": _opaque_tag(row["shard"], key,
                                                  "shard")}
                            if isinstance(row.get("shard"), str)
                            else {})}
                        for s, row in val.items()}
                elif (k in ("least_loaded", "shard_id", "peer")
                      and isinstance(val, str)):
                    out[k] = _opaque_tag(val, key, "shard")
                elif isinstance(k, str) and "tenant=" in k:
                    out[_redact_key(k)] = walk(val)
                else:
                    out[k] = walk(val)
            return out
        if isinstance(node, list):
            return [walk(x) for x in node]
        return node

    return walk(doc)


# -- Prometheus rendering -----------------------------------------------------

_BRACKET_RE = re.compile(r"^(?P<base>[^\[\]]+)\[(?P<item>.+)\]$")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_parts(name: str, labels: dict) -> tuple[str, dict]:
    """(prometheus metric name, labels) for a registry metric name: the
    `name[item]` per-executable convention becomes an `item` label."""
    m = _BRACKET_RE.match(name)
    if m is not None:
        name = m.group("base")
        labels = dict(labels, item=m.group("item"))
    return "mplc_" + _SANITIZE_RE.sub("_", name), labels


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_SANITIZE_RE.sub("_", k)}="{_escape(str(v))}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text() -> str:
    """The whole registry in Prometheus text exposition format 0.0.4."""
    lines = []
    typed: set = set()
    for row in metrics.export_view():
        name, labels = _prom_parts(row["name"], row["labels"])
        kind = row["kind"]
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            cum = 0
            for bound, c in zip(row["bounds"], row["bucket_counts"]):
                cum += c
                lines.append(
                    f"{name}_bucket"
                    f"{_label_str(dict(labels, le=_fmt(bound)))} {cum}")
            cum += row["bucket_counts"][-1]
            lines.append(
                f'{name}_bucket{_label_str(dict(labels, le="+Inf"))} {cum}')
            lines.append(f"{name}_sum{_label_str(labels)} "
                         f"{_fmt(row['sum'])}")
            lines.append(f"{name}_count{_label_str(labels)} {row['count']}")
        else:
            value = row["value"]
            if value is None:
                continue  # an unset gauge has no sample
            lines.append(f"{name}{_label_str(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


# -- the HTTP server ----------------------------------------------------------

_LIVE_ROUND_RE = re.compile(r"^/live/([^/]+)/round$")


class _Handler(http.server.BaseHTTPRequestHandler):
    def _auth_role(self, query: str) -> "tuple[str, str | None]":
        """(role, viewer) for the request's bearer credential:

          ("open", None)       — MPLC_TPU_METRICS_TOKEN unset (the
                                 loopback default: everything open);
          ("operator", None)   — the master token itself: full,
                                 unredacted access;
          ("tenant", <name>)   — the per-tenant HMAC credential
                                 (`tenant_token(master, name)`) together
                                 with `?tenant=<name>`: the viewer claim
                                 is AUTHENTICATED, not self-declared —
                                 tenant A cannot read tenant B's rows by
                                 editing the query string;
          ("denied", None)     — anything else.

        Comparisons are constant-time over BYTES (a non-ASCII header
        must 401, not TypeError the handler thread)."""
        token = os.environ.get(METRICS_TOKEN_ENV)
        if not token:
            return "open", None
        header = self.headers.get("Authorization", "")
        supplied = header[7:] if header.startswith("Bearer ") else ""
        supplied_b = supplied.encode("utf-8", "surrogateescape")
        if hmac.compare_digest(supplied_b, token.encode()):
            return "operator", None
        viewer = urllib.parse.parse_qs(query).get("tenant", [None])[0]
        if viewer is not None and hmac.compare_digest(
                supplied_b, tenant_token(token, viewer).encode()):
            return "tenant", viewer
        return "denied", None

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            # operator-only when the token is set: the Prometheus text
            # carries every tenant's labeled series and has no redacted
            # rendering — a per-tenant credential does not unlock it
            if self._auth_role(query)[0] not in ("open", "operator"):
                return self._deny()
            body = prometheus_text().encode()
            self._reply(200, body, "text/plain; version=0.0.4")
        elif path == "/healthz":
            # liveness stays unauthenticated: a 401ing health probe
            # reads as "down" to every orchestrator that matters. In
            # token mode, caller-supplied job ids are hashed out of the
            # open body (liveness semantics untouched).
            healthy, view = health_view()
            token = os.environ.get(METRICS_TOKEN_ENV)
            if token:
                view = redact_health(view, token)
            self._reply(200 if healthy else 503,
                        json.dumps(view, default=str).encode(),
                        "application/json")
        elif path == "/varz":
            role, viewer = self._auth_role(query)
            if role == "denied":
                return self._deny()
            doc = varz_view()
            if role == "tenant":
                # authenticated per-tenant view: everyone else's rows
                # redacted under HMAC-keyed tags
                doc = redact_varz(doc, viewer,
                                  key=os.environ.get(METRICS_TOKEN_ENV))
            self._reply(200, json.dumps(doc, default=str).encode(),
                        "application/json")
        elif path in ("/fleet/varz", "/fleet/metrics"):
            # the aggregated cluster view (obs/fleet_view.py): same
            # credential model as the per-process routes — Prometheus
            # text is operator-only, the JSON view serves tenants with
            # every aggregated row under the PR-12 redaction walk
            role, viewer = self._auth_role(query)
            if role == "denied" or (path == "/fleet/metrics"
                                    and role not in ("open", "operator")):
                return self._deny()
            from . import fleet_view
            coll = fleet_view.get_or_create_collector()
            if coll is None:
                return self._reply(
                    404, b"no fleet collector configured (set "
                    b"MPLC_TPU_FLEET_PEERS or MPLC_TPU_FLEET_STATE_DIR, "
                    b"or install one via fleet_view.set_collector)\n",
                    "text/plain")
            try:
                if path == "/fleet/metrics":
                    merged = coll.collect().get("merged") or {}
                    body = fleet_view.fleet_metrics_text(merged).encode()
                    self._reply(200, body, "text/plain; version=0.0.4")
                else:
                    doc = coll.fleet_varz()
                    if role == "tenant":
                        doc = redact_varz(
                            doc, viewer,
                            key=os.environ.get(METRICS_TOKEN_ENV))
                    self._reply(200,
                                json.dumps(doc, default=str).encode(),
                                "application/json")
            except Exception as e:  # collector failure is a 503, not 500
                self._reply(503, json.dumps(
                    {"error": str(e)[:500]}).encode(), "application/json")
        elif path == "/router/job":
            # routed-job polling (the fleet router's result surface);
            # gated like the submit route — the pair only exists
            # together. Tenant-credentialed viewers may only read their
            # OWN jobs: the v(S) table is the tenant's game data.
            if os.environ.get(ROUTER_SERVE_ENV) != "1":
                return self._reply(404, b"not found\n", "text/plain")
            role, viewer = self._auth_role(query)
            if role == "denied":
                return self._deny()
            job_id = urllib.parse.parse_qs(query).get("id", [None])[0]
            if not job_id:
                return self._reply(400, json.dumps(
                    {"error": "missing ?id=<job_id>"}).encode(),
                    "application/json")
            try:
                doc = router_dispatch("job", {"job": job_id})
            except KeyError as e:
                return self._reply(404, json.dumps(
                    {"error": str(e)[:500]}).encode(), "application/json")
            except LookupError as e:
                return self._reply(503, json.dumps(
                    {"error": str(e)[:500]}).encode(), "application/json")
            if role == "tenant" and doc.get("tenant") != viewer:
                return self._deny()
            self._reply(200, json.dumps(doc, default=str).encode(),
                        "application/json")
        elif path == "/":
            self._reply(200, b"mplc_tpu telemetry: /metrics /healthz "
                        b"/varz /fleet/metrics /fleet/varz\n",
                        "text/plain")
        else:
            self._reply(404, b"not found\n", "text/plain")

    def _router_submit(self) -> None:
        """POST /router/submit — one routed job submission (the fleet
        router's wire path into this shard's ShardServer sink). Error
        mapping mirrors the service's submit contract: 429+Retry-After
        for ServiceOverloaded/JobShed (body carries retry_after_sec,
        the `kind`, and the cluster redirect hint), 403 for a failed
        credential, 503 for a closed service / missing sink, 400 for a
        malformed document."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length).decode())
            if not isinstance(doc, dict):
                raise ValueError("submission must be a JSON object")
        except Exception as e:
            return self._reply(400, json.dumps(
                {"error": f"bad request body: {str(e)[:300]}"}).encode(),
                "application/json")
        from ..service.scheduler import (JobShed, ServiceAuthError,
                                         ServiceClosed, ServiceOverloaded)
        try:
            ack = router_dispatch("submit", doc)
        except (ServiceOverloaded, JobShed) as e:
            cluster = getattr(e, "cluster", None) or {}
            body = json.dumps({
                "error": str(e)[:500],
                "kind": "shed" if isinstance(e, JobShed) else "overloaded",
                "retry_after_sec": float(
                    getattr(e, "retry_after_sec", 0.0) or 0.0),
                # the redirect hint alone rides the wire — never the
                # full view (its rows carry other shards' metrics)
                "cluster": {"least_loaded": cluster.get("least_loaded")},
            })
            self.send_response(429)
            self.send_header("Retry-After", str(max(1, int(float(
                getattr(e, "retry_after_sec", 0.0) or 0.0) + 0.5))))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body.encode())
            return
        except ServiceAuthError as e:
            return self._reply(403, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        except (ServiceClosed, LookupError) as e:
            return self._reply(503, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        except KeyError as e:
            return self._reply(404, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        except ValueError as e:
            return self._reply(400, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        except Exception as e:  # a sink crash is a 500 with evidence
            return self._reply(500, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        self._reply(200, json.dumps(ack, default=str).encode(),
                    "application/json")

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path, _, query = self.path.partition("?")
        if path == "/router/submit":
            if os.environ.get(ROUTER_SERVE_ENV) != "1":
                # same opt-in rule as live ingestion below: the
                # mutating route doesn't exist unless asked for
                return self._reply(404, b"not found\n", "text/plain")
            return self._router_submit()
        m = _LIVE_ROUND_RE.match(path)
        if m is None or os.environ.get(LIVE_INGEST_ENV) != "1":
            # the mutating route doesn't EXIST unless the operator
            # opted in — a 404, not a 403, so probes learn nothing
            return self._reply(404, b"not found\n", "text/plain")
        tenant = urllib.parse.unquote(m.group(1))
        role, viewer = self._auth_role(query)
        # per-tenant credentials must match the PATH tenant: tenant A's
        # token cannot append rounds into tenant B's game
        if role == "denied" or (role == "tenant" and viewer != tenant):
            return self._deny()
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length).decode())
            if not isinstance(doc, dict):
                raise ValueError("round document must be a JSON object")
        except Exception as e:
            return self._reply(400, json.dumps(
                {"error": f"bad request body: {str(e)[:300]}"}).encode(),
                "application/json")
        try:
            ack = live_ingest(tenant, doc)
        except KeyError as e:
            return self._reply(404, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        except ValueError as e:
            return self._reply(400, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        except Exception as e:
            retry = getattr(e, "retry_after_sec", None)
            if retry is not None:
                # LiveGameFull / LiveResidencyFull: the client should
                # back off, not hammer — the hint rides the standard
                # header AND the body (sub-second resolution)
                body = json.dumps({"error": str(e)[:500],
                                   "retry_after_sec": float(retry)})
                self.send_response(429)
                self.send_header("Retry-After",
                                 str(max(1, int(float(retry) + 0.5))))
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body.encode())
                return
            return self._reply(503, json.dumps(
                {"error": str(e)[:500]}).encode(), "application/json")
        self._reply(200, json.dumps(ack, default=str).encode(),
                    "application/json")

    def _deny(self) -> None:
        self.send_response(401)
        self.send_header("WWW-Authenticate", "Bearer")
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args) -> None:  # silence per-request spam
        pass


class TelemetryServer:
    """One process-wide HTTP thread serving the routes above."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="mplc-telemetry")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start(port: int, host: str = "127.0.0.1") -> TelemetryServer:
    """Start (or return) the process-singleton telemetry server. Binds
    loopback by default — the endpoints are unauthenticated (tenant
    names, job tables, error strings), so exposing them beyond the host
    is an explicit operator decision (`MPLC_TPU_METRICS_PORT=host:port`),
    not a side effect."""
    global _server
    with _lock:
        if _server is None:
            srv = TelemetryServer(port, host)
            logger.info("telemetry server listening on %s:%d "
                        "(/metrics /healthz /varz)", srv.host, srv.port)
            _server = srv
        elif port not in (0, _server.port):
            warnings.warn(
                f"telemetry server already bound to :{_server.port}; "
                f"ignoring request for :{port}", stacklevel=2)
        return _server


def stop() -> None:
    """Shut the singleton down (tests; production lets the daemon die
    with the process)."""
    global _server
    with _lock:
        srv, _server = _server, None
    if srv is not None:
        srv.close()


def active_server() -> "TelemetryServer | None":
    return _server


def active_port() -> "int | None":
    """The singleton telemetry server's bound port (None with no server
    up) — published in the fleet shard state file so a router can
    discover each shard's HTTP surface through the state dir alone."""
    srv = _server
    return srv.port if srv is not None else None


def maybe_start_from_env() -> "TelemetryServer | None":
    """Start the server iff `MPLC_TPU_METRICS_PORT` is set. Unset/empty
    -> None with NO socket or thread created; a malformed value warns and
    stays off (telemetry must never kill the workload it watches). A
    plain port binds loopback only; `host:port` (e.g. `0.0.0.0:9090`)
    opts into wider exposure explicitly."""
    raw = os.environ.get(METRICS_PORT_ENV)
    if not raw:
        return None
    host, _, port_s = raw.rpartition(":")
    host = host or "127.0.0.1"
    try:
        port = int(port_s)
        if not 0 <= port <= 65535:
            raise ValueError(raw)
    except ValueError:
        warnings.warn(
            f"{METRICS_PORT_ENV}={raw!r} is not a port number (0-65535) "
            "or host:port; telemetry endpoints disabled", stacklevel=2)
        return None
    try:
        return start(port, host)
    except OSError as e:
        warnings.warn(
            f"telemetry server could not bind {host}:{port} ({e}); "
            "endpoints disabled", stacklevel=2)
        return None
