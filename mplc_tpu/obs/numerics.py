"""Numeric-truth plane: value provenance, reduction audit, drift diffing.

The telemetry plane (obs/trace.py, PR 10) and device-time truth
(obs/devcost.py, PR 12) made *time* observable; this module makes
*values* observable. Three parts:

1. **Value-provenance ledger** (`ValueLedger`, `MPLC_TPU_NUMERICS_LEDGER`):
   every harvested v(S) — exact engine, reconstruction, live — is recorded
   with its EXACT float bits, a content hash, and float-path metadata
   (topology, device count, reduction mode, slot width, OOM-ladder rungs
   taken) keyed by (subset bitmask, engine fingerprint). Two ledgers —
   runs, topologies, device counts — diff into per-subset ulp-distance
   histograms, max/percentile drift, and the Kendall-tau of the induced
   value ranking (`diff_ledgers`, `scripts/drift_diff.py`): per "On the
   Volatility of Shapley-Based Contribution Metrics" (PAPERS.md), small
   v(S) perturbations flip contributivity *rankings*, so drift is a
   correctness metric, not a cosmetic one.

2. **Per-device reduction audit** (`audit_coalition`,
   `MPLC_TPU_NUMERICS_AUDIT=1`): at device-fence ordinals the engine
   captures one audited coalition's per-round per-partner aggregation
   terms through a SEPARATE instrumented recording run (the dispatched
   sweep programs are never touched, so v(S) is bit-identical audit-on vs
   audit-off — equality-tested incl. the fault ladder), then replays the
   reduction orders on the host: the reference left-to-right fold vs the
   sharded grouping (per-device partial sums + cross-device combine,
   i.e. what a `psum` over `part` computes) — localizing the FIRST
   divergent reduction (round, leaf, shard count) with exact ulp
   distances. A detected order divergence emits a `numerics.drift` event
   and a flight-recorder postmortem carrying the divergent leaf path and
   the per-device partials.

3. **Deterministic-reduction support** (`MPLC_TPU_DETERMINISTIC_REDUCE`):
   the mode itself lives in ops/aggregation.py (`ordered_fold`) and
   mpl/engine.py (stream hoisting, unrolled round loops, aux-drop); this
   module holds the env plumbing and the audit that VERIFIES the pinned
   order. What the audit established on this toolchain (full evidence in
   DESIGN_NOTES.md "2-D shard_map numeric drift — closed"):

     - the aggregation `psum` order (the original root-cause prose) is
       only ONE root: the grouped reduction diverges from the linear fold
       at ulp scale, which adam's sqrt(v)-normalized updates amplify
       chaotically;
     - a second, larger root is COMPILATION-CONTEXT sensitivity: the same
       per-partner training pass embedded in programs that generate their
       threefry streams next to a collective (or that run it at another
       batch width inside a loop body) rounds a few lanes differently per
       topology;
     - both are eliminated by the deterministic mode's recipe — ordered
       fold over all-gathered terms, rng/permutation streams hoisted into
       a separate dispatch and passed as data, trace-time-unrolled round
       loops, and one shard_map program family with `part=1` as the
       unsharded reference — under which the 2-D partner-sharded path is
       BIT-IDENTICAL to the unsharded reference
       (tests/test_partner_shard.py, tests/test_numerics.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import struct
import time

import numpy as np

from .. import constants
from . import metrics as obs_metrics
from . import trace as obs_trace

logger = logging.getLogger("mplc_tpu")

LEDGER_SCHEMA = 1


def audit_enabled() -> bool:
    """MPLC_TPU_NUMERICS_AUDIT=1 (default off)."""
    return os.environ.get(constants.NUMERICS_AUDIT_ENV, "") == "1"


def ledger_path_from_env() -> "str | None":
    return os.environ.get(constants.NUMERICS_LEDGER_ENV) or None


def reduction_mode() -> str:
    from .. import constants as _c
    return ("deterministic" if _c.deterministic_reduce_enabled()
            else "default")


# ---------------------------------------------------------------------------
# float forensics
# ---------------------------------------------------------------------------

def float_bits(v: float) -> str:
    """Exact IEEE-754 double bits of a Python float, as 16 hex chars —
    the ledger's canonical value representation (`float(x)` of the stored
    bits round-trips exactly; JSON's decimal repr also round-trips, the
    hex form just makes bit-equality greppable)."""
    return struct.pack(">d", float(v)).hex()


def bits_to_float(bits: str) -> float:
    return struct.unpack(">d", bytes.fromhex(bits))[0]


def _ordinal(v: float) -> int:
    """Monotonic integer mapping of a double: adjacent floats map to
    adjacent integers, so |ordinal(a) - ordinal(b)| is the ulp distance."""
    (i,) = struct.unpack(">q", struct.pack(">d", float(v)))
    return i if i >= 0 else -(i & 0x7FFFFFFFFFFFFFFF)


def ulp_distance(a: float, b: float) -> int:
    """Units-in-the-last-place distance between two doubles (0 iff
    bit-identical up to +/-0.0; NaNs compare infinite unless both NaN)."""
    fa, fb = float(a), float(b)
    if fa == fb:  # covers +0.0 vs -0.0
        return 0
    if np.isnan(fa) and np.isnan(fb):
        return 0
    if np.isnan(fa) or np.isnan(fb):
        return int(2 ** 63 - 1)
    return abs(_ordinal(fa) - _ordinal(fb))


def ulp_distance_f32(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise ulp distance between two float32 arrays (the audit's
    per-leaf forensics)."""
    ia = np.ascontiguousarray(a, np.float32).view(np.int32).astype(np.int64)
    ib = np.ascontiguousarray(b, np.float32).view(np.int32).astype(np.int64)
    ia = np.where(ia >= 0, ia, -(ia & 0x7FFFFFFF))
    ib = np.where(ib >= 0, ib, -(ib & 0x7FFFFFFF))
    d = np.abs(ia - ib)
    return np.where(np.asarray(a, np.float32) == np.asarray(b, np.float32),
                    0, d)


# ---------------------------------------------------------------------------
# value-provenance ledger
# ---------------------------------------------------------------------------

class ValueLedger:
    """In-memory ledger of harvested v(S) bits + float-path metadata,
    keyed by (subset bitmask, engine fingerprint). One ledger per engine;
    `save()` persists atomically as JSON (the artifact drift_diff.py and
    the bench sidecar consume)."""

    def __init__(self, engine_fingerprint: str, meta: dict | None = None,
                 path: "str | None" = None):
        self.engine_fingerprint = engine_fingerprint
        self.meta = dict(meta or {})
        self.path = path
        self.entries: dict[str, dict] = {}

    @staticmethod
    def subset_key(subset) -> str:
        """Canonical bitmask hex of a membership tuple."""
        bits = 0
        for i in subset:
            bits |= 1 << int(i)
        return hex(bits)

    def record(self, subset, value: float, *, source: str = "exact",
               slot_width: "int | None" = None,
               cap_halvings: int = 0, degraded: bool = False) -> None:
        key = self.subset_key(subset)
        entry = {
            "mask": key,
            "value": float(value),
            "value_bits": float_bits(value),
            "source": source,
            "slot_width": slot_width,
            "cap_halvings": int(cap_halvings),
            "degraded": bool(degraded),
        }
        body = json.dumps({**entry, "fingerprint": self.engine_fingerprint,
                           **{k: self.meta.get(k) for k in
                              ("topology", "part_shards", "n_devices",
                               "reduction_mode")}},
                          sort_keys=True)
        entry["content_hash"] = hashlib.sha256(body.encode()).hexdigest()[:16]
        self.entries[key] = entry
        obs_metrics.counter("numerics.ledger_records").inc()

    def to_doc(self) -> dict:
        return {
            "schema": LEDGER_SCHEMA,
            "engine_fingerprint": self.engine_fingerprint,
            "meta": self.meta,
            "entries": self.entries,
        }

    def values_bits(self) -> dict:
        """{mask_hex: value_bits} — the compact map the bench sidecar
        embeds for the bench_diff numerics gate."""
        return {k: e["value_bits"] for k, e in self.entries.items()}

    def save(self, path: "str | None" = None) -> "str | None":
        """Atomic write (temp + os.replace); never raises — a ledger that
        can kill a sweep over a full disk is worse than a gap in it."""
        path = path or self.path
        if not path:
            return None
        try:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(self.to_doc(), f)
            os.replace(tmp, path)
            obs_trace.event("numerics.ledger", path=str(path),
                            entries=len(self.entries),
                            reduction_mode=self.meta.get("reduction_mode"))
            return path
        except OSError as e:
            logger.error("numerics ledger save to %r failed: %s", path, e)
            return None

    @classmethod
    def load(cls, path: str) -> "ValueLedger":
        with open(path) as f:
            doc = json.load(f)
        led = cls(doc.get("engine_fingerprint", "?"), doc.get("meta"),
                  path=path)
        led.entries = dict(doc.get("entries", {}))
        return led


def _discordant_pairs(ranks: np.ndarray) -> int:
    """Strict inversions in a rank sequence via a binary indexed tree —
    O(n log n), the count Knight's tau algorithm needs (ties are not
    inversions)."""
    m = int(ranks.max()) + 1
    tree = [0] * (m + 1)
    disc = 0
    for seen, r in enumerate(ranks):
        r = int(r)
        # earlier elements with rank strictly greater than r
        s, i = 0, r
        while i > 0:
            s += tree[i]
            i -= i & (-i)
        disc += seen - s
        i = r
        while i <= m:
            tree[i] += 1
            i += i & (-i)
    return disc


def kendall_tau_b(a, b) -> "float | None":
    """Kendall tau-b over two paired value lists (the induced subset
    ranking agreement, tie-aware: two bit-identical ledgers score exactly
    1.0 even when some subsets share a value). None below two pairs.

    Knight's O(n log n) formulation — the ledgers this compares hold one
    entry per SUBSET (2^P - 1 of them), so a quadratic pair loop would
    hang the drift gate at the partner counts the engine already serves."""
    n = len(a)
    if n < 2:
        return None
    a = np.asarray(a, float)
    b = np.asarray(b, float)
    order = np.lexsort((b, a))
    a_s, b_s = a[order], b[order]

    def ties(counts: np.ndarray) -> int:
        return int((counts * (counts - 1) // 2).sum())

    n0 = n * (n - 1) // 2
    n1 = ties(np.unique(a_s, return_counts=True)[1])
    n2 = ties(np.unique(b_s, return_counts=True)[1])
    n3 = ties(np.unique(np.stack([a_s, b_s], axis=1), axis=0,
                        return_counts=True)[1])
    # b ranks in a-major order: within equal-a runs lexsort sorted b
    # ascending, so a-tied pairs contribute no inversions; b-ties are
    # not strict inversions either — exactly the discordant-pair count
    ranks = np.unique(b_s, return_inverse=True)[1] + 1
    disc = _discordant_pairs(ranks)
    conc_minus_disc = n0 - n1 - n2 + n3 - 2 * disc
    denom = ((n0 - n1) * (n0 - n2)) ** 0.5
    return conc_minus_disc / denom if denom else None


# backward-compatible internal alias (tests + diff_ledgers call sites)
_kendall_tau = kendall_tau_b


def diff_ledgers(a, b) -> dict:
    """Compare two ledgers (ValueLedger or their to_doc() dicts).

    Returns {comparable, same_fingerprint, common, only_a, only_b,
    ulp: {max, p50, p99, nonzero}, histogram (log2-bucketed ulp counts),
    kendall_tau, drift}: `drift` is True when any common subset's value
    bits differ. Fingerprint-mismatched ledgers describe different GAMES
    — deltas are reported but flagged not comparable."""
    da = a.to_doc() if isinstance(a, ValueLedger) else a
    db = b.to_doc() if isinstance(b, ValueLedger) else b
    ea, eb = da.get("entries", {}), db.get("entries", {})
    common = sorted(set(ea) & set(eb))
    same_fp = (da.get("engine_fingerprint") == db.get("engine_fingerprint"))
    dists = []
    va, vb = [], []
    per_subset = {}
    for k in common:
        x = bits_to_float(ea[k]["value_bits"])
        y = bits_to_float(eb[k]["value_bits"])
        d = ulp_distance(x, y)
        dists.append(d)
        per_subset[k] = d
        va.append(x)
        vb.append(y)
    hist: dict[str, int] = {}
    for d in dists:
        if d == 0:
            bucket = "0"
        else:
            bucket = f"2^{max(int(d).bit_length() - 1, 0)}"
        hist[bucket] = hist.get(bucket, 0) + 1
    sd = sorted(dists)

    def pct(q):
        if not sd:
            return None
        return sd[min(max(int(q * len(sd)), 1), len(sd)) - 1]

    return {
        "comparable": same_fp and bool(common),
        "same_fingerprint": same_fp,
        "common": len(common),
        "only_a": len(set(ea) - set(eb)),
        "only_b": len(set(eb) - set(ea)),
        "ulp": {
            "max": max(dists) if dists else None,
            "p50": pct(0.50),
            "p99": pct(0.99),
            "nonzero": sum(1 for d in dists if d),
        },
        "histogram": hist,
        "per_subset": per_subset,
        "kendall_tau": _kendall_tau(va, vb),
        "drift": any(dists),
        "meta_a": da.get("meta", {}),
        "meta_b": db.get("meta", {}),
    }


# ---------------------------------------------------------------------------
# per-device reduction audit
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AuditResult:
    subset: tuple
    rounds: int
    partners: int
    shard_counts: tuple
    # the grouping the ENGINE actually executes: the 2-D pipe's
    # part_shards under the default (psum) order, or None when the
    # executed reduction is the linear reference order itself (every
    # 1-D engine, and every deterministic-reduce engine at any shards)
    executed_shards: "int | None"
    # first (round, leaf_path, shard_count) where the EXECUTED grouping
    # diverges from the reference linear fold — None when the executed
    # order is the reference order or agrees bit-exactly
    first_divergence: "tuple | None"
    # max ulp of the executed-order divergence (0 when none)
    max_ulp: int
    divergent_elements: int
    # the hypothetical table: per-CANDIDATE-shard-count max ulp across
    # all rounds/leaves — what sharding at s devices WOULD do to this
    # coalition's reductions, recorded in every mode as evidence
    ulp_by_shards: dict
    # the audited run's per-device partial sums at the first divergent
    # reduction (host-derived, linear order within each device's block)
    partials_at_divergence: "list | None"
    seconds: float


def _linear_fold(terms: np.ndarray) -> np.ndarray:
    """Strict left-to-right fold over axis 0 in float32 — the reference
    (and deterministic-mode) reduction order, replayed exactly on host
    (NumPy float32 adds are IEEE single adds, bit-equal to the device's
    unfused adds)."""
    out = terms[0].astype(np.float32)
    for i in range(1, terms.shape[0]):
        out = out + terms[i].astype(np.float32)
    return out


def _grouped_fold(terms: np.ndarray, shards: int) -> np.ndarray:
    """The sharded grouping: per-device partial sums over contiguous
    partner blocks (linear within the block), then a linear cross-device
    combine — the order a `psum` over a part axis of `shards` devices
    induces on the same terms."""
    P = terms.shape[0]
    block = P // shards
    partials = [_linear_fold(terms[d * block:(d + 1) * block])
                for d in range(shards)]
    return _linear_fold(np.stack(partials))


def _device_partials(terms: np.ndarray, shards: int) -> list:
    P = terms.shape[0]
    block = P // shards
    return [_linear_fold(terms[d * block:(d + 1) * block])
            for d in range(shards)]


def audit_coalition(engine, subset) -> "AuditResult | None":
    """Capture one coalition's per-round per-partner aggregation terms
    through a separate instrumented (record_updates) run and localize the
    first reduction step where a sharded grouping diverges from the
    reference linear fold.

    Touches NOTHING the engine serves: separate trainer instance,
    separate TrainState, no memo/cache writes — v(S) is bit-identical
    with the audit on or off (equality-tested, tests/test_numerics.py).
    Returns None when the game shape can't be audited (non-fedavg
    approach, early stopping on, seed ensembles). Never raises."""
    t0 = time.perf_counter()
    try:
        import jax

        from ..mpl.engine import MplTrainer

        cfg = engine._multi_cfg
        if (cfg.approach != "fedavg" or cfg.is_early_stopping
                or getattr(engine, "seed_ensemble", 1) > 1):
            return None
        subset = tuple(sorted(int(i) for i in subset))
        eff = engine._effective_subset(subset)
        if len(eff) < 2:
            return None  # singles never aggregate
        audit_cfg = dataclasses.replace(
            cfg, record_updates=True, partner_axis=None, slot_count=None)
        trainer = MplTrainer.get(engine.model, audit_cfg)
        rng = engine._coalition_rng(eff)
        P = engine.partners_count
        mask = np.zeros((P,), np.float32)
        mask[list(subset)] = 1.0
        state = trainer.init_state(rng, P)
        state = trainer.jit_epoch_chunk(
            state, engine.stacked, engine.val,
            jax.numpy.asarray(mask), rng, n_epochs=cfg.epoch_count)
        upd_h = [np.asarray(leaf) for leaf in
                 jax.tree_util.tree_leaves(state.upd_h)]   # [R, P, ...]
        leaf_paths = [
            "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path)
            for path, _ in jax.tree_util.tree_flatten_with_path(
                state.upd_h)[0]]
        w_h = np.asarray(state.w_h)                        # [R, P]
        R = w_h.shape[0]

        # candidate shard counts: the engine's actual 2-D width plus
        # every divisor of P — the replay is a HOST computation, so a
        # single-device run still audits what sharding at any width
        # WOULD do to its reductions
        cands = sorted({s for s in range(2, P + 1) if P % s == 0})
        # the grouping the engine ACTUALLY executes: default-order psum
        # over the 2-D pipe's part axis; every other configuration (1-D
        # engines, deterministic-reduce at any shards) executes the
        # linear reference order itself
        pipe2d = getattr(engine, "_pipe2d", None)
        executed = None
        if (pipe2d is not None and pipe2d.part_shards > 1
                and not cfg.deterministic_reduce):
            executed = pipe2d.part_shards
            cands = sorted(set(cands) | {executed})
        if not cands:
            return None

        first = None
        partials = None
        max_ulp = 0
        diverged = 0
        by_shards = {s: 0 for s in cands}
        for r in range(R):
            w = w_h[r]
            if not np.any(w):
                continue  # round never reached / zero survivors
            for leaf, path in zip(upd_h, leaf_paths):
                terms = leaf[r] * w.reshape((-1,) + (1,) * (leaf.ndim - 2))
                terms = terms.astype(np.float32)
                ref = _linear_fold(terms)
                for s in cands:
                    grouped = _grouped_fold(terms, s)
                    d = ulp_distance_f32(ref, grouped)
                    dmax = int(d.max()) if d.size else 0
                    by_shards[s] = max(by_shards[s], dmax)
                    if dmax and s == executed:
                        # executed-order divergence: the localized drift
                        diverged += int((d > 0).sum())
                        max_ulp = max(max_ulp, dmax)
                        if first is None:
                            first = (r, path, s)
                            partials = [p.tolist() if p.size <= 8
                                        else {"shape": list(p.shape),
                                              "max": float(np.max(p)),
                                              "min": float(np.min(p))}
                                        for p in _device_partials(terms, s)]
        res = AuditResult(
            subset=subset, rounds=R, partners=P,
            shard_counts=tuple(cands), executed_shards=executed,
            first_divergence=first,
            max_ulp=max_ulp, divergent_elements=diverged,
            ulp_by_shards=by_shards, partials_at_divergence=partials,
            seconds=time.perf_counter() - t0)
        obs_metrics.counter("numerics.audits").inc()
        obs_trace.event(
            "numerics.audit", dur=res.seconds,
            subset=ValueLedger.subset_key(subset), rounds=R,
            shard_counts=list(cands), executed_shards=executed,
            max_ulp=max_ulp,
            hypothetical_max_ulp=max(by_shards.values(), default=0),
            divergent_elements=diverged,
            first_round=None if first is None else first[0],
            first_leaf=None if first is None else first[1],
            reduction_mode=("deterministic" if cfg.deterministic_reduce
                            else "default"))
        if first is not None:
            # reduction-order divergence localized: in the default mode
            # this is the expected psum-order root cause made concrete;
            # under deterministic-reduce it would mean the pinned order
            # is NOT holding — either way it is flight-recorder material
            obs_metrics.counter("numerics.drift_events").inc()
            obs_trace.event(
                "numerics.drift",
                subset=ValueLedger.subset_key(subset),
                round=first[0], leaf=first[1], shards=first[2],
                max_ulp=max_ulp,
                reduction_mode=("deterministic" if cfg.deterministic_reduce
                                else "default"))
            from . import flight as obs_flight
            obs_flight.dump("numerics_drift", extra={
                "subset": list(subset),
                "first_divergent_round": first[0],
                "divergent_leaf": first[1],
                "shard_count": first[2],
                "max_ulp": max_ulp,
                "divergent_elements": diverged,
                "ulp_by_shards": {str(k): v for k, v in by_shards.items()},
                "per_device_partials": partials,
            })
        return res
    except Exception as e:  # noqa: BLE001 — the audit must never kill a sweep
        logger.warning("numerics audit for %r failed: %s", subset, e)
        return None
