"""Crash flight recorder: postmortem dumps of the always-on span ring.

An operator debugging a quarantined tenant, an exhausted OOM ladder or a
corrupt service journal needs the spans of the FAILING work — but the
failure is precisely the run nobody was tracing on purpose.
`obs/trace.py` therefore keeps a bounded ring of the most recent
span/event records unconditionally (`MPLC_TPU_FLIGHT_RECORDER_SIZE`,
default 512), and this module dumps it — plus a full metrics snapshot —
to an atomic postmortem JSON file when one of the three terminal
failures fires:

  - `service.JobQuarantined` (service/scheduler.py `_fail_attempt`),
  - `faults.LadderExhaustedError` (contrib/engine.py `_ladder_exhausted`),
  - `service.JournalCorruptError` (service/journal.py `replay`).

The triggering log line references the written file, so the postmortem
is one `less` away from the quarantine message.

File format (one JSON object):

    {"reason": str, "ts": epoch-s, "pid": int, "extra": {...},
     "ring_records": [trace records, oldest first],
     "metrics": metrics.snapshot()}

Files land in `MPLC_TPU_FLIGHT_RECORDER_DIR` (default: the working
directory) as `mplc_flight_<reason>_<pid>_<seq>.json`; the write is
temp-file + `os.replace`, same atomicity discipline as the engine's
cache autosave. `dump()` NEVER raises — a postmortem writer that can
itself kill the process (disk full during an OOM spiral) is worse than
no postmortem.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import time

logger = logging.getLogger("mplc_tpu")

FLIGHT_DIR_ENV = "MPLC_TPU_FLIGHT_RECORDER_DIR"

_seq = itertools.count(1)


def dump(reason: str, extra: dict | None = None) -> str | None:
    """Write a postmortem file for `reason`; returns its path, or None
    when the dump failed (logged, never raised)."""
    try:
        from . import metrics, trace

        records = trace.flight_records()
        payload = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "extra": dict(extra or {}),
            "ring_records": records,
            "metrics": metrics.snapshot(),
        }
        out_dir = os.environ.get(FLIGHT_DIR_ENV) or "."
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"mplc_flight_{reason}_{os.getpid()}_{next(_seq)}.json")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        metrics.counter("obs.flight_dumps").inc()
        trace.event("flight.dump", reason=reason, path=path,
                    records=len(records))
        return path
    except Exception as e:  # noqa: BLE001 — the no-raise contract
        logger.error("flight recorder: postmortem dump for %r failed: %s",
                     reason, e)
        return None
