"""Zero-dependency structured tracing: spans with monotonic timing, nesting
and an optional JSONL sink.

The coalition engine's hot paths are instrumented with

    with span("engine.dispatch", width=b, slot_count=k):
        ...

Spans always measure duration (two `perf_counter` calls and a thread-local
list push/pop — nanoseconds, no device sync), but a span only *emits* a
record when a sink is active:

  - the JSONL file named by the `MPLC_TPU_TRACE_FILE` env var (checked at
    span end, so tests and long-lived processes can flip it at runtime), or
  - an in-memory collector opened with `collect()` (how `obs.report` and
    `bench.py` gather a run's spans without touching the filesystem).

With neither active the instrumentation is a no-op apart from the timing
itself — no dict building, no serialization, no I/O.

Record schema (one JSON object per line):

    {"name": str, "id": int, "parent": int | null, "ts": float (epoch s),
     "dur": float (s), "thread": int, "attrs": {...}}

Nesting is per-thread (a thread-local span stack); `parent` links a span to
the innermost span open on the same thread when it started. File writes are
serialized by a module lock, so concurrent threads interleave whole lines,
never partial ones.

Every closed span/event record is ALSO appended to a bounded in-memory
ring (`MPLC_TPU_FLIGHT_RECORDER_SIZE` records, default 512) regardless of
sinks — the crash flight recorder (obs/flight.py) dumps it alongside a
metrics snapshot when a job quarantines, a degrade ladder exhausts, or a
journal turns out corrupt. The ring costs one dict build + deque append
per record (no serialization, no I/O); the rest of the instrumentation
stays a no-op without an active sink.

The JSONL sink is flushed and closed from an `atexit` hook, so the final
line of a trace survives normal interpreter exit; a hard kill can still
tear the last line, which `obs/chrome_trace.py` tolerates and reports.
The same hook converts the trace to Chrome trace-event JSON when
`MPLC_TPU_CHROME_TRACE_FILE` names an output path.
"""

from __future__ import annotations

import atexit
import collections
import itertools
import json
import os
import threading
import time

_lock = threading.Lock()
_local = threading.local()
_ids = itertools.count(1)
# (path, file) of the currently open JSONL sink; reopened when the env var
# changes between spans. Guarded by _lock.
_sink_state: dict = {"path": None, "file": None}
# active in-memory collectors (lists appended to by _emit). Guarded by _lock.
_collectors: list[list] = []

TRACE_FILE_ENV = "MPLC_TPU_TRACE_FILE"
FLIGHT_SIZE_ENV = "MPLC_TPU_FLIGHT_RECORDER_SIZE"
# Fleet trace context (parallel/fleet.py injects both into worker env;
# read per-record so an env overlay mid-process — the inproc fleet path —
# stamps correctly): every span/event record emitted while these are set
# carries `fleet_run` / `fleet_shard` fields, which is what lets
# scripts/fleet_trace_merge.py correlate W per-shard JSONL streams into
# one timeline by construction instead of by filename convention.
FLEET_RUN_ID_ENV = "MPLC_TPU_FLEET_RUN_ID"
FLEET_TRACE_SHARD_ENV = "MPLC_TPU_FLEET_SHARD_ID"


# The span-name registry: EVERY literal name passed to span()/start_span()
# /event() in the package, bench and scripts must be listed here (enforced
# by the static scan in tests/test_knob_hygiene.py). The registry is what
# keeps trace CONSUMERS — obs/report.py, obs/chrome_trace.py, the
# projection scripts — from silently drifting away from the
# instrumentation: renaming a span without updating its consumers (or this
# table) is a fast-tier test failure, not a quietly empty report row.
SPAN_REGISTRY = {
    "engine.evaluate": "one CharacteristicEngine.evaluate() call "
                       "(attrs: requested/missing, optional method)",
    "engine.prep": "whole-call host-side batch construction",
    "engine.dispatch": "device dispatch of one coalition batch",
    "engine.harvest": "result fetch (device sync) of one batch",
    "engine.batch": "per-batch accounting event (dispatch->harvest dur; "
                    "attrs: ordinal/width/slot_count/coalitions/padding/"
                    "epochs/samples/partner_passes)",
    "engine.hbm": "per-evaluate HBM/donation footprint snapshot",
    "engine.device_fence": "sampled device fence: a batch dispatched "
                           "without overlap and timed through a host "
                           "fetch — true device-step seconds (attrs: "
                           "ordinal/width/coalitions/interval)",
    "engine.retry": "transient-failure retry (attrs: site/attempt/"
                    "backoff_sec/ordinal)",
    "engine.degrade": "OOM ladder rung (attrs: action=halve_cap|"
                      "cpu_fallback|ladder_exhausted)",
    "engine.fault": "injected fault fired (MPLC_TPU_FAULT_PLAN)",
    "trainer.compile": "jit cache-miss compile (externally timed)",
    "bank.compile": "program-bank AOT compile (attrs: overlapped)",
    "bank.wait": "serial stall behind the bank's background compiler",
    "recon.record": "grand-coalition recording run (retrain-free)",
    "contributivity": "one estimator method end-to-end",
    "contrib.trust": "trust row (CIs + rank stability)",
    "contrib.plan": "adaptive planner resolved method='auto' for a batch "
                    "query (attrs: QueryPlan.describe() — method/"
                    "est_evals/est_cost_sec/cost_basis/reason)",
    "live.plan": "adaptive planner resolved method='auto' for a live "
                 "query (attrs: tenant + QueryPlan.describe())",
    "mpl.fit": "one multi-partner fit",
    "service.submit": "job accepted onto the service queue",
    "service.reject": "admission refused (backpressure or fault plan)",
    "service.slice": "one scheduling quantum of one job",
    "service.stall": "injected scheduler stall (service fault plan)",
    "service.shed": "job shed by the overload admission governor "
                    "(attrs: priority/queue_wait_p99_sec/retry_after_sec)",
    "service.job": "terminal job event (attrs incl. SLO: queue_wait_sec/"
                   "ttfv_sec/deadline_missed)",
    "service.job_fault": "one failed job attempt (pre retry/quarantine)",
    "service.recover": "journal-seeded job recovery",
    "live.query": "one live contributivity query (attrs: tenant/method/"
                  "rounds/stamp/prune_tau/memo_hit/evaluations/pruned)",
    "live.append": "one aggregation round appended to a resident live "
                   "game (attrs: tenant/seq/stamp/invalidating)",
    "live.recover": "journal-restored live game (attrs: tenant/rounds/"
                    "stamp)",
    "live.evict": "live game's round stack LRU-evicted to a WAL-backed "
                  "stub (attrs: tenant/rounds/stamp)",
    "live.restore": "evicted live game restored from its WAL on touch "
                    "(attrs: tenant/rounds/stamp/restore_s)",
    "live.ingest": "one wire round accepted via POST /live/<tenant>/"
                   "round (attrs: tenant/stamp/rounds)",
    "service.journal_broken": "WAL append failure (journaling disabled)",
    "service.auth_reject": "submit-path credential check failed (attrs: "
                           "tenant) — a synchronous auth error, never a "
                           "quarantine",
    "router.submit": "one job routed end-to-end by the fleet router "
                     "(attrs: tenant/job/shard/attempts/route_s)",
    "router.redirect": "one overload/shed redirect followed (attrs: "
                       "tenant/job/from/to/attempt/retry_after_sec)",
    "router.repin": "a tenant's sticky shard pin deliberately broken "
                    "(attrs: tenant/from/to/reason=death|overload)",
    "router.failover": "a dead shard drained from the routing table and "
                       "its journaled incomplete jobs resubmitted "
                       "(attrs: shard/jobs/resubmitted)",
    "router.exhausted": "a job's routing budget ran out — failure "
                        "surfaced classified as RoutedJobFailed (attrs: "
                        "tenant/job/attempts/budget)",
    "router.fault": "router-level chaos plan entry fired (attrs: kind/"
                    "shard/at_sec)",
    "flight.dump": "flight-recorder postmortem written (attrs: reason/"
                   "path)",
    "numerics.audit": "per-device reduction audit of one coalition "
                      "(attrs: subset/rounds/shard_counts/max_ulp/"
                      "first_round/first_leaf/reduction_mode)",
    "numerics.drift": "reduction-order divergence localized (attrs: "
                      "subset/round/leaf/shards/max_ulp) — also dumps a "
                      "flight-recorder postmortem",
    "numerics.ledger": "value-provenance ledger persisted (attrs: path/"
                       "entries/reduction_mode)",
    "fleet.sweep": "one coordinated fleet sweep: spawn shards -> merge "
                   "(attrs: shards/inproc/devices_per_shard)",
    "fleet.shard": "one fleet shard completed (attrs: shard/shards/"
                   "wallclock_s/coalitions)",
    "fleet.merge": "per-shard ledgers/memos merged into one sweep "
                   "(attrs: shards/coalitions/verified/wallclock_s)",
    "fleet.shard_run": "root span of one fleet worker's shard execution "
                       "(attrs: shard/shards/run) — the flow-link target "
                       "of the coordinator's fleet.shard dispatch event "
                       "in the merged Perfetto timeline",
    "fleet.incident": "fleet incident bundle written on shard failure or "
                      "merge refusal (attrs: run/reason/failed_shards/"
                      "path)",
    "fleet.collect": "one FleetCollector pass assembling the cluster "
                     "snapshot (attrs: sources/shards/fresh)",
    "fleet.scrape": "one shard scraped (HTTP /varz or published state) "
                    "by the fleet collector (attrs: shard/source/ok)",
}


def _flight_size() -> int:
    raw = os.environ.get(FLIGHT_SIZE_ENV)
    if raw:
        try:
            n = int(raw)
            if n > 0:
                return n
        except ValueError:
            pass
        import warnings
        warnings.warn(f"{FLIGHT_SIZE_ENV}={raw!r} is not a positive "
                      "integer; using 512", stacklevel=2)
    return 512


# Always-on bounded ring of recent records for the crash flight recorder.
# Sized once at import (the ring is process-global state, like the ids).
_flight_ring: collections.deque = collections.deque(maxlen=_flight_size())


def flight_records() -> list:
    """The flight-recorder ring's current contents, oldest first."""
    return list(_flight_ring)


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def _sink_file():
    """The open JSONL sink, or None. Re-opens when the env var changed.
    An unopenable path degrades to a one-time warning, never an exception
    into the instrumented hot path (the path stays recorded so the failed
    open is not retried on every span). After the atexit close the sink
    stays closed for good — a daemon thread emitting during interpreter
    shutdown must not reopen the file the exit hook just finished (and
    may be converting)."""
    if _sink_state.get("closed"):
        return None
    path = os.environ.get(TRACE_FILE_ENV) or None
    if path == _sink_state["path"]:
        return _sink_state["file"]
    with _lock:
        if path != _sink_state["path"]:
            if _sink_state["file"] is not None:
                try:
                    _sink_state["file"].close()
                except OSError:
                    pass
            _sink_state["path"] = path
            _sink_state["file"] = None
            if path:
                try:
                    _sink_state["file"] = open(path, "a")
                except OSError as e:
                    import warnings
                    warnings.warn(f"{TRACE_FILE_ENV}={path!r} could not be "
                                  f"opened ({e}); tracing to file disabled")
    return _sink_state["file"]


def _emit(record: dict) -> None:
    # fleet trace context: stamped on every record while the coordinator's
    # env injection is in effect, so cross-process correlation never
    # depends on which file a record happened to land in
    run = os.environ.get(FLEET_RUN_ID_ENV)
    if run:
        record["fleet_run"] = run
        shard = os.environ.get(FLEET_TRACE_SHARD_ENV)
        if shard:
            record["fleet_shard"] = shard
    # the flight ring sees EVERY record, sink or not (deque.append is
    # atomic; maxlen bounds it) — the crash recorder must hold the spans
    # of a failure nobody was tracing on purpose
    _flight_ring.append(record)
    f = _sink_file()
    if f is None and not _collectors:
        return
    with _lock:
        for c in _collectors:
            c.append(record)
        if f is not None:
            try:
                f.write(json.dumps(record) + "\n")
                f.flush()
            except ValueError:
                # a record emitted after the atexit hook closed the sink
                # (daemon threads unwinding): the ring has it, drop the
                # file write
                _sink_state["file"] = None


class Span:
    """One timed region. Use as a context manager, or via `start_span` +
    an explicit `end()` (for regions with early returns) / `cancel()`
    (discard without emitting). `duration` is valid after exit."""

    __slots__ = ("name", "attrs", "id", "parent", "ts", "_t0", "duration",
                 "_closed")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.id = next(_ids)
        st = _stack()
        self.parent = st[-1].id if st else None
        st.append(self)
        self.ts = time.time()
        self.duration = None
        self._closed = False
        self._t0 = time.perf_counter()

    def _pop(self) -> None:
        st = _stack()
        # pop up to and including self: robust against out-of-order ends
        # (an early-returning caller that leaked an inner span must not
        # corrupt the nesting of everything that follows)
        while st:
            if st.pop() is self:
                break

    def end(self) -> "Span":
        if self._closed:
            return self
        self.duration = time.perf_counter() - self._t0
        self._closed = True
        self._pop()
        # record built unconditionally: the flight ring is always on
        # (one dict per span; sinks/collectors still gate serialization)
        _emit({"name": self.name, "id": self.id, "parent": self.parent,
               "ts": self.ts, "dur": self.duration,
               "thread": threading.get_ident(), "attrs": self.attrs})
        return self

    def cancel(self) -> None:
        """Close without emitting (duration still recorded)."""
        if self._closed:
            return
        self.duration = time.perf_counter() - self._t0
        self._closed = True
        self._pop()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


def span(name: str, **attrs) -> Span:
    """Context manager: `with span("engine.run_batch", width=16): ...`"""
    return Span(name, attrs)


def start_span(name: str, **attrs) -> Span:
    """Explicit-lifetime variant for regions that outlive one lexical
    block; pair with `.end()` or `.cancel()`."""
    return Span(name, attrs)


def active_span(name: str) -> "Span | None":
    """Innermost OPEN span with `name` on this thread, or None. Lets deep
    engine code attribute metrics to the caller that drove it (e.g. the
    estimator method named by the enclosing `contributivity` span) without
    threading a parameter through every call layer."""
    for sp in reversed(_stack()):
        if sp.name == name:
            return sp
    return None


def event(name: str, dur: float = 0.0, **attrs) -> None:
    """Emit a point-in-time (or externally timed) record without opening a
    span — e.g. a compile whose duration was measured by the caller.
    Always lands in the flight ring; sinks/collectors only when active.

    `ts` is backdated by `dur` so it marks the interval's START, matching
    span records (events are emitted AFTER the measured work — an
    engine.batch fires at harvest end). Timeline consumers (the Perfetto
    exporter) would otherwise draw every externally timed slice one full
    duration too late."""
    st = _stack()
    _emit({"name": name, "id": next(_ids),
           "parent": st[-1].id if st else None,
           "ts": time.time() - float(dur), "dur": float(dur),
           "thread": threading.get_ident(), "attrs": attrs})


class collect:
    """Context manager capturing every record emitted while open:

        with collect() as records:
            ...
        report = sweep_report(records)

    Works with or without the JSONL file sink; nesting is allowed (each
    collector sees every record emitted while it is open)."""

    def __enter__(self) -> list:
        self.records: list = []
        with _lock:
            _collectors.append(self.records)
        return self.records

    def __exit__(self, *exc) -> bool:
        with _lock:
            try:
                _collectors.remove(self.records)
            except ValueError:
                pass
        return False


@atexit.register
def _close_sink_at_exit() -> None:
    """Flush + close the JSONL sink on interpreter exit, so the final
    span of a run is a complete line (a torn tail after a crash is
    invisible to line-oriented tooling — the chrome_trace converter
    tolerates one, but a normal exit should never produce one). When
    `MPLC_TPU_CHROME_TRACE_FILE` is set alongside the trace file, the
    finished JSONL is converted to Chrome trace-event JSON in the same
    hook (the live-export counterpart of scripts/trace_to_perfetto.py)."""
    with _lock:
        f, _sink_state["file"] = _sink_state["file"], None
        _sink_state["path"] = None
        _sink_state["closed"] = True  # _sink_file stays None from here on
    if f is not None:
        try:
            f.flush()
            f.close()
        except (OSError, ValueError):
            pass
    src = os.environ.get(TRACE_FILE_ENV)
    out = os.environ.get("MPLC_TPU_CHROME_TRACE_FILE")
    if src and out and os.path.exists(src):
        try:
            from .chrome_trace import convert
            convert(src, out)
        except Exception as e:  # never let telemetry break exit
            import warnings
            warnings.warn(f"Chrome-trace export to {out!r} failed: {e}")
