"""Zero-dependency structured tracing: spans with monotonic timing, nesting
and an optional JSONL sink.

The coalition engine's hot paths are instrumented with

    with span("engine.dispatch", width=b, slot_count=k):
        ...

Spans always measure duration (two `perf_counter` calls and a thread-local
list push/pop — nanoseconds, no device sync), but a span only *emits* a
record when a sink is active:

  - the JSONL file named by the `MPLC_TPU_TRACE_FILE` env var (checked at
    span end, so tests and long-lived processes can flip it at runtime), or
  - an in-memory collector opened with `collect()` (how `obs.report` and
    `bench.py` gather a run's spans without touching the filesystem).

With neither active the instrumentation is a no-op apart from the timing
itself — no dict building, no serialization, no I/O.

Record schema (one JSON object per line):

    {"name": str, "id": int, "parent": int | null, "ts": float (epoch s),
     "dur": float (s), "thread": int, "attrs": {...}}

Nesting is per-thread (a thread-local span stack); `parent` links a span to
the innermost span open on the same thread when it started. File writes are
serialized by a module lock, so concurrent threads interleave whole lines,
never partial ones.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

_lock = threading.Lock()
_local = threading.local()
_ids = itertools.count(1)
# (path, file) of the currently open JSONL sink; reopened when the env var
# changes between spans. Guarded by _lock.
_sink_state: dict = {"path": None, "file": None}
# active in-memory collectors (lists appended to by _emit). Guarded by _lock.
_collectors: list[list] = []

TRACE_FILE_ENV = "MPLC_TPU_TRACE_FILE"


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def _sink_file():
    """The open JSONL sink, or None. Re-opens when the env var changed.
    An unopenable path degrades to a one-time warning, never an exception
    into the instrumented hot path (the path stays recorded so the failed
    open is not retried on every span)."""
    path = os.environ.get(TRACE_FILE_ENV) or None
    if path == _sink_state["path"]:
        return _sink_state["file"]
    with _lock:
        if path != _sink_state["path"]:
            if _sink_state["file"] is not None:
                try:
                    _sink_state["file"].close()
                except OSError:
                    pass
            _sink_state["path"] = path
            _sink_state["file"] = None
            if path:
                try:
                    _sink_state["file"] = open(path, "a")
                except OSError as e:
                    import warnings
                    warnings.warn(f"{TRACE_FILE_ENV}={path!r} could not be "
                                  f"opened ({e}); tracing to file disabled")
    return _sink_state["file"]


def _emit(record: dict) -> None:
    f = _sink_file()
    if f is None and not _collectors:
        return
    with _lock:
        for c in _collectors:
            c.append(record)
        if f is not None:
            f.write(json.dumps(record) + "\n")
            f.flush()


def _active() -> bool:
    return bool(_collectors) or bool(os.environ.get(TRACE_FILE_ENV))


class Span:
    """One timed region. Use as a context manager, or via `start_span` +
    an explicit `end()` (for regions with early returns) / `cancel()`
    (discard without emitting). `duration` is valid after exit."""

    __slots__ = ("name", "attrs", "id", "parent", "ts", "_t0", "duration",
                 "_closed")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.id = next(_ids)
        st = _stack()
        self.parent = st[-1].id if st else None
        st.append(self)
        self.ts = time.time()
        self.duration = None
        self._closed = False
        self._t0 = time.perf_counter()

    def _pop(self) -> None:
        st = _stack()
        # pop up to and including self: robust against out-of-order ends
        # (an early-returning caller that leaked an inner span must not
        # corrupt the nesting of everything that follows)
        while st:
            if st.pop() is self:
                break

    def end(self) -> "Span":
        if self._closed:
            return self
        self.duration = time.perf_counter() - self._t0
        self._closed = True
        self._pop()
        if _active():
            _emit({"name": self.name, "id": self.id, "parent": self.parent,
                   "ts": self.ts, "dur": self.duration,
                   "thread": threading.get_ident(), "attrs": self.attrs})
        return self

    def cancel(self) -> None:
        """Close without emitting (duration still recorded)."""
        if self._closed:
            return
        self.duration = time.perf_counter() - self._t0
        self._closed = True
        self._pop()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


def span(name: str, **attrs) -> Span:
    """Context manager: `with span("engine.run_batch", width=16): ...`"""
    return Span(name, attrs)


def start_span(name: str, **attrs) -> Span:
    """Explicit-lifetime variant for regions that outlive one lexical
    block; pair with `.end()` or `.cancel()`."""
    return Span(name, attrs)


def active_span(name: str) -> "Span | None":
    """Innermost OPEN span with `name` on this thread, or None. Lets deep
    engine code attribute metrics to the caller that drove it (e.g. the
    estimator method named by the enclosing `contributivity` span) without
    threading a parameter through every call layer."""
    for sp in reversed(_stack()):
        if sp.name == name:
            return sp
    return None


def event(name: str, dur: float = 0.0, **attrs) -> None:
    """Emit a point-in-time (or externally timed) record without opening a
    span — e.g. a compile whose duration was measured by the caller."""
    if not _active():
        return
    st = _stack()
    _emit({"name": name, "id": next(_ids),
           "parent": st[-1].id if st else None,
           "ts": time.time(), "dur": float(dur),
           "thread": threading.get_ident(), "attrs": attrs})


class collect:
    """Context manager capturing every record emitted while open:

        with collect() as records:
            ...
        report = sweep_report(records)

    Works with or without the JSONL file sink; nesting is allowed (each
    collector sees every record emitted while it is open)."""

    def __enter__(self) -> list:
        self.records: list = []
        with _lock:
            _collectors.append(self.records)
        return self.records

    def __exit__(self, *exc) -> bool:
        with _lock:
            try:
                _collectors.remove(self.records)
            except ValueError:
                pass
        return False
