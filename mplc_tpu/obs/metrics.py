"""Process-global metrics registry: counters, gauges, histograms.

The sweep telemetry layer (obs/trace.py gives *when*, this module gives
*how much*): compile seconds, coalitions evaluated, memo hits/misses,
padding waste, epochs trained, device-memory high water. Everything is
host-side arithmetic — incrementing a counter never syncs the device.

Metric names used by the instrumented paths:

    trainer.compiles_total            counter  jit cache-miss compiles
    trainer.compile_seconds_total     counter  wall-clock spent compiling
    trainer.compiles[<fn>]            counter  per-executable compile count
    trainer.compile_seconds[<fn>]     counter  per-executable compile time
    engine.memo_hits                  counter  v(S) served from the memo
    engine.memo_misses                counter  v(S) requiring training
    engine.coalitions_evaluated       counter  coalitions actually trained
    engine.epochs_trained             counter  coalition-epochs executed
    engine.samples_trained            counter  training samples consumed
                                               (non-padding coalitions)
    engine.partner_passes             counter  partner passes dispatched
                                               (epochs x minibatches x
                                               slots-or-P: slot execution
                                               runs <= slot_count where the
                                               masked path runs P)
    engine.batches                    counter  device batches harvested —
                                               the dispatch-count view a
                                               seed-ensemble sweep must
                                               grow SUB-linearly in K
                                               (replica rows pack into the
                                               padding a single-seed sweep
                                               wastes; asserted in
                                               tests/test_partner_faults)
    engine.pad_waste_fraction         histogram per-batch padding fraction
    engine.device_mem_high_water_bytes gauge   peak bytes (memory_stats)
    engine.retries                    counter  transient-failure batch
                                               retries (dispatch + harvest)
    engine.backoff_sec                counter  seconds slept in retry
                                               backoff
    engine.cap_halvings               counter  rungs taken down the OOM
                                               cap-degradation ladder
    engine.cpu_degraded_batches       counter  batches run on the ladder's
                                               terminal per-batch CPU path
    engine.cpu_degraded_coalitions    counter  coalitions trained there
    engine.faults_injected            counter  faults fired by the
                                               MPLC_TPU_FAULT_PLAN hook

`snapshot()` exports the whole registry as a plain dict (JSON-ready);
`reset()` clears it (tests and per-run report boundaries).
"""

from __future__ import annotations

import math
import threading

_lock = threading.Lock()
_registry: dict = {}


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        with _lock:
            self.value += v


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v: float) -> None:
        with _lock:
            self.value = v

    def set_max(self, v: float) -> None:
        """High-water-mark update (device_mem_high_water)."""
        with _lock:
            if self.value is None or v > self.value:
                self.value = v


class Histogram:
    """Streaming count/sum/min/max/mean — enough for padding-waste and
    batch-duration distributions without bucket-boundary bikeshedding."""

    __slots__ = ("name", "count", "total", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        with _lock:
            self.count += 1
            self.total += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v


def _get(name: str, cls):
    m = _registry.get(name)
    if m is None:
        with _lock:
            m = _registry.get(name)
            if m is None:
                m = _registry[name] = cls(name)
    if not isinstance(m, cls):
        raise TypeError(f"metric {name!r} is a {type(m).__name__}, "
                        f"not a {cls.__name__}")
    return m


def counter(name: str) -> Counter:
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    return _get(name, Gauge)


def histogram(name: str) -> Histogram:
    return _get(name, Histogram)


def snapshot() -> dict:
    """The whole registry as {counters, gauges, histograms} of plain
    numbers — JSON-serializable, suitable for the sweep-report sidecar."""
    with _lock:
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(_registry.items()):
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = {
                    "count": m.count, "sum": m.total,
                    "min": m.min if m.count else None,
                    "max": m.max if m.count else None,
                    "mean": m.total / m.count if m.count else None,
                }
        return out


def reset() -> None:
    with _lock:
        _registry.clear()


def sample_device_memory(gauge_name: str = "engine.device_mem_high_water_bytes") -> None:
    """Record the device's peak allocated bytes via `memory_stats()` (a
    host-side query, no sync). No-op on backends without the API (CPU)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return
        peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        if peak is not None:
            gauge(gauge_name).set_max(int(peak))
    except Exception:
        pass
