"""Process-global metrics registry: counters, gauges, histograms.

The sweep telemetry layer (obs/trace.py gives *when*, this module gives
*how much*): compile seconds, coalitions evaluated, memo hits/misses,
padding waste, epochs trained, device-memory high water. Everything is
host-side arithmetic — incrementing a counter never syncs the device.

Metrics may carry LABELS (`counter("service.queue_wait_sec",
tenant="t0")`): each distinct (name, labels) pair is its own metric
object, keyed in the registry as `name{k=v,...}` with sorted label keys.
Labels are how the multi-tenant service exports per-tenant SLO series to
the `/metrics` endpoint (obs/export.py) without inventing one metric
name per tenant; unlabeled metrics keep their plain-`name` keys, so
every pre-label snapshot consumer reads unchanged.

Histograms record count/sum/min/max PLUS fixed log2 bucket counts
(`LOG_BUCKET_BOUNDS`, ~1e-6 .. 4096 — seconds-and-fractions scale), so
p50/p95/p99 are derivable at read time (`Histogram.quantile`) and the
Prometheus exporter can emit real `_bucket{le=...}` series. Bucket
boundaries are process-wide constants: two histograms are always
mergeable, and a quantile is at worst one bucket-width (2x) off.

Metric names used by the instrumented paths:

    trainer.compiles_total            counter  jit cache-miss compiles
    trainer.compile_seconds_total     counter  wall-clock spent compiling
    trainer.compiles[<fn>]            counter  per-executable compile count
    trainer.compile_seconds[<fn>]     counter  per-executable compile time
    engine.memo_hits                  counter  v(S) served from the memo
    engine.memo_misses                counter  v(S) requiring training
    engine.coalitions_evaluated       counter  coalitions actually trained
    engine.epochs_trained             counter  coalition-epochs executed
    engine.samples_trained            counter  training samples consumed
                                               (non-padding coalitions)
    engine.partner_passes             counter  partner passes dispatched
                                               (epochs x minibatches x
                                               slots-or-P: slot execution
                                               runs <= slot_count where the
                                               masked path runs P)
    engine.batches                    counter  device batches harvested —
                                               the dispatch-count view a
                                               seed-ensemble sweep must
                                               grow SUB-linearly in K
                                               (replica rows pack into the
                                               padding a single-seed sweep
                                               wastes; asserted in
                                               tests/test_partner_faults)
    engine.pad_waste_fraction         histogram per-batch padding fraction
    engine.device_mem_high_water_bytes gauge   peak bytes (memory_stats)
    engine.retries                    counter  transient-failure batch
                                               retries (dispatch + harvest)
    engine.backoff_sec                counter  seconds slept in retry
                                               backoff
    engine.cap_halvings               counter  rungs taken down the OOM
                                               cap-degradation ladder
    engine.cpu_degraded_batches       counter  batches run on the ladder's
                                               terminal per-batch CPU path
    engine.cpu_degraded_coalitions    counter  coalitions trained there
    engine.faults_injected            counter  faults fired by the
                                               MPLC_TPU_FAULT_PLAN hook
    engine.device_step_sec            histogram measured device-step
                                               seconds of FENCED batches
                                               (MPLC_TPU_DEVICE_FENCE_RATE,
                                               obs/devcost.py — a host
                                               fetch timed with the
                                               pipeline overlap drained)
    obs.memory_sample_errors          counter  sample_device_memory
                                               failures (warned once)
    obs.flight_dumps                  counter  flight-recorder postmortems
                                               written (obs/flight.py)

Per-tenant SLO series (service/scheduler.py, labeled `tenant=...`):

    service.queue_wait_sec            histogram submit -> first quantum
    service.time_to_first_value_sec   histogram submit -> first streamed
                                               v(S)
    service.slice_sec                 histogram scheduling-quantum span
    service.deadline_misses           counter  jobs cancelled past their
                                               deadline_sec
    service.job_retries               counter  failed attempts re-queued
    service.job_attempts              histogram attempts at job terminal
    service.device_seconds            counter  metered device-seconds
                                               billed per tenant
                                               (obs/devcost.py: fenced-
                                               sample extrapolation,
                                               cost-model when fences
                                               are off; journaled with
                                               job terminals and
                                               restored on replay, so
                                               restarts don't lose
                                               billing)

Overload accounting (unlabeled; service/admission.py governor):

    service.jobs_shed                 counter  queued jobs terminated by
                                               the overload governor
                                               (classified JobShed —
                                               separate from rejected /
                                               cancelled / quarantined)

`snapshot()` exports the whole registry as a plain dict (JSON-ready);
`reset()` clears it (tests and per-run report boundaries);
`export_view()` returns structured rows (name, labels, kind, values) for
the Prometheus renderer.
"""

from __future__ import annotations

import bisect
import math
import threading

_lock = threading.Lock()
_registry: dict = {}

# Fixed log2 bucket upper bounds shared by every histogram: 2^-20
# (~0.95 us) .. 2^12 (4096). Seconds-scale latencies, fractions in [0,1]
# and small counts all land inside; anything larger goes to +Inf.
LOG_BUCKET_BOUNDS = tuple(2.0 ** e for e in range(-20, 13))


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        with _lock:
            self.value += v


class Gauge:
    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self.value = None

    def set(self, v: float) -> None:
        with _lock:
            self.value = v

    def set_max(self, v: float) -> None:
        """High-water-mark update (device_mem_high_water)."""
        with _lock:
            if self.value is None or v > self.value:
                self.value = v


class Histogram:
    """Streaming count/sum/min/max plus fixed log2 bucket counts — enough
    for padding-waste and latency distributions with exportable
    p50/p95/p99, without per-metric bucket-boundary bikeshedding."""

    __slots__ = ("name", "labels", "count", "total", "min", "max",
                 "bucket_counts")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        # one count per LOG_BUCKET_BOUNDS entry, plus the +Inf bucket
        self.bucket_counts = [0] * (len(LOG_BUCKET_BOUNDS) + 1)

    def observe(self, v: float) -> None:
        with _lock:
            self.count += 1
            self.total += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            # le-inclusive, Prometheus-style: bucket i counts v <= bound_i
            self.bucket_counts[bisect.bisect_left(LOG_BUCKET_BOUNDS, v)] += 1

    def quantile(self, q: float) -> float | None:
        """Log-bucket quantile estimate: the upper bound of the bucket
        holding the q-th ranked observation, clamped to the observed
        [min, max] (so tight distributions report exact-ish values and
        the +Inf bucket degrades to the observed max). None when empty."""
        with _lock:
            return _locked_quantile(self, q)


def _get(name: str, cls, labels: dict | None = None):
    labels = dict(labels or {})
    key = _key(name, labels)
    m = _registry.get(key)
    if m is None:
        with _lock:
            m = _registry.get(key)
            if m is None:
                m = _registry[key] = cls(name, labels)
    if not isinstance(m, cls):
        raise TypeError(f"metric {key!r} is a {type(m).__name__}, "
                        f"not a {cls.__name__}")
    return m


def counter(name: str, **labels) -> Counter:
    return _get(name, Counter, labels)


def gauge(name: str, **labels) -> Gauge:
    return _get(name, Gauge, labels)


def histogram(name: str, **labels) -> Histogram:
    return _get(name, Histogram, labels)


def snapshot() -> dict:
    """The whole registry as {counters, gauges, histograms} of plain
    numbers — JSON-serializable, suitable for the sweep-report sidecar.
    Labeled metrics appear under their `name{k=v,...}` registry keys;
    histogram entries carry log-bucket p50/p95/p99 estimates."""
    with _lock:
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for key, m in sorted(_registry.items()):
            if isinstance(m, Counter):
                out["counters"][key] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = {
                    "count": m.count, "sum": m.total,
                    "min": m.min if m.count else None,
                    "max": m.max if m.count else None,
                    "mean": m.total / m.count if m.count else None,
                    "p50": _locked_quantile(m, 0.50),
                    "p95": _locked_quantile(m, 0.95),
                    "p99": _locked_quantile(m, 0.99),
                    # raw log2 bucket counts (+Inf last): bounds are the
                    # process-wide LOG_BUCKET_BOUNDS constant, so two
                    # snapshots from different processes merge exactly
                    # (merge_snapshots) — the fleet collector depends on
                    # this field being present in every shard snapshot.
                    "bucket_counts": list(m.bucket_counts),
                }
        return out


def _locked_quantile(m: Histogram, q: float) -> float | None:
    """Histogram.quantile body for callers already holding `_lock`."""
    if not m.count:
        return None
    return bucket_quantile(m.bucket_counts, m.count, m.min, m.max, q)


def bucket_quantile(bucket_counts, count, mn, mx, q: float) -> float | None:
    """Nearest-rank quantile over shared-log2-bucket counts: the upper
    bound of the bucket holding the q-th ranked observation, clamped to
    the observed [min, max]. Pure arithmetic on plain values so merged
    (cross-process) histograms use the EXACT same estimator as live
    Histogram objects — that identity is what makes fleet-merged
    quantiles equal pooled-sample quantiles at bucket granularity."""
    if not count:
        return None
    rank = max(1, math.ceil(q * count))
    cum = 0
    for i, c in enumerate(bucket_counts):
        cum += c
        if cum >= rank:
            bound = (LOG_BUCKET_BOUNDS[i]
                     if i < len(LOG_BUCKET_BOUNDS) else mx)
            return min(max(bound, mn), mx)
    return mx


def merge_snapshots(snaps) -> dict:
    """Merge `snapshot()` dicts from multiple processes (fleet shards)
    into one cluster-level snapshot. Semantics per kind:

      counters    summed — fleet totals (device-seconds, batches, shed).
      gauges      max of non-None values — every exported gauge is a
                  high-water mark (device_mem_high_water_bytes), so the
                  fleet value is the worst shard's.
      histograms  exact merge: counts/sums/bucket_counts summed,
                  min/max combined. Because every histogram shares
                  LOG_BUCKET_BOUNDS, the merged buckets are identical to
                  a histogram fed the pooled raw samples, so merged
                  p50/p95/p99 EQUAL pooled-sample quantiles (not an
                  approximation on top of an approximation).

    Snapshots missing `bucket_counts` (pre-merge-era producers) degrade
    gracefully: their counts/sums still aggregate, quantiles come from
    whatever buckets are present. Non-dict entries are skipped."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    merged_h: dict = {}
    for snap in snaps or ():
        if not isinstance(snap, dict):
            continue
        for k, v in (snap.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                out["counters"][k] = out["counters"].get(k, 0.0) + v
        for k, v in (snap.get("gauges") or {}).items():
            cur = out["gauges"].get(k)
            if v is None:
                out["gauges"].setdefault(k, None)
            else:
                out["gauges"][k] = v if cur is None else max(cur, v)
        for k, h in (snap.get("histograms") or {}).items():
            if not isinstance(h, dict) or not h.get("count"):
                merged_h.setdefault(
                    k, {"count": 0, "sum": 0.0, "min": math.inf,
                        "max": -math.inf,
                        "bucket_counts": [0] * (len(LOG_BUCKET_BOUNDS) + 1)})
                continue
            acc = merged_h.setdefault(
                k, {"count": 0, "sum": 0.0, "min": math.inf,
                    "max": -math.inf,
                    "bucket_counts": [0] * (len(LOG_BUCKET_BOUNDS) + 1)})
            acc["count"] += int(h.get("count") or 0)
            acc["sum"] += float(h.get("sum") or 0.0)
            if h.get("min") is not None:
                acc["min"] = min(acc["min"], float(h["min"]))
            if h.get("max") is not None:
                acc["max"] = max(acc["max"], float(h["max"]))
            bc = h.get("bucket_counts")
            if isinstance(bc, (list, tuple)):
                for i, c in enumerate(bc[:len(acc["bucket_counts"])]):
                    acc["bucket_counts"][i] += int(c or 0)
    for k, acc in merged_h.items():
        n = acc["count"]
        out["histograms"][k] = {
            "count": n, "sum": acc["sum"],
            "min": acc["min"] if n else None,
            "max": acc["max"] if n else None,
            "mean": acc["sum"] / n if n else None,
            "p50": bucket_quantile(acc["bucket_counts"], n,
                                   acc["min"], acc["max"], 0.50),
            "p95": bucket_quantile(acc["bucket_counts"], n,
                                   acc["min"], acc["max"], 0.95),
            "p99": bucket_quantile(acc["bucket_counts"], n,
                                   acc["min"], acc["max"], 0.99),
            "bucket_counts": acc["bucket_counts"],
        }
    return out


def export_view() -> list:
    """Structured registry rows for the Prometheus renderer
    (obs/export.py): `[{name, labels, kind, ...}]` with histogram rows
    carrying the shared bucket bounds and per-bucket counts."""
    with _lock:
        rows = []
        for key, m in sorted(_registry.items()):
            row = {"name": m.name, "labels": dict(m.labels)}
            if isinstance(m, Counter):
                row.update(kind="counter", value=m.value)
            elif isinstance(m, Gauge):
                row.update(kind="gauge", value=m.value)
            else:
                row.update(kind="histogram", count=m.count, sum=m.total,
                           bounds=LOG_BUCKET_BOUNDS,
                           bucket_counts=list(m.bucket_counts))
            rows.append(row)
        return rows


def reset() -> None:
    with _lock:
        _registry.clear()


_mem_sample_warned = False


def sample_device_memory(gauge_name: str = "engine.device_mem_high_water_bytes") -> None:
    """Record the device's peak allocated bytes via `memory_stats()` (a
    host-side query, no sync). A backend without the API (CPU) returning
    no stats is a silent no-op; an actual FAILURE (import error, dead
    tunnel, runtime raise) is counted in `obs.memory_sample_errors` and
    warned ONCE per process — a fleet whose memory telemetry silently
    stopped is how an OOM postmortem ends up with no HBM data."""
    global _mem_sample_warned
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return
        peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        if peak is not None:
            gauge(gauge_name).set_max(int(peak))
    except Exception as e:
        counter("obs.memory_sample_errors").inc()
        if not _mem_sample_warned:
            _mem_sample_warned = True
            import warnings
            warnings.warn(
                f"sample_device_memory failed ({e}); further failures are "
                "counted in obs.memory_sample_errors without warning",
                stacklevel=2)
