"""Device-time attribution and XLA cost accounting.

Everything the obs layer measured before this module is HOST wall-clock:
JAX dispatch is asynchronous, so an `engine.dispatch` span times enqueue,
an `engine.batch` duration times dispatch-start → harvest-end (which
under batch pipelining overlaps its neighbors), and the compute row's
`mfu_proxy` rests on the hand-derived `models/zoo.fwd_flops_per_sample`
estimate. This module supplies the three device-side primitives the rest
of the observability plane builds on:

  1. **XLA cost truth** — `cost_analysis(compiled)` /
     `bundle_cost(bundle)` harvest `Compiled.cost_analysis()` (flops,
     bytes accessed, transcendentals) from AOT-compiled executables at
     compile time. The program bank (contrib/bank.py) attaches the cost
     to every bundle and persists it in its manifest; `engine.batch`
     events then carry per-batch modeled flops/bytes, and the sweep
     report derives a per-program ROOFLINE row (achieved FLOP/s vs peak,
     bytes/s vs HBM bandwidth, arithmetic intensity) plus an XLA-derived
     `mfu_xla` that supersedes the analytic proxy when available.
     Backends/executables without cost analysis (some CPU builds, the
     OOM-rebucketed inline-jit fallback widths) degrade to None and the
     report falls back to the analytic proxy — schema unchanged.

  2. **Sampled device fences** — `fence_interval()` parses
     `MPLC_TPU_DEVICE_FENCE_RATE` (default 1/16; 0 = off) into a batch-
     ordinal stride and `should_fence(ordinal, interval)` decides
     deterministically, so a replayed run fences the same batches. A
     fenced batch is dispatched with the pipeline overlap drained and
     its results are fetched to the host immediately (a host fetch, not
     `block_until_ready` — the axon tunnel does not reliably sync the
     latter), timing a true device-step-seconds sample
     (`engine.device_step_sec` histogram, `engine.device_fence` event).
     Fencing never changes v(S): it only moves harvest points
     (equality-tested in tests/test_devcost.py, fault ladder included).

  3. **Device-seconds metering** — `DeviceMeter` accumulates per-engine
     batch accounting (coalitions, host span, fenced seconds, modeled
     flops) and `estimate_device_seconds(delta, peak)` turns a delta of
     it into billable device-seconds with an explicit BASIS:
     `"fenced"` (fenced samples extrapolated over all coalitions:
     sec/coalition × coalitions), `"cost_model"` (XLA flops / fleet
     peak — used when fences are off), `"host_span"` (the old
     span-seconds, the last resort), `"none"`. The sweep service bills
     each scheduling quantum's delta to the owning tenant
     (`service.device_seconds{tenant=...}`), journals the meter with job
     terminals, and switches the report's `cost_share` to device-seconds
     (span-seconds kept as `host_share`).

Chip tables (public Google Cloud TPU spec figures) provide bf16 peak
FLOP/s and HBM bandwidth per chip for the roofline axes; unknown kinds
(including host CPU) return None and every derived cell degrades to
"n/a" rather than inventing a number.
"""

from __future__ import annotations

import logging
import threading

from .. import constants

logger = logging.getLogger("mplc_tpu")

# sample ~1 batch in 16 by default: one extra sync per 16 batches is
# noise next to a training batch, and small sweeps still get a sample
# (ordinal 1 is always fenced when fencing is on)
DEFAULT_FENCE_RATE = 1.0 / 16.0

# bf16 peak FLOP/s per chip — Google Cloud TPU public spec pages
# (v4 275 TFLOP/s, v5e 197, v5p 459, v6e/Trillium 918)
_PEAK_FLOPS_BF16 = {
    "tpu v5 lite": 197e12, "tpu v5e": 197e12, "tpu v5p": 459e12,
    "tpu v4": 275e12, "tpu v6 lite": 918e12, "tpu v6e": 918e12,
}
# HBM bandwidth, bytes/s per chip — same spec pages (v4 1.2 TB/s,
# v5e 0.82 TB/s, v5p 2.77 TB/s, v6e 1.64 TB/s)
_HBM_BYTES_PER_S = {
    "tpu v5 lite": 819e9, "tpu v5e": 819e9, "tpu v5p": 2765e9,
    "tpu v4": 1228e9, "tpu v6 lite": 1640e9, "tpu v6e": 1640e9,
}


# -- sampled device fences ----------------------------------------------------

def fence_interval(rate: "float | None" = None) -> int:
    """The batch-ordinal stride of the device-fence sampler: 0 = fencing
    off, else every `interval`-th batch (ordinal 1 included) runs fenced.
    `rate` defaults to `MPLC_TPU_DEVICE_FENCE_RATE` (warn+fallback parse,
    same contract as every other engine knob); rates above 1 clamp to
    fence-every-batch."""
    if rate is None:
        rate = constants._env_nonneg_float(
            constants.DEVICE_FENCE_RATE_ENV, DEFAULT_FENCE_RATE)
    if rate <= 0:
        return 0
    return max(1, int(round(1.0 / min(rate, 1.0))))


def should_fence(ordinal: int, interval: int) -> bool:
    """Deterministic sampling decision for 1-based batch `ordinal`: pure
    in (ordinal, interval), so a replayed run — any retry/recovery
    schedule included — fences the same ordinals. Ordinal 1 is always a
    sample when fencing is on (short runs still measure something)."""
    return bool(interval) and ordinal % interval == 1 % interval


# -- XLA cost harvesting ------------------------------------------------------

def cost_analysis(compiled) -> "dict | None":
    """`{"flops", "bytes_accessed", "transcendentals"}` floats from a
    `Compiled.cost_analysis()`, or None when the backend/executable does
    not expose it (older runtimes, some fallback paths). Tolerates both
    the list-wrapped (one dict per partition) and bare-dict forms and
    missing keys: `flops` is required for the result to be useful, the
    other fields degrade to 0.0."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    if flops is None:
        return None
    try:
        return {
            "flops": float(flops),
            # XLA's key has a space; normalize for JSON/attr consumers
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
            "transcendentals": float(ca.get("transcendentals", 0.0)
                                     or 0.0),
        }
    except (TypeError, ValueError):
        # an exotic cost-analysis schema (non-numeric values) degrades
        # to "no cost truth", never to an exception in the compile path
        return None


def bundle_cost(bundle: dict) -> "dict | None":
    """Summed cost analysis of a program-bank bundle's executables
    (init + run + fin = exactly one batch execution; the epoch-chunk
    `run` dominates). None when NO executable exposes flops — a partial
    bundle (e.g. only `run` costed) still yields the partial sum, which
    is the conservative direction for an achieved-FLOP/s figure."""
    total = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0}
    any_cost = False
    for name in ("init", "run", "fin"):
        c = bundle.get(name)
        if c is None:
            continue
        cost = cost_analysis(c)
        if cost is None:
            continue
        any_cost = True
        for k in total:
            total[k] += cost[k]
    return total if any_cost else None


# -- chip tables --------------------------------------------------------------

def peak_flops_per_chip(device_kind: str) -> "float | None":
    """bf16 peak FLOP/s of one chip by `device_kind` substring match;
    None for unknown kinds and host CPU (no peak = no MFU, never a
    made-up denominator)."""
    kind = (device_kind or "").lower()
    for k, v in _PEAK_FLOPS_BF16.items():
        if k in kind:
            return v
    return None


def hbm_bytes_per_s_per_chip(device_kind: str) -> "float | None":
    """HBM bandwidth (bytes/s) of one chip; None when unknown."""
    kind = (device_kind or "").lower()
    for k, v in _HBM_BYTES_PER_S.items():
        if k in kind:
            return v
    return None


# the fleet is fixed for the process lifetime, and the scheduler asks at
# every quantum billing — memoize so metering never re-queries the
# backend (jax.devices() can cross a tunnel on remote backends)
_FLEET_CACHE: dict = {}


def fleet_peak_flops() -> "float | None":
    """The attached fleet's aggregate bf16 peak (per-chip peak × device
    count), or None on unknown chips / host CPU / no importable jax.
    Memoized per process."""
    if "peak" not in _FLEET_CACHE:
        try:
            import jax
            devs = jax.devices()
            peak = peak_flops_per_chip(devs[0].device_kind)
            _FLEET_CACHE["peak"] = peak * len(devs) if peak else None
        except Exception:
            _FLEET_CACHE["peak"] = None
    return _FLEET_CACHE["peak"]


def fleet_hbm_bytes_per_s() -> "float | None":
    """Aggregate HBM bandwidth of the attached fleet, or None.
    Memoized per process."""
    if "hbm" not in _FLEET_CACHE:
        try:
            import jax
            devs = jax.devices()
            bw = hbm_bytes_per_s_per_chip(devs[0].device_kind)
            _FLEET_CACHE["hbm"] = bw * len(devs) if bw else None
        except Exception:
            _FLEET_CACHE["hbm"] = None
    return _FLEET_CACHE["hbm"]


# -- the device-seconds meter -------------------------------------------------

_METER_FIELDS = ("batches", "coalitions", "span_sec", "fenced_batches",
                 "fenced_coalitions", "fenced_sec", "flops",
                 "bytes_accessed", "costed_coalitions",
                 "eval_coalitions", "eval_span_sec",
                 "degraded_coalitions", "degraded_span_sec")

# billing-basis trust order, best first
_BASIS_RANK = ("fenced", "cost_model", "host_span", "none")


class DeviceMeter:
    """Per-engine device-time accounting: every harvested batch notes its
    coalition count and host span, fenced batches add their measured
    device seconds, and bank-served batches add their XLA-modeled
    flops/bytes. Thread-safe (the service's worker pool bills deltas of
    one engine from its owning worker, but /varz snapshots concurrently).
    """

    __slots__ = ("interval", "_lock") + _METER_FIELDS

    def __init__(self, interval: int = 0):
        self.interval = interval
        self._lock = threading.Lock()
        for f in _METER_FIELDS:
            setattr(self, f, 0 if f not in ("span_sec", "fenced_sec",
                                            "flops", "bytes_accessed",
                                            "eval_span_sec",
                                            "degraded_span_sec")
                    else 0.0)

    def note(self, coalitions: int, span_sec: float = 0.0,
             device_sec: "float | None" = None,
             flops: "float | None" = None,
             bytes_accessed: "float | None" = None,
             eval_only: bool = False, degraded: bool = False) -> None:
        """One harvested batch's accounting (padding rows excluded from
        `coalitions`, like every other throughput counter). `eval_only`
        marks reconstruction batches (retrain-free estimators) and
        `degraded` marks the OOM ladder's CPU-rung batches: both cost
        wildly differently from a fenced device training batch (orders
        of magnitude cheaper / slower respectively), so each is tracked
        in its own class, billed at its own host span, and NEVER mixed
        into the fenced training-rate extrapolation. (The CPU rung is
        synchronous, so its host span IS its compute time.)"""
        with self._lock:
            self.batches += 1
            self.coalitions += int(coalitions)
            self.span_sec += float(span_sec)
            if eval_only:
                self.eval_coalitions += int(coalitions)
                self.eval_span_sec += float(span_sec)
            elif degraded:
                self.degraded_coalitions += int(coalitions)
                self.degraded_span_sec += float(span_sec)
            if device_sec is not None:
                self.fenced_batches += 1
                self.fenced_coalitions += int(coalitions)
                self.fenced_sec += float(device_sec)
            if flops:
                self.flops += float(flops)
                self.bytes_accessed += float(bytes_accessed or 0.0)
                self.costed_coalitions += int(coalitions)

    def snapshot(self) -> dict:
        with self._lock:
            return {f: getattr(self, f) for f in _METER_FIELDS}

    def device_seconds(self, peak_flops: "float | None" = None
                       ) -> "tuple[float, str]":
        """Lifetime (seconds, basis) — see `estimate_device_seconds`."""
        return estimate_device_seconds(self.snapshot(), peak_flops)


def meter_delta(before: dict, after: dict) -> dict:
    """Field-wise `after - before` of two meter snapshots (the unit the
    scheduler bills per quantum)."""
    return {f: after.get(f, 0) - before.get(f, 0) for f in _METER_FIELDS}


def estimate_device_seconds(totals: dict,
                            peak_flops: "float | None" = None
                            ) -> "tuple[float, str]":
    """(device_seconds, basis) for a meter snapshot or delta.

    Basis order — most to least trusted:
      "fenced":      measured fenced seconds extrapolated over every
                     TRAINING coalition (sec/coalition × train
                     coalitions; the documented extrapolation rule —
                     batch widths vary, so the per-coalition rate is
                     the stable unit). Eval-only reconstruction
                     coalitions (orders of magnitude cheaper) and
                     CPU-degraded-rung coalitions (orders of magnitude
                     slower, and synchronous) are billed at their own
                     host span instead of the device training rate;
      "cost_model":  XLA-modeled flops (scaled up for un-costed
                     training coalitions by the same per-coalition
                     rule) over the fleet's peak FLOP/s — an OPTIMISTIC
                     lower bound (assumes peak-rate execution), used
                     when fences are off and a peak figure exists;
      "host_span":   summed per-batch host spans (dispatch→harvest) —
                     the pre-devcost behavior, kept as the explicit
                     last resort (over-counts under batch pipelining);
      "none":        no signal at all (0.0 seconds).
    """
    coalitions = totals.get("coalitions", 0)
    eval_c = totals.get("eval_coalitions", 0)
    deg_c = totals.get("degraded_coalitions", 0)
    # eval-only and CPU-degraded batches bill at their own (synchronous)
    # host span — only clean device TRAINING coalitions ride the fenced
    # or cost-model rate
    extra = (totals.get("eval_span_sec", 0.0)
             + totals.get("degraded_span_sec", 0.0))
    train_c = coalitions - eval_c - deg_c
    fenced_c = totals.get("fenced_coalitions", 0)
    if fenced_c > 0 and train_c > 0:
        per = totals.get("fenced_sec", 0.0) / fenced_c
        return per * train_c + extra, "fenced"
    flops = totals.get("flops", 0.0)
    costed_c = totals.get("costed_coalitions", 0)
    if flops > 0 and peak_flops:
        scale = (train_c / costed_c) if costed_c and train_c > 0 else 1.0
        return flops * scale / peak_flops + extra, "cost_model"
    span = totals.get("span_sec", 0.0)
    if span > 0:
        return span, "host_span"
    return 0.0, "none"


def merge_basis(a: "str | None", b: "str | None") -> "str | None":
    """The most-trusted basis either argument carries (a job whose
    quanta billed under mixed bases reports the best one; the per-quantum
    `service.slice` attrs keep the exact per-delta basis)."""
    if a is None:
        return b
    if b is None:
        return a
    return a if _BASIS_RANK.index(a) <= _BASIS_RANK.index(b) else b
