"""Config loading, experiment-grid expansion, logging, result folders.

Mirrors /root/reference/mplc/utils.py: YAML experiment files with the shape
{experiment_name, n_repeats, scenario_params_list}, where every list-valued
parameter is grid-expanded via itertools.product into one scenario per
combination (utils.py:41-91), including the dataset-name dict sub-syntax for
`init_model_from` (utils.py:62-71).

Logging uses stdlib `logging` (the reference uses loguru, which is not
available here) with the same split: console + per-experiment info.log /
debug.log files (utils.py:165-200).
"""

from __future__ import annotations

import argparse
import datetime
import logging
import sys
from itertools import product
from pathlib import Path
from shutil import copyfile

import yaml

from . import constants

logger = logging.getLogger("mplc_tpu")


def load_cfg(yaml_filepath):
    logger.info("Loading experiment yaml file")
    with open(yaml_filepath, "r") as stream:
        cfg = yaml.safe_load(stream)
    logger.info(str(cfg))
    return cfg


def _expand_dataset_dict(block):
    """Yield one grid block per dataset when `dataset_name` uses the dict
    sub-syntax `{mnist: [path, ...], cifar10: ~}`: each dataset becomes its
    own block whose `init_model_from` axis is the mapped value (or
    `random_initialization` for null) — reference utils.py:62-71 semantics.
    """
    names = block.get("dataset_name")
    if not isinstance(names, dict):
        yield block
        return
    for name, warm_starts in names.items():
        sub = dict(block)
        sub["dataset_name"] = [name]
        sub["init_model_from"] = (["random_initialization"]
                                  if warm_starts is None else warm_starts)
        yield sub


def _check_per_partner_lengths(scenario):
    """Cross-field validation: every per-partner list must have exactly
    `partners_count` entries (reference utils.py:80-87)."""
    n = scenario["partners_count"]
    amounts = scenario["amounts_per_partner"]
    if len(amounts) != n:
        raise Exception(
            f"amounts_per_partner has {len(amounts)} entries but the "
            f"scenario declares {n} partners.")
    split = scenario.get("samples_split_option")
    if split is not None and split[0] == "advanced" and len(split[1]) != n:
        raise Exception(
            f"advanced samples_split_option describes {len(split[1])} "
            f"partners but the scenario declares {n}.")
    if "corrupted_datasets" in scenario and \
            len(scenario["corrupted_datasets"]) != n:
        raise Exception(
            f"corrupted_datasets has {len(scenario['corrupted_datasets'])} "
            f"entries but the scenario declares {n} partners.")


def get_scenario_params_list(config):
    """Flatten the YAML `scenario_params_list` into one dict per scenario.

    Every field in a block is a grid axis (its list of values is crossed
    with all the others via itertools.product), and the `dataset_name` dict
    sub-syntax fans out into per-dataset blocks first. Same expansion
    semantics as reference utils.py:41-91.
    """
    scenarios = []
    for block in config:
        for sub in _expand_dataset_dict(block):
            axes = list(sub.keys())
            for combo in product(*sub.values()):
                scenario = dict(zip(axes, combo))
                _check_per_partner_lengths(scenario)
                scenarios.append(scenario)
    logger.info(f"Number of scenario(s) configured: {len(scenarios)}")
    return scenarios


def init_result_folder(yaml_filepath, cfg, shard=None):
    """Create the experiment folder. Unsharded runs get the reference's
    timestamped-unique folder. Sharded runs (`--grid-shard I/N`) need the
    OPPOSITE: N concurrently-launched hosts must all land in the SAME
    folder (on a shared filesystem) so the per-shard results files end up
    side by side — so the folder name is deterministic
    (<name>_shardedN), created with exist_ok=True (no launch race), and
    the config copy is per-shard to avoid concurrent writes to one file."""
    logger.info("Init result folder")
    root = Path.cwd() / constants.EXPERIMENTS_FOLDER_NAME
    if shard is not None:
        shard_i, shard_n = shard
        experiment_path = root / f"{cfg['experiment_name']}_sharded{shard_n}"
        experiment_path.mkdir(parents=True, exist_ok=True)
        copyfile(yaml_filepath,
                 experiment_path / f"config_shard{shard_i}.yml")
    else:
        now_str = datetime.datetime.now().strftime("%Y-%m-%d_%Hh%M")
        experiment_path = root / (cfg["experiment_name"] + "_" + now_str)
        while experiment_path.exists():
            logger.warning(f"Experiment folder {experiment_path} already exists")
            experiment_path = Path(str(experiment_path) + "_bis")
        experiment_path.mkdir(parents=True, exist_ok=False)
        copyfile(yaml_filepath, experiment_path / Path(yaml_filepath).name)
    cfg["experiment_path"] = experiment_path
    logger.info(f"Experiment folder {experiment_path} created.")
    return cfg


def get_config_from_file(config_filepath, shard=None):
    config = load_cfg(config_filepath)
    config = init_result_folder(config_filepath, config, shard=shard)
    return config


def parse_command_line_arguments(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("-f", "--file", help="input config file")
    parser.add_argument("-v", "--verbose", help="verbose output",
                        action="store_true")
    parser.add_argument(
        "--grid-shard", metavar="I/N", default=None, type=parse_grid_shard,
        help="run only scenarios I::N of the expanded grid (0-based). The "
             "grid axis is embarrassingly parallel — this is the multi-HOST "
             "scale-out: launch N processes/hosts with I=0..N-1; they share "
             "one deterministic experiment folder (<name>_shardedN) and "
             "each writes its own results_shardI.csv; stitch with "
             "scripts/merge_shards.py when all finish. (The reference has "
             "no multi-host story; within one host, coalition/partner "
             "parallelism already uses every chip over ICI.)")
    return parser.parse_args(argv)


def parse_grid_shard(spec):
    """'I/N' -> (i, n) with 0 <= i < n. Argparse `type` callable: raising
    ArgumentTypeError makes a malformed spec a usage error BEFORE any
    filesystem side effect (folder creation happens later in main)."""
    try:
        i, n = (int(part) for part in spec.split("/"))
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--grid-shard must look like I/N, got {spec!r}")
    if not 0 <= i < n:
        raise argparse.ArgumentTypeError(
            f"--grid-shard needs 0 <= I < N, got {spec!r}")
    return i, n


class ConsoleLevelFilter(logging.Filter):
    """Runtime-switchable console verbosity. The reference's loguru
    `MyFilter` lets the console level change after the sink is installed
    (/root/reference/mplc/utils.py:165-193); stdlib handlers freeze their
    level at setLevel time, so the handler stays at DEBUG and this filter
    decides — flip it any time via `set_console_level`."""

    def __init__(self, level=logging.INFO):
        super().__init__()
        self.level = level

    def filter(self, record):
        return record.levelno >= self.level


_console_filter = ConsoleLevelFilter()


def set_console_level(level):
    """Change the console verbosity at runtime ('DEBUG'/'INFO'/... or a
    logging int constant)."""
    if isinstance(level, str):
        level = logging.getLevelName(level.upper())
        if not isinstance(level, int):  # getLevelName echoes unknown names
            raise ValueError(f"unknown log level {level!r}")
    _console_filter.level = level


def init_logger(debug=False):
    root = logging.getLogger("mplc_tpu")
    root.setLevel(logging.DEBUG)
    for h in list(root.handlers):
        root.removeHandler(h)
    console = logging.StreamHandler(sys.stdout)
    console.setLevel(logging.DEBUG)  # the filter decides, not the handler
    _console_filter.level = logging.DEBUG if debug else logging.INFO
    console.addFilter(_console_filter)
    console.setFormatter(logging.Formatter(
        "%(asctime)s | %(levelname)s | %(message)s"))
    root.addHandler(console)
    return root


class profile_trace:
    """jax.profiler trace hook (SURVEY.md §5: the reference only has
    timeit wall-clock pairs; this adds a real device trace).

    Use as a context manager around any training/contributivity region:
        with utils.profile_trace("/tmp/mplc_trace"):
            scenario.run()
    No-op unless a directory is given or MPLC_TPU_PROFILE_DIR is set, so it
    can be left in production code paths.
    """

    def __init__(self, trace_dir: str | None = None):
        import os
        self.trace_dir = trace_dir or os.environ.get("MPLC_TPU_PROFILE_DIR")

    def __enter__(self):
        if self.trace_dir:
            import jax
            jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, *exc):
        if self.trace_dir:
            import jax
            jax.profiler.stop_trace()
        return False


def set_log_file(path: Path):
    root = logging.getLogger("mplc_tpu")
    info_h = logging.FileHandler(Path(path) / constants.INFO_LOGGING_FILE_NAME)
    info_h.setLevel(logging.INFO)
    debug_h = logging.FileHandler(Path(path) / constants.DEBUG_LOGGING_FILE_NAME)
    debug_h.setLevel(logging.DEBUG)
    fmt = logging.Formatter("%(asctime)s | %(levelname)s | %(message)s")
    info_h.setFormatter(fmt)
    debug_h.setFormatter(fmt)
    root.addHandler(info_h)
    root.addHandler(debug_h)


_COMPILE_CACHE_CONFIGURED = {"dir": None}


def enable_compile_cache_from_env() -> str | None:
    """Point JAX's persistent compilation cache at
    `MPLC_TPU_COMPILE_CACHE_DIR` (constants.COMPILE_CACHE_DIR_ENV) when
    set — the first step of the ROADMAP "program bank" item: every
    compiled slot-pipeline/reconstruction program is persisted, so a
    repeated sweep or a service restart pays zero residual compile.

    Returns the configured directory, or None when the knob is unset or
    configuration failed (a bad path warns instead of killing the run —
    the sweep still works, it just recompiles). Idempotent: repeated
    calls with an unchanged env are free."""
    import os
    path = os.environ.get(constants.COMPILE_CACHE_DIR_ENV)
    if not path:
        return None
    if _COMPILE_CACHE_CONFIGURED["dir"] == path:
        return path
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache even the small/fast programs: the point is a byte-exact
        # program bank, and tiny eval executables recompile too
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # JAX latches a disabled cache at the process's FIRST compile;
            # a dir configured after any prior jit (e.g. an engine built
            # mid-session) is silently ignored unless the cache module is
            # reset. Best-effort: private API, absent versions just rely
            # on being configured early.
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
        except Exception:
            pass
        _COMPILE_CACHE_CONFIGURED["dir"] = path
        return path
    except Exception as e:
        import warnings
        warnings.warn(f"{constants.COMPILE_CACHE_DIR_ENV}={path!r} could "
                      f"not be configured ({e}); persistent compile cache "
                      "disabled", stacklevel=2)
        return None


def compile_cache_entries(path: str | None) -> int | None:
    """Number of persisted executables under a compile-cache dir (None
    when the dir is unset/missing) — the bench sidecar's cache-hit
    provenance: a run whose entry count didn't grow was served entirely
    from the bank."""
    import os
    if not path or not os.path.isdir(path):
        return None
    return sum(len(files) for _, _, files in os.walk(path))
