"""Config loading, experiment-grid expansion, logging, result folders.

Mirrors /root/reference/mplc/utils.py: YAML experiment files with the shape
{experiment_name, n_repeats, scenario_params_list}, where every list-valued
parameter is grid-expanded via itertools.product into one scenario per
combination (utils.py:41-91), including the dataset-name dict sub-syntax for
`init_model_from` (utils.py:62-71).

Logging uses stdlib `logging` (the reference uses loguru, which is not
available here) with the same split: console + per-experiment info.log /
debug.log files (utils.py:165-200).
"""

from __future__ import annotations

import argparse
import datetime
import logging
import sys
from itertools import product
from pathlib import Path
from shutil import copyfile

import yaml

from . import constants

logger = logging.getLogger("mplc_tpu")


def load_cfg(yaml_filepath):
    logger.info("Loading experiment yaml file")
    with open(yaml_filepath, "r") as stream:
        cfg = yaml.safe_load(stream)
    logger.info(str(cfg))
    return cfg


def get_scenario_params_list(config):
    """Cartesian-product grid expansion (reference utils.py:41-91)."""
    scenario_params_list = []
    config_dataset = []

    for list_scenario in config:
        if isinstance(list_scenario["dataset_name"], dict):
            for dataset_name in list_scenario["dataset_name"].keys():
                dataset_scenario = list_scenario.copy()
                dataset_scenario["dataset_name"] = [dataset_name]
                if list_scenario["dataset_name"][dataset_name] is None:
                    dataset_scenario["init_model_from"] = ["random_initialization"]
                else:
                    dataset_scenario["init_model_from"] = \
                        list_scenario["dataset_name"][dataset_name]
                config_dataset.append(dataset_scenario)
        else:
            config_dataset.append(list_scenario)

    for list_scenario in config_dataset:
        params_name = list_scenario.keys()
        params_list = list(list_scenario.values())
        for el in product(*params_list):
            scenario = dict(zip(params_name, el))
            if scenario["partners_count"] != len(scenario["amounts_per_partner"]):
                raise Exception(
                    "Length of amounts_per_partner does not match number of partners.")
            if scenario.get("samples_split_option") is not None and \
                    scenario["samples_split_option"][0] == "advanced" and \
                    scenario["partners_count"] != len(scenario["samples_split_option"][1]):
                raise Exception(
                    "Length of samples_split_option does not match number of partners.")
            if "corrupted_datasets" in params_name:
                if scenario["partners_count"] != len(scenario["corrupted_datasets"]):
                    raise Exception(
                        "Length of corrupted_datasets does not match number of partners.")
            scenario_params_list.append(scenario)

    logger.info(f"Number of scenario(s) configured: {len(scenario_params_list)}")
    return scenario_params_list


def init_result_folder(yaml_filepath, cfg):
    logger.info("Init result folder")
    now_str = datetime.datetime.now().strftime("%Y-%m-%d_%Hh%M")
    full_experiment_name = cfg["experiment_name"] + "_" + now_str
    experiment_path = Path.cwd() / constants.EXPERIMENTS_FOLDER_NAME / full_experiment_name
    while experiment_path.exists():
        logger.warning(f"Experiment folder {experiment_path} already exists")
        experiment_path = Path(str(experiment_path) + "_bis")
    experiment_path.mkdir(parents=True, exist_ok=False)
    cfg["experiment_path"] = experiment_path
    copyfile(yaml_filepath, experiment_path / Path(yaml_filepath).name)
    logger.info(f"Experiment folder {experiment_path} created.")
    return cfg


def get_config_from_file(config_filepath):
    config = load_cfg(config_filepath)
    config = init_result_folder(config_filepath, config)
    return config


def parse_command_line_arguments(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("-f", "--file", help="input config file")
    parser.add_argument("-v", "--verbose", help="verbose output",
                        action="store_true")
    return parser.parse_args(argv)


def init_logger(debug=False):
    root = logging.getLogger("mplc_tpu")
    root.setLevel(logging.DEBUG)
    for h in list(root.handlers):
        root.removeHandler(h)
    console = logging.StreamHandler(sys.stdout)
    console.setLevel(logging.DEBUG if debug else logging.INFO)
    console.setFormatter(logging.Formatter(
        "%(asctime)s | %(levelname)s | %(message)s"))
    root.addHandler(console)
    return root


class profile_trace:
    """jax.profiler trace hook (SURVEY.md §5: the reference only has
    timeit wall-clock pairs; this adds a real device trace).

    Use as a context manager around any training/contributivity region:
        with utils.profile_trace("/tmp/mplc_trace"):
            scenario.run()
    No-op unless a directory is given or MPLC_TPU_PROFILE_DIR is set, so it
    can be left in production code paths.
    """

    def __init__(self, trace_dir: str | None = None):
        import os
        self.trace_dir = trace_dir or os.environ.get("MPLC_TPU_PROFILE_DIR")

    def __enter__(self):
        if self.trace_dir:
            import jax
            jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, *exc):
        if self.trace_dir:
            import jax
            jax.profiler.stop_trace()
        return False


def set_log_file(path: Path):
    root = logging.getLogger("mplc_tpu")
    info_h = logging.FileHandler(Path(path) / constants.INFO_LOGGING_FILE_NAME)
    info_h.setLevel(logging.INFO)
    debug_h = logging.FileHandler(Path(path) / constants.DEBUG_LOGGING_FILE_NAME)
    debug_h.setLevel(logging.DEBUG)
    fmt = logging.Formatter("%(asctime)s | %(levelname)s | %(message)s")
    info_h.setFormatter(fmt)
    debug_h.setFormatter(fmt)
    root.addHandler(info_h)
    root.addHandler(debug_h)
