"""Adaptive query planner: route a contributivity query to an estimator.

Method choice among the registered estimators has always been manual —
the operator picks exact/GTG-Shapley/SVARM (and a DPVS pruning tau) per
query and discovers too late that an exact sweep blows a deadline or
that a sampled estimator was pointless on a 6-partner game. This module
makes `method="auto"` a first-class request: a `(game size,
accuracy_target, deadline_sec)` triple resolves — deterministically,
from written-down rules — to a concrete QueryPlan that is journaled
wherever it is used (`contrib.plan` / `service.job` events, the service
WAL), so a replay runs the SAME concrete method and kwargs, never a
re-plan under different meter state.

Cost model: the per-coalition eval-seconds estimate comes from measured
truth when any exists, in a ranked basis order mirroring
`obs/devcost.estimate_device_seconds`:

  meter           the engine DeviceMeter's eval-only span rate
                  (reconstruction batches billed at host span) — real
                  measured seconds per coalition on THIS engine
  bank_cost_model the ProgramBank manifest's XLA-costed flops for banked
                  reconstruction programs over the fleet's peak (a
                  conservative per-program upper bound on per-coalition
                  cost: the modeled program evaluates a whole batch)
  default         a fixed conservative constant, when nothing has run yet

Accuracy contract: `accuracy_target` is the trust-row CI half-width on
normalized scores the caller is asking for
(`MPLC_TPU_PLANNER_ACCURACY` default). The sampled estimators receive
it as their stopping threshold (GTG's `sv_accuracy`); exact queries
satisfy any target by construction (CI width 0); the planner grid test
asserts the delivered trust-row CI width meets the contracted target.

Routing table (tested in tests/test_planner.py; deterministic given the
inputs, every row carries its reason):

  1. exact        P <= MAX_EXACT_PARTNERS and the 2^P - 1 sweep fits the
                  deadline (no deadline = loose: any exact-capable game
                  routes exact).
  2. hierarchical live games past the exact wall (P > 16) whose grouped
                  sweep — the 2^k cluster powerset plus exact intra
                  splits (live/hierarchy.py) — fits the deadline: exact
                  macro Shapley over DPVS-score clusters, split within.
                  The cluster count/tau knobs are frozen into the plan's
                  method_kw so a journaled plan replays bit-identically.
  3. GTG-Shapley  the truncated-permutation budget (min_iter x P evals)
                  fits the deadline (or no deadline on a big game).
  4. SVARM        tighter deadlines: its explicit sample budget is
                  clamped to what the deadline affords (anchors +
                  stratum warm-up + at least the 128-sample floor).
  5. DPVS-pruned  deadlines below even SVARM's floor: GTG over the
                  pruned game (live tier; non-live falls back to
                  floor-budget SVARM, best-effort, reason says so).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import constants

#: per-coalition eval seconds when nothing measured/modeled is available
DEFAULT_EVAL_SEC = 0.05
#: assumed MXU utilization when deriving seconds from modeled flops
_COST_MODEL_MFU = 0.10
#: default DPVS tau for the pruned fallback rung (an explicit
#: MPLC_TPU_LIVE_PRUNE_TAU wins at query time, like any live query)
_PRUNE_TAU_FALLBACK = 0.5
#: SVARM's minimum useful sampled budget (mirrors its 128-sample floor)
_SVARM_FLOOR = 128
#: GTG's default permutation budget per partner (min_iter default)
_GTG_MIN_ITER = 100

MAX_EXACT_PARTNERS = 16


@dataclasses.dataclass(frozen=True)
class QueryPlan:
    """One resolved plan: everything a replay needs to run the same
    concrete query, plus the cost/accuracy evidence behind the choice."""
    method: str                    # "exact"/"hierarchical"/"GTG-Shapley"/"SVARM"
    partners: int
    accuracy_target: float         # contracted trust-row CI half-width
    deadline_sec: "float | None"   # None = loose
    est_evals: int                 # estimated coalition evaluations
    est_eval_sec: float            # per-coalition eval-seconds estimate
    est_cost_sec: float            # est_evals * est_eval_sec
    cost_basis: str                # "meter" | "bank_cost_model" | "default"
    prune_tau: float               # 0 = unpruned
    reason: str
    method_kw: dict = dataclasses.field(default_factory=dict)

    def describe(self) -> dict:
        d = dataclasses.asdict(self)
        d["method_kw"] = dict(self.method_kw)
        return d


def plan_from_dict(doc: dict) -> QueryPlan:
    """Rebuild a journaled plan (service WAL replay / report tooling)."""
    fields = {f.name for f in dataclasses.fields(QueryPlan)}
    return QueryPlan(**{k: v for k, v in doc.items() if k in fields})


def estimate_eval_seconds(engine=None) -> tuple:
    """(seconds-per-coalition-eval, basis), best available truth first."""
    meter = getattr(engine, "device_meter", None) if engine else None
    if meter is not None:
        snap = meter.snapshot()
        if snap.get("eval_coalitions", 0) >= 8 and \
                snap.get("eval_span_sec", 0.0) > 0.0:
            return (snap["eval_span_sec"] / snap["eval_coalitions"],
                    "meter")
    bank = getattr(engine, "program_bank", None) if engine else None
    if bank is not None:
        try:
            from ..obs.devcost import fleet_peak_flops
            peak = fleet_peak_flops()
            costs = [c.get("flops", 0.0)
                     for c in bank.persistent_costs().values()
                     if c.get("flops")]
            if peak and costs:
                # a banked program's modeled flops cover a whole batch:
                # per-coalition cost is bounded above by it, so this
                # basis over-estimates (deadline-safe direction)
                return (float(np.median(costs)) / (peak * _COST_MODEL_MFU),
                        "bank_cost_model")
        except Exception:
            pass
    return (DEFAULT_EVAL_SEC, "default")


def _estimated_evals(partners: int) -> dict:
    """Estimated coalition-eval budgets per estimator family."""
    n = int(partners)
    warmup = max(n * n - 2 * n, 0)  # SVARM per-(partner, size) strata
    return {
        "exact": (1 << n) - 1,
        "GTG-Shapley": _GTG_MIN_ITER * n,
        # anchors (2n) + stratum warm-up + the sampled floor
        "SVARM_floor": 2 * n + warmup + _SVARM_FLOOR,
        "SVARM_auto": 2 * n + warmup + max(4 * n * n, _SVARM_FLOOR),
    }


def default_accuracy_target() -> float:
    t = constants._env_nonneg_float(constants.PLANNER_ACCURACY_ENV, 0.0)
    return t if t > 0 else 0.02


def default_deadline_sec() -> "float | None":
    d = constants._env_nonneg_float(constants.PLANNER_DEADLINE_ENV, 0.0)
    return d if d > 0 else None


def plan_query(partners_count: int,
               accuracy_target: "float | None" = None,
               deadline_sec: "float | None" = None, *,
               eval_sec: "float | None" = None,
               cost_basis: str = "default",
               live: bool = False) -> QueryPlan:
    """Resolve `method="auto"` to a concrete QueryPlan (routing table in
    the module docstring). Pure given its inputs — callers pass the
    measured `eval_sec` (from `estimate_eval_seconds`) so the decision
    is reproducible from the journaled plan alone."""
    n = int(partners_count)
    if n < 1:
        raise ValueError(f"partners_count must be >= 1, got {n}")
    if accuracy_target is None:
        accuracy_target = default_accuracy_target()
    if deadline_sec is None:
        deadline_sec = default_deadline_sec()
    if eval_sec is None:
        eval_sec, cost_basis = DEFAULT_EVAL_SEC, "default"
    evals = _estimated_evals(n)

    def _plan(method, est_evals, prune_tau, reason, **method_kw):
        return QueryPlan(
            method=method, partners=n,
            accuracy_target=float(accuracy_target),
            deadline_sec=None if deadline_sec is None else float(deadline_sec),
            est_evals=int(est_evals), est_eval_sec=float(eval_sec),
            est_cost_sec=float(est_evals) * float(eval_sec),
            cost_basis=cost_basis, prune_tau=float(prune_tau),
            reason=reason, method_kw=method_kw)

    def _fits(est_evals):
        return deadline_sec is None or est_evals * eval_sec <= deadline_sec

    # 1. exact: zero sampling error, so it satisfies ANY accuracy target
    if n <= MAX_EXACT_PARTNERS and _fits(evals["exact"]):
        return _plan(
            "exact", evals["exact"], 0.0,
            f"2^{n}-1 exact sweep fits "
            + ("a loose deadline" if deadline_sec is None
               else f"the {deadline_sec:g}s deadline")
            + "; exact Shapley meets any accuracy target (CI width 0)")
    # 2. hierarchical (live only): past the exact wall, exact Shapley
    # over <= 16 DPVS-score clusters + exact intra splits. The knobs are
    # resolved HERE and frozen into method_kw — a journaled plan fully
    # determines the query (same rule as the pruned rung's tau)
    if live and n > MAX_EXACT_PARTNERS:
        from ..live import hierarchy as _hier
        k = _hier.resolve_clusters(n)
        ctau = _hier.resolve_cluster_tau()
        hier_evals = _hier.estimate_evaluations(n, k)
        if _fits(hier_evals):
            return _plan(
                "hierarchical", hier_evals, 0.0,
                f"game too large for the exact table (P={n} > "
                f"{MAX_EXACT_PARTNERS}) but the grouped sweep over {k} "
                "clusters fits; exact macro Shapley + exact intra splits",
                clusters=int(k), cluster_tau=float(ctau))
    # 3. GTG-Shapley: permutation sampling to the accuracy target
    if _fits(evals["GTG-Shapley"]):
        reason = (f"game too large for the exact table (P={n} > "
                  f"{MAX_EXACT_PARTNERS})" if n > MAX_EXACT_PARTNERS
                  else "exact sweep would blow the deadline")
        return _plan(
            "GTG-Shapley", evals["GTG-Shapley"], 0.0,
            reason + "; truncated-permutation budget fits",
            sv_accuracy=float(accuracy_target))
    # 4. SVARM: explicit budget clamped to the deadline
    if _fits(evals["SVARM_floor"]):
        affordable = int(deadline_sec / eval_sec) if deadline_sec else 0
        overhead = evals["SVARM_floor"] - _SVARM_FLOOR
        budget = min(max(affordable - overhead, _SVARM_FLOOR),
                     max(4 * n * n, _SVARM_FLOOR))
        return _plan(
            "SVARM", overhead + budget, 0.0,
            "deadline below the GTG permutation budget; SVARM's sample "
            f"budget clamps to {budget} coalitions",
            budget=int(budget))
    # 5. pruned (live) / floor-budget SVARM (best-effort, non-live)
    if live:
        tau = constants._env_nonneg_float(
            constants.LIVE_PRUNE_TAU_ENV, 0.0) or _PRUNE_TAU_FALLBACK
        tau = min(tau, 1.0)
        return _plan(
            "GTG-Shapley", evals["GTG-Shapley"] // 2, tau,
            "deadline below every unpruned estimator's floor; DPVS "
            f"pruning at tau={tau:g} collapses low-information partners",
            sv_accuracy=float(accuracy_target))
    return _plan(
        "SVARM", evals["SVARM_floor"], 0.0,
        "deadline below every estimator's floor — best-effort SVARM at "
        "the minimum sample budget (expect the deadline to be missed)",
        budget=_SVARM_FLOOR)
