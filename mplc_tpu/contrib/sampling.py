"""Subset samplers for the importance-sampling Shapley estimators.

The reference draws each importance sample by walking the full power set of
N\\{k} with a Python loop — O(2^(n-1)) *per draw, per partner, per
iteration* (/root/reference/mplc/contributivity.py:326-439, the
`characteristic_no_nul_proba` / inverse-CDF walk). Here the same
distributions are produced from precomputed, vectorized tables:

  * `ExactSubsetSampler` — enumerates the subsets of N\\{k} once per refit
    (the reference's size-ascending, lexicographic order), evaluates the
    |approximate increment| for the whole table in ONE vectorized call, and
    turns each draw into a binary search over the cumulative distribution.
    Identical draw distribution and identical importance weights to the
    reference's walk, at O(2^m) vectorized work per *refit* instead of
    O(2^m) interpreted work per *draw*.

  * `SizeStratifiedSubsetSampler` — for partner counts where enumeration is
    infeasible (m = n-1 > max_exact_bits), an exact-weight two-stage
    proposal: draw the coalition size l from p_l ∝ P_shapley(l)·C(m,l)·g(l)
    (g = probed mean |increment| per size, mixed with a uniform floor so
    every size keeps positive mass), then a uniform size-l subset. Because
    P_shapley(l)·C(m,l) = 1/n exactly, the importance weight
    P(S)/q(S) = 1/(n·p_l) is closed-form and the estimator stays unbiased
    for ANY probe quality — g only shapes variance, never bias.

Both expose `draw(u, rng) -> (subset ndarray, weight)` where `weight` is the
multiplier for the observed increment in the Shapley estimator (the
reference's `renorm / |approx_increment(S)|`).

Also here: lexicographic combination unranking (used to turn the stratified
MC methods' uniform-subset draws from enumeration walks into O(l·m)
arithmetic) and a sparse without-replacement rank pool (so WR_SMC no longer
materializes all C(m,l) subsets up front —
/root/reference/mplc/contributivity.py:823-938 builds the full list per
stratum).
"""

from __future__ import annotations

from functools import lru_cache
from itertools import combinations
from math import comb, factorial

import numpy as np

# Above this many non-k partners the IS samplers switch from exact power-set
# tables (2^m rows) to the two-stage size-stratified proposal.
MAX_EXACT_BITS = 16


def shapley_size_prob(size: int, n: int) -> float:
    """P_shapley(S) for one |S|=size subset of N\\{k}: |S|!(n-1-|S|)!/n!."""
    return factorial(n - 1 - size) * factorial(size) / factorial(n)


@lru_cache(maxsize=4)
def combination_mask_table(m: int) -> tuple[np.ndarray, np.ndarray]:
    """All subsets of range(m) as a [2^m, m] bool matrix, in the reference's
    enumeration order (size-ascending, lexicographic within a size).
    Returns (masks, sizes-per-row). Cached: every per-partner sampler (and
    every AIS refit) shares one table — callers must treat it as
    read-only."""
    blocks = []
    sizes = []
    for length in range(m + 1):
        if length == 0:
            blocks.append(np.zeros((1, m), bool))
            sizes.append(np.zeros(1, int))
            continue
        idx = np.array(list(combinations(range(m), length)), int)
        rows = np.zeros((len(idx), m), bool)
        rows[np.arange(len(idx))[:, None], idx] = True
        blocks.append(rows)
        sizes.append(np.full(len(idx), length, int))
    return np.concatenate(blocks), np.concatenate(sizes)


def unrank_combination(m: int, length: int, rank: int) -> list[int]:
    """rank-th (0-based) size-`length` combination of range(m) in
    lexicographic order, without enumerating its predecessors."""
    out = []
    x = 0
    for i in range(length):
        while True:
            c = comb(m - x - 1, length - i - 1)
            if rank < c:
                out.append(x)
                x += 1
                break
            rank -= c
            x += 1
    return out


def randbelow(rng: np.random.Generator, n: int) -> int:
    """Uniform integer in [0, n) for arbitrarily large Python ints (numpy's
    integers() caps at int64; WR_SMC stratum cardinalities can exceed it)."""
    if n <= 0:
        raise ValueError("randbelow needs n >= 1")
    bits = n.bit_length()
    nbytes = (bits + 7) // 8
    while True:
        r = int.from_bytes(rng.bytes(nbytes), "little") >> (nbytes * 8 - bits)
        if r < n:
            return r


class WithoutReplacementRanks:
    """Sparse Fisher-Yates over ranks [0, total): pop a uniformly random
    not-yet-seen rank in O(1) time and O(draws) memory."""

    def __init__(self, total: int):
        self.total = total
        self._moved: dict[int, int] = {}

    def __len__(self):
        return self.total

    def pop_random(self, rng: np.random.Generator) -> int:
        if self.total <= 0:
            raise IndexError("pool exhausted")
        j = randbelow(rng, self.total)
        val = self._moved.get(j, j)
        last = self.total - 1
        self._moved[j] = self._moved.pop(last, last)
        if j == last:
            self._moved.pop(j, None)
        self.total = last
        return val


class ExactSubsetSampler:
    """Inverse-CDF sampler over all subsets of N\\{k}, weighted by
    P_shapley(|S|)·|approx_increment(S, k)| — the reference's IS proposal,
    tabulated once. `batch_fn(masks) -> [B] increments` is evaluated
    vectorized over the whole table at construction."""

    def __init__(self, n: int, k: int, batch_fn):
        self.n = n
        self.k = k
        self.members = np.delete(np.arange(n), k)
        m = n - 1
        self.masks, sizes = combination_mask_table(m)
        probs = np.array([shapley_size_prob(int(s), n) for s in range(m + 1)])
        self.f = np.abs(np.asarray(batch_fn(self.masks), float))
        w = probs[sizes] * self.f
        self.renorm = float(w.sum())
        if self.renorm <= 0:
            # degenerate model (all-zero increments): fall back to the
            # plain Shapley size distribution, weights handled below
            w = probs[sizes]
            self.renorm = float(w.sum())
            self.f = np.ones_like(self.f)
        self._cdf = np.cumsum(w) / self.renorm

    def draw(self, u: float, rng=None):
        idx = int(np.searchsorted(self._cdf, u, side="right"))
        idx = min(idx, len(self._cdf) - 1)
        subset = self.members[self.masks[idx]]
        weight = self.renorm / max(self.f[idx], 1e-300)
        return subset, weight


class SizeStratifiedSubsetSampler:
    """Two-stage exact-weight proposal for large n (see module docstring)."""

    def __init__(self, n: int, k: int, batch_fn, rng: np.random.Generator,
                 probes_per_size: int = 8, uniform_mix: float = 0.05):
        self.n = n
        self.k = k
        self.members = np.delete(np.arange(n), k)
        m = n - 1
        g = np.zeros(m + 1)
        for length in range(m + 1):
            rows = np.zeros((probes_per_size, m), bool)
            for r in range(probes_per_size):
                if length:
                    rows[r, rng.choice(m, length, replace=False)] = True
            g[length] = float(np.mean(np.abs(np.asarray(
                batch_fn(rows), float))))
        total = g.sum()
        if total <= 0:
            g = np.ones(m + 1)
            total = g.sum()
        p = (1 - uniform_mix) * g / total + uniform_mix / (m + 1)
        self._p = p
        self._cdf = np.cumsum(p)
        # P_shapley(l)·C(m,l) = l!(n-1-l)!/n! · (n-1)!/(l!(n-1-l)!) = 1/n
        self._weight_per_size = 1.0 / (n * p)

    def draw(self, u: float, rng: np.random.Generator):
        length = int(np.searchsorted(self._cdf, u, side="right"))
        length = min(length, len(self._cdf) - 1)
        if length:
            subset = np.sort(rng.choice(self.members, length, replace=False))
        else:
            subset = np.array([], int)
        return subset, float(self._weight_per_size[length])


# ---------------------------------------------------------------------------
# SVARM stratified sampling ("Approximating the Shapley Value without
# Marginal Contributions", arXiv:2302.00736). The Shapley value splits as
#
#   phi_i = (1/n) * sum_{s=0}^{n-1} (phi+_{i,s} - phi-_{i,s}),
#   phi+_{i,s} = E[v(S u {i})],  phi-_{i,s} = E[v(S)]   over uniform
#                size-s subsets S of N \ {i}
#
# so ONE sampled coalition A updates phi+ estimates for every i in A
# (stratum |A|-1) and phi- estimates for every i not in A (stratum |A|) —
# no paired (S, S u {i}) marginal evaluations at all, which is what lets a
# whole sample block pack into one eval batch. Uniformity is inherited:
# A uniform among size-s sets, conditioned on i in A, has A \ {i} uniform
# among size-(s-1) subsets of N \ {i}.
# ---------------------------------------------------------------------------

def svarm_warmup_draws(n: int, rng: np.random.Generator
                       ) -> list[tuple[str, int, int, tuple]]:
    """One guaranteed sample per non-exact stratum: for every partner i
    and size s in 1..n-2, one uniform S subset of N\\{i} for the minus
    estimator and its i-joined set for the plus estimator. (Strata s=0 and
    s=n-1 are exact anchors — v({i}), v(empty), v(N), v(N\\{i}) — and need
    no samples.) Returns (sign, i, s, coalition) entries; each warm-up
    coalition updates ONLY its designated stratum, keeping every stratum
    mean a mean of uniform draws."""
    draws = []
    for i in range(n):
        others = np.delete(np.arange(n), i)
        for s in range(1, n - 1):
            sp = rng.choice(others, s, replace=False)
            draws.append(("plus", i, s,
                          tuple(sorted([int(x) for x in sp] + [i]))))
            sm = rng.choice(others, s, replace=False)
            draws.append(("minus", i, s,
                          tuple(sorted(int(x) for x in sm))))
    return draws


def svarm_batch_draws(n: int, block: int, rng: np.random.Generator
                      ) -> list[tuple[tuple, tuple]]:
    """`block` main-loop iterations of (A_plus, A_minus) coalition pairs:
    A_plus uniform among sets of a uniform size 2..n-1 (updates plus
    strata for its members), A_minus uniform among sets of a uniform
    size 1..n-2 (updates minus strata for its non-members). Sizes that
    would only touch the exact anchor strata (|A+| in {1, n}, |A-| in
    {0, n-1}) are excluded — their updates are skipped anyway, so
    sampling them would burn budget on no-op evaluations; conditional
    uniformity within each remaining stratum is unchanged. n < 3 has no
    non-exact stratum at all: returns [] (the caller's sampling loop
    must not spin on an empty block)."""
    if n < 3:
        return []
    out = []
    for _ in range(block):
        sp = int(rng.integers(2, n))
        ap = tuple(sorted(int(x) for x in
                          rng.choice(n, sp, replace=False)))
        sm = int(rng.integers(1, n - 1))
        am = tuple(sorted(int(x) for x in
                          rng.choice(n, sm, replace=False)))
        out.append((ap, am))
    return out


def make_importance_sampler(n: int, k: int, batch_fn,
                            rng: np.random.Generator,
                            max_exact_bits: int = MAX_EXACT_BITS):
    if n - 1 <= max_exact_bits:
        return ExactSubsetSampler(n, k, batch_fn)
    return SizeStratifiedSubsetSampler(n, k, batch_fn, rng)
