"""The program bank: AOT-compiled slot programs with compile/execute overlap.

Before this module, every slot program compiled lazily inside its first
dispatch — the sweep's warm-up was a SERIAL phase (the bench pays ~15 min
of slot-pipeline compiles before timing), and a cap change mid-sweep
stalled the device behind a foreground compile. The bank restructures
compilation three ways:

  1. **AOT**: every (slots, width) program the sweep will run is lowered
     and compiled ahead of its first dispatch via the jit AOT path
     (`jit.lower(...).compile()`), keyed by the engine's cache fingerprint
     x (slot_count, width, donation signature, epoch count, device count,
     backend) — the full identity of the executable. The compiled
     executables are held in a PROCESS-GLOBAL store, so a second engine on
     the same game (the bench's timed engine after its warm engine, a
     resumed sweep, a second tenant of the same scenario shape) executes
     straight from the bank with zero compiles.
  2. **Overlap**: `prefetch(plan)` hands the sweep's whole bucket schedule
     to a background thread that compiles bucket k+1's programs while
     bucket k executes on the device. Only the FIRST bucket's compile
     remains serial (`acquire` compiles it in the caller's thread); the
     rest land as `bank.compile` events with `overlapped=True`, which the
     sweep report separates from the serial compile row.
  3. **Persistence**: compiles run under JAX's persistent compilation
     cache (MPLC_TPU_COMPILE_CACHE_DIR, utils.enable_compile_cache_from_env),
     so the executables serialize to disk as a side effect — and the bank
     additionally writes a MANIFEST of compiled program keys next to the
     cache entries, turning the cache dir into a queryable program bank:
     `holds_persistent(plan)` proves a fresh process already has every
     program a sweep needs (bench.py skips its compile-prime warm-up loop
     on that proof and records `warmup_skipped` provenance).

Execution contract: a banked bundle is the SAME jit, lowered with the same
donation signature and the same input shardings the engine dispatches with
— bit-identity between banked and freshly-jit-compiled sweeps is an
invariant (equality-tested in tests/test_program_bank.py, including under
injected transient/OOM faults). A bundle is only served for the exact
width it was lowered at; the OOM ladder's re-bucketed widths fall back to
the ordinary jit path (and may bank their own width on a later call).
MPLC_TPU_PROGRAM_BANK=0 disables the bank entirely.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time

import jax

from .. import constants
from ..obs import devcost
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

logger = logging.getLogger("mplc_tpu")

MANIFEST_NAME = "mplc_program_bank.json"

# Process-global store: key -> bundle dict ({"init","run","fin"} Compiled)
# or the Exception that killed its compile (acquire then falls back to the
# jit path instead of retrying a known-bad lowering every bucket).
# FIFO-bounded: a long-lived multi-tenant process banks a bundle per
# (game x shape x width), and loaded executables hold device program
# memory — evicting the oldest beyond the bound only costs a recompile
# (served from the persistent cache when configured), never correctness.
_PROGRAMS: dict = {}
_MAX_PROGRAMS = 256
# key -> threading.Event for compiles in flight (foreground or background);
# exactly one thread owns a key's compile, everyone else waits on the event.
_INFLIGHT: dict = {}
_LOCK = threading.Lock()
_MANIFEST_LOCK = threading.Lock()


def bank_enabled() -> bool:
    return os.environ.get(constants.PROGRAM_BANK_ENV, "1") != "0"


def reset_bank() -> None:
    """Drop every banked executable (tests; never needed in production —
    the store is keyed by the full program identity)."""
    with _LOCK:
        _PROGRAMS.clear()
        for ev in _INFLIGHT.values():
            ev.set()
        _INFLIGHT.clear()


def bank_stats() -> dict:
    """The process-global bank's state for the /varz endpoint
    (obs/export.py): how many bundles it holds, how many compiles are in
    flight, and the (stringified) program keys — enough for an operator
    to see whether a tenant's shape is served from the bank without
    attaching a debugger."""
    with _LOCK:
        keys = [str(k) for k in _PROGRAMS]
        failed = sum(1 for v in _PROGRAMS.values()
                     if not isinstance(v, dict))
        costed = sum(1 for v in _PROGRAMS.values()
                     if isinstance(v, dict) and v.get("cost"))
        return {
            "enabled": bank_enabled(),
            "programs": len(keys),
            "failed_compiles": failed,
            # bundles whose compile exposed XLA cost analysis (the
            # roofline/metering input; see obs/devcost.py)
            "costed_programs": costed,
            "inflight": len(_INFLIGHT),
            "max_programs": _MAX_PROGRAMS,
            "manifest_dir": manifest_dir(),
            # capped: /varz is a snapshot, not a dump
            "keys": keys[:50],
        }


def manifest_dir() -> "str | None":
    """Where the persistent manifest lives: the configured compile-cache
    dir (env knob first, then whatever the process pointed jax's
    persistent cache at). None = no persistence, the bank is
    process-local only."""
    path = os.environ.get(constants.COMPILE_CACHE_DIR_ENV)
    if path:
        return path
    try:
        path = jax.config.jax_compilation_cache_dir
        return path or None
    except Exception:
        return None


class ProgramBank:
    """Per-engine view onto the process-global AOT program store.

    `shared=True` (the sweep service's mode) keys programs by SHAPE
    identity instead of game identity: an XLA executable is a function of
    argument shapes/dtypes and the compiled TrainConfig, never of data
    VALUES, seeds or the data digest — so two tenants whose games share a
    model, partner count and data shapes are served the SAME banked
    executables (cross-tenant batch packing: the second tenant compiles
    nothing the first already banked). Results stay bit-identical to a
    private-bank run because every value-level input (data, rng keys,
    masks) is a runtime argument. The default (per-game) scope is kept
    for solo engines: it can never over-share, and its keys subsume the
    shape key."""

    def __init__(self, engine, shared: bool = False):
        self.engine = engine
        self.shared = shared
        self._digest_cache = None

    # -- program identity ------------------------------------------------

    def _shape_signature(self) -> list:
        """Everything the compiled executables depend on OUTSIDE the
        per-program key fields (repr(cfg), partners_count, slot/width,
        donation, topology): the model identity and the shapes/dtypes of
        the data arguments the programs are lowered against."""
        eng = self.engine

        def sig(tree):
            return [[list(l.shape), str(l.dtype)]
                    for l in jax.tree_util.tree_leaves(tree)]

        return [eng.model.name, sig(eng.stacked), sig(eng.val),
                sig(eng.test)]

    def _engine_digest(self) -> str:
        if self._digest_cache is None:
            if self.shared:
                fp = json.dumps(self._shape_signature(), default=str)
            else:
                fp = json.dumps(self.engine._fingerprint(), sort_keys=True,
                                default=str)
            self._digest_cache = hashlib.sha256(fp.encode()).hexdigest()[:16]
        return self._digest_cache

    @staticmethod
    def _pipe_donates(pipe) -> bool:
        """The donation signature of the executables this pipe would lower
        — the policy BOUND into its jits at construction, not the live env
        (an env flip between engines must not let a donating executable be
        served under a non-donating key, or vice versa: the caller's
        nb_epochs_done copy depends on it)."""
        return bool(getattr(pipe, "_fin_donates", False))

    def program_key(self, pipe, slot_count, width) -> str:
        """The executable's full identity: the engine fingerprint (game +
        data + trainer config as far as v(S) sees it) x the per-program
        shape (TrainConfig repr covers slot_count/approach/record flags,
        plus the batch width) x the donation signature x the topology.
        Two programs with equal keys are interchangeable executables."""
        eng = self.engine
        cfg = pipe.trainer.cfg
        n_dev = eng._sharding.num_devices if eng._sharding else 1
        raw = json.dumps([
            self._engine_digest(), repr(cfg), pipe.partners_count,
            slot_count, int(width), self._pipe_donates(pipe),
            n_dev, jax.default_backend()])
        return hashlib.sha256(raw.encode()).hexdigest()[:24]

    # -- lowering --------------------------------------------------------

    def _arg_sds(self, pipe, slot_count, width):
        """ShapeDtypeStructs for the per-batch arguments, carrying the
        sharding the engine dispatches with (device_put onto the coal
        mesh), so the compiled executable accepts the real batches."""
        import jax.numpy as jnp
        eng = self.engine
        sh = eng._sharding.batch_sharding if eng._sharding else None

        def sds(shape, dtype):
            if sh is not None:
                return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)
            return jax.ShapeDtypeStruct(shape, dtype)

        rngs = sds((width, 2), jnp.uint32)
        if slot_count is not None:
            masks = sds((width, slot_count), jnp.int32)
        else:
            masks = sds((width, eng.partners_count), jnp.float32)
        return masks, rngs

    def _compile_bundle(self, pipe, slot_count, width) -> dict:
        """AOT-lower + compile the pipeline's init -> epoch-chunk ->
        finalize for one (slots, width) program. State shardings chain
        through `Compiled.output_shardings`, so the three executables
        compose exactly like the jit path's dispatch."""
        eng = self.engine
        cfg = pipe.trainer.cfg
        masks_sds, rngs_sds = self._arg_sds(pipe, slot_count, width)

        def state_sds_like(shapes, shardings):
            return jax.tree_util.tree_map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                                   sharding=sh),
                shapes, shardings)

        init_c = pipe._init.lower(rngs_sds, pipe.partners_count).compile()
        # partners_count must stay a static python int under eval_shape
        # (init_state builds shapes from it), so close over it instead of
        # passing it as a traced argument
        state_shapes = jax.eval_shape(
            jax.vmap(lambda r: pipe.trainer.init_state(
                r, pipe.partners_count)),
            rngs_sds)
        run_c = pipe._run.lower(
            state_sds_like(state_shapes, init_c.output_shardings),
            eng.stacked, eng.val, masks_sds, rngs_sds,
            cfg.epoch_count).compile()
        fin_c = pipe._fin.lower(
            state_sds_like(state_shapes, run_c.output_shardings),
            eng.test).compile()
        return {"init": init_c, "run": run_c, "fin": fin_c}

    def _do_compile(self, key, pipe, slot_count, width,
                    overlapped: bool) -> None:
        """Compile under an exclusive in-flight claim and publish the
        result (bundle or the failure) to the global store."""
        t0 = time.perf_counter()
        entry = None
        cost = None
        ok = False
        try:
            try:
                entry = self._compile_bundle(pipe, slot_count, width)
                ok = True
                # XLA cost truth: harvest the compiled executables' cost
                # analysis (flops / bytes accessed / transcendentals) at
                # compile time — the engine stamps it onto every batch
                # the bundle runs, and the report derives the roofline
                # row from it. None (no cost analysis on this backend /
                # executable) degrades to the analytic proxy downstream.
                # Harvested AFTER ok=True and under its own guard: an
                # observability failure (an exotic cost-analysis schema)
                # must never discard a successfully compiled bundle as a
                # "failed compile".
                try:
                    cost = devcost.bundle_cost(entry)
                except Exception as ce:
                    cost = None
                    logger.warning(
                        "program-bank cost analysis failed for "
                        "(slots=%s, width=%s) — bundle banked without "
                        "cost truth: %s", slot_count, width, ce)
                if cost is not None:
                    entry["cost"] = cost
            except Exception as e:  # a bad lowering must not kill the sweep
                logger.warning(
                    "program-bank compile failed for (slots=%s, width=%s) — "
                    "falling back to inline jit compilation: %s",
                    slot_count, width, e)
                entry = e
        finally:
            # publish UNCONDITIONALLY — a waiter blocked on the in-flight
            # event must never hang because the compiling thread died
            self._publish(key, entry)
        dur = time.perf_counter() - t0
        if ok:
            obs_metrics.counter("bank.compiles").inc()
            obs_metrics.counter("bank.compile_seconds").inc(dur)
            if overlapped:
                obs_metrics.counter("bank.compiles_overlapped").inc()
            extra = ({"flops": cost["flops"],
                      "bytes_accessed": cost["bytes_accessed"]}
                     if cost else {})
            obs_trace.event(
                "bank.compile", dur=dur, slot_count=slot_count,
                width=int(width), overlapped=overlapped,
                donation=self._pipe_donates(pipe), programs=3, **extra)
            self._record_manifest(key, cost)

    def _claim(self, key):
        """(entry, event, owner): the published entry if any, else the
        in-flight event to wait on, else ownership of the compile."""
        with _LOCK:
            entry = _PROGRAMS.get(key)
            if entry is not None:
                return entry, None, False
            ev = _INFLIGHT.get(key)
            if ev is not None:
                return None, ev, False
            _INFLIGHT[key] = threading.Event()
            return None, None, True

    @staticmethod
    def _publish(key, entry) -> None:
        """Publish a compile result — a bundle dict or the failure
        tombstone — to the global store and release the in-flight claim:
        the ONE place the tombstone/FIFO-evict/event-release protocol
        lives (training bundles and recon programs both go through it).
        `entry=None` (the compiling thread died before producing either)
        publishes an explicit tombstone so waiters never hang."""
        with _LOCK:
            _PROGRAMS[key] = (entry if entry is not None
                              else RuntimeError("bank compile aborted"))
            ev = _INFLIGHT.pop(key, None)
            # FIFO bound on the global store (oldest first; dicts are
            # insertion-ordered). In-flight users keep their bundle
            # alive through their own reference.
            while len(_PROGRAMS) > _MAX_PROGRAMS:
                _PROGRAMS.pop(next(iter(_PROGRAMS)))
        if ev is not None:
            ev.set()

    # -- the two engine-facing operations --------------------------------

    def _acquire_entry(self, key, compile_owner, slot_count, width):
        """The claim/wait/hit protocol shared by `acquire` and
        `acquire_recon`: exactly one thread owns a key's compile
        (`compile_owner()` runs it in the caller's thread); everyone
        else waits on the in-flight event. The wait is SERIAL
        wall-clock: on a cold bank where execution outruns the
        background compiler the stall can span several programs'
        compiles — it is emitted as a `bank.wait` span so the sweep
        report books it as serial compile stall instead of letting the
        worker's overlapped=True events claim the time never blocked
        anyone. The timeout is a belt-and-braces bound (owners publish
        in a finally); on expiry the caller just takes the inline jit
        path. A bundle served with no compile and no wait counts as a
        bank hit (failed-compile tombstones are NOT hits — the sweep is
        actually compiling inline for that program)."""
        entry, ev, owner = self._claim(key)
        if owner:
            compile_owner()
        elif ev is not None:
            with obs_trace.span("bank.wait", slot_count=slot_count,
                                width=int(width)):
                ev.wait(timeout=600)
        entry = _PROGRAMS.get(key)
        if not owner and ev is None and isinstance(entry, dict):
            obs_metrics.counter("bank.hits").inc()
        return entry if isinstance(entry, dict) else None

    def acquire(self, pipe, slot_count, width):
        """The executable bundle for one bucket, compiling in the CALLER's
        thread when the background prefetch hasn't produced it (the first
        bucket's compile stays serial by design). Returns None — jit path
        — when the bank is disabled, the pipe needs mid-run host decisions
        (early-stopping chunk loop), or the program's compile failed."""
        if not bank_enabled() or not pipe.dispatches_async:
            return None
        key = self.program_key(pipe, slot_count, width)
        return self._acquire_entry(
            key,
            lambda: self._do_compile(key, pipe, slot_count, width,
                                     overlapped=False),
            slot_count, width)

    def prefetch(self, plan) -> None:
        """Background-compile every bucket AFTER the first: while bucket k
        executes, bucket k+1's programs compile on this worker, so the
        sweep's compile phase collapses to the first bucket only. `plan`
        is [(pipe, slot_count, width)] in dispatch order (the engine's
        evaluate() bucket schedule)."""
        if not bank_enabled():
            return
        work = []
        for pipe, slot_count, width in plan[1:]:
            if not pipe.dispatches_async:
                continue
            key = self.program_key(pipe, slot_count, width)
            with _LOCK:
                if key in _PROGRAMS or key in _INFLIGHT:
                    continue
                _INFLIGHT[key] = threading.Event()
            work.append((key, pipe, slot_count, width))
        if not work:
            return

        def worker():
            for key, pipe, slot_count, width in work:
                self._do_compile(key, pipe, slot_count, width,
                                 overlapped=True)

        threading.Thread(target=worker, daemon=True,
                         name="mplc-program-bank").start()

    # -- reconstruction eval programs (the live tier's warm path) --------

    def recon_key(self, evaluator, width: int) -> str:
        """Identity of one fused reconstruct+eval executable: the engine
        digest (SHAPE-scoped under `shared=True`, so two tenants of the
        same shape — or a restarted live game — share programs), the
        recorded-round count (the scan length is baked into the
        program), the mask width, the donation signature and the
        topology."""
        rec = evaluator.recorded
        eng = self.engine
        from ..mpl.engine import buffer_donation_enabled
        donates = getattr(evaluator, "_fn_donates", None)
        if donates is None:
            donates = buffer_donation_enabled()
        n_dev = eng._sharding.num_devices if eng._sharding else 1
        # the precision mode and the kernel-vs-scan routing are part of
        # the program identity: a bf16 (or fused-kernel) executable must
        # never serve an fp32 (or scan) query from a shared bank
        kernel = list(evaluator.kernel_plan()) \
            if hasattr(evaluator, "kernel_plan") else [False, False]
        raw = json.dumps([self._engine_digest(), "recon",
                          int(rec.weights.shape[0]), eng.partners_count,
                          int(width), bool(donates), n_dev,
                          jax.default_backend(),
                          getattr(evaluator, "precision", "fp32"), kernel])
        return hashlib.sha256(raw.encode()).hexdigest()[:24]

    def _compile_recon_bundle(self, evaluator, width: int) -> dict:
        """AOT-lower + compile the evaluator's fused reconstruct+eval
        program for one mask width. The recorded stream and test set are
        lowered from the CONCRETE arrays (capturing their live
        shardings); the per-batch mask argument is a ShapeDtypeStruct
        carrying the engine's batch sharding, exactly what the dispatch
        closure device_puts."""
        import jax.numpy as jnp
        eng = self.engine
        rec = evaluator.recorded
        sh = eng._sharding.batch_sharding if eng._sharding else None
        if sh is not None:
            masks = jax.ShapeDtypeStruct((int(width), eng.partners_count),
                                         jnp.float32, sharding=sh)
        else:
            masks = jax.ShapeDtypeStruct((int(width), eng.partners_count),
                                         jnp.float32)
        fn = evaluator._batch_eval_fn()
        return {"recon": fn.lower(masks, rec.init_params, rec.deltas,
                                  rec.weights, eng.test).compile()}

    def _do_compile_recon(self, key, evaluator, width: int) -> None:
        """The recon analog of `_do_compile`: compile under an exclusive
        in-flight claim, publish through the shared protocol, account the
        compile (one program, never overlapped — recon compiles happen
        in the querying caller's thread) and record the manifest key."""
        t0 = time.perf_counter()
        entry = None
        ok = False
        try:
            try:
                entry = self._compile_recon_bundle(evaluator, width)
                ok = True
            except Exception as e:
                logger.warning(
                    "program-bank recon compile failed for width=%s — "
                    "falling back to inline jit compilation: %s",
                    width, e)
                entry = e
        finally:
            self._publish(key, entry)
        if ok:
            dur = time.perf_counter() - t0
            obs_metrics.counter("bank.compiles").inc()
            obs_metrics.counter("bank.compile_seconds").inc(dur)
            obs_trace.event(
                "bank.compile", dur=dur, slot_count=None,
                width=int(width), overlapped=False,
                donation=getattr(evaluator, "_fn_donates", False),
                programs=1, recon=True)
            self._record_manifest(key)

    def acquire_recon(self, evaluator, width: int):
        """The banked executable for one reconstruction batch width (or
        None — inline jit path — when the bank is disabled or the
        compile failed). Same claim/wait/publish/hit protocol as
        `acquire` (`_acquire_entry`); compiled keys land in the
        persistent manifest, so a fresh process can prove it already
        holds a live game's programs."""
        if not bank_enabled():
            return None
        key = self.recon_key(evaluator, width)
        entry = self._acquire_entry(
            key, lambda: self._do_compile_recon(key, evaluator, width),
            None, width)
        return entry.get("recon") if entry is not None else None

    # -- persistence (the manifest that makes the cache dir a bank) ------

    def _manifest_doc(self) -> dict:
        d = manifest_dir()
        if not d:
            return {}
        try:
            with open(os.path.join(d, MANIFEST_NAME)) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def persistent_keys(self) -> set:
        return set(self._manifest_doc().get("programs", []))

    def persistent_costs(self) -> dict:
        """key -> {"flops", "bytes_accessed", "transcendentals"} for every
        manifest program whose compile exposed XLA cost analysis —
        pre-cost manifests simply have no `costs` block, so an operator
        (or /varz) can query a cache dir's program costs without
        compiling anything."""
        return dict(self._manifest_doc().get("costs", {}))

    def _record_manifest(self, key: str,
                         cost: "dict | None" = None) -> None:
        """Append a compiled program's key (and its XLA cost analysis,
        when available) to the cache-dir manifest (atomic replace; lost
        manifests only cost a warm-up, never correctness — the XLA cache
        itself is content-addressed). Pre-cost manifests are upgraded in
        place: the `programs` list is untouched, a `costs` block grows
        beside it."""
        d = manifest_dir()
        if not d:
            return
        with _MANIFEST_LOCK:
            doc = self._manifest_doc()
            keys = set(doc.get("programs", []))
            costs = dict(doc.get("costs", {}))
            if key in keys and (cost is None or key in costs):
                return
            keys.add(key)
            if cost is not None:
                costs[key] = cost
            path = os.path.join(d, MANIFEST_NAME)
            tmp = f"{path}.tmp"
            try:
                os.makedirs(d, exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump({"programs": sorted(keys), "costs": costs}, f)
                os.replace(tmp, path)
            except OSError as e:
                logger.warning("program-bank manifest write failed: %s", e)

    def holds_persistent(self, plan) -> bool:
        """True when the persistent manifest proves every program in
        `plan` was compiled (into the persistent compile cache) by some
        earlier run — the bench warm-up's skip condition. A plan whose
        every entry needs mid-run host decisions (non-async pipes, e.g.
        early stopping past the patience bound) has NO bankable programs,
        so the answer is False — the warm-up must still prime those
        inline-jit compiles."""
        if not bank_enabled() or not plan:
            return False
        keys = self.persistent_keys()
        if not keys:
            return False
        bankable = [(pipe, slot_count, width)
                    for pipe, slot_count, width in plan
                    if pipe.dispatches_async]
        if not bankable:
            return False
        return all(self.program_key(pipe, slot_count, width) in keys
                   for pipe, slot_count, width in bankable)
