"""The batched characteristic-function engine.

The reference's `not_twice_characteristic` (/root/reference/mplc/
contributivity.py:92-136) trains ONE coalition at a time — a full serialized
Keras run per subset, 2^N-1 of them for exact Shapley. This engine is the
TPU-native replacement and the performance core of the framework:

  - A coalition is a length-P bitmask over the stacked partner axis.
  - `evaluate(subsets)` batches all cache-misses, pads the batch to a bucket
    size divisible by the device count, and runs the coalition-masked MPL
    trainer `vmap`ped over the mask batch — so 2^N coalitions cost
    ~2^N / (B x n_devices) training wall-clocks instead of 2^N.
  - Across devices the mask batch is sharded over a 1-D `coal` mesh axis
    (data replicated); XLA partitions the whole training program with zero
    communication until the final score gather.
  - Training still early-stops per coalition (frozen `done` flag inside the
    compiled epoch chunk); the host loop stops as soon as every coalition in
    the batch is done.
  - Results are memoized by sorted subset tuple — same key structure as the
    reference, including the marginal-increment bookkeeping
    (contributivity.py:116-134) that IS_reg/AIS consume.

Parity note: 1-partner coalitions run through the dedicated `single` trainer
(persistent optimizer + Keras-style early stopping), mirroring the
reference's SinglePartnerLearning routing (contributivity.py:107-112).

Fault tolerance: every dispatch/harvest boundary runs under a recovery
ladder — transient-failure retry with bounded backoff, OOM cap halving
with re-bucketing of the remaining subsets, and a terminal per-batch CPU
path — plus checksummed, fsync'd cache autosaves for crash/resume. The
invariant is that recovery never changes v(S): retried/re-bucketed/CPU
batches train the same per-coalition rng-fold streams (doc/documentation.md
"Robustness & fault injection"; deterministic injection via
MPLC_TPU_FAULT_PLAN, faults.py).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import constants, faults
from ..obs import devcost
from ..obs import metrics as obs_metrics
from ..obs import numerics as obs_numerics
from ..obs import trace as obs_trace
from ..data.partition import StackedPartners, stack_eval_set
from ..mpl.engine import (EvalSet, MplTrainer, TrainConfig,
                          buffer_donation_enabled)
from ..parallel.mesh import coalition_sharding, make_2d_mesh
from .bank import ProgramBank, bank_enabled

logger = logging.getLogger("mplc_tpu")


def _bucket_size(n: int, n_dev: int, cap_per_dev: int) -> int:
    """Smallest power-of-two multiple of n_dev that fits n, capped."""
    cap = n_dev * cap_per_dev
    b = n_dev
    while b < min(n, cap):
        b *= 2
    return min(b, cap)


def _memo_counters(hits: int, misses: int) -> "str | None":
    """Global + per-estimator-method memo accounting, shared by the exact
    engine and the reconstruction evaluator (contrib/reconstruct.py) so
    the counter keys and the method-attribution rule can't drift apart.
    The method comes from the enclosing `contributivity` span — mixed-
    method runs can attribute memo wins the global
    `not_twice_characteristic` stats can't. Returns the method (or None)
    for the caller's own span attrs."""
    obs_metrics.counter("engine.memo_hits").inc(hits)
    obs_metrics.counter("engine.memo_misses").inc(misses)
    method_span = obs_trace.active_span("contributivity")
    method = (method_span.attrs.get("method")
              if method_span is not None else None)
    if method:
        obs_metrics.counter(f"engine.memo_hits[{method}]").inc(hits)
        obs_metrics.counter(f"engine.memo_misses[{method}]").inc(misses)
    return method


# one-time (per process) deprecation warning for legacy no-checksum caches
_legacy_cache_warned = False


class CacheIntegrityError(ValueError):
    """A coalition cache file is unreadable AS A FILE — truncated write,
    corrupted bytes, checksum mismatch, missing payload keys. Distinct
    from the fingerprint ValueError (a VALID cache describing a different
    game): resume paths may quarantine-and-continue on integrity failures
    but must still refuse fingerprint mismatches."""


@jax.jit
def _fold_bitmask_keys(seed_key: jax.Array, words: jax.Array,
                       n_words: jax.Array) -> jax.Array:
    """Vectorized `_coalition_rng`: one vmapped fold over a [B, W] uint32
    bitmask-word array instead of B host loops of chained fold_in dispatches.
    `n_words[i]` is the per-row fold count (the scalar path folds only up to
    the highest non-zero word, minimum one), so the key streams are
    bit-identical to the loop for every partner count — trailing zero words
    are computed but discarded by the `where`, never folded in."""
    W = words.shape[1]

    def one(wrow, n):
        key = seed_key
        for w in range(W):          # static unroll; W = ceil(P/32), 1 for P<32
            folded = jax.random.fold_in(key, wrow[w])
            key = jnp.where(w < n, folded, key)
        return key

    return jax.vmap(one)(words, n_words)


@jax.jit
def _fold_bitmask_keys_seeded(seed_keys: jax.Array, words: jax.Array,
                              n_words: jax.Array) -> jax.Array:
    """Seed-ensemble variant of `_fold_bitmask_keys`: a PER-ROW seed key
    ([B, 2]) instead of one shared key, so a batch can mix replicas of the
    same coalition under different base seeds. Replica 0 rows carry the
    engine's base key and produce streams bit-identical to the shared-key
    fold (equality-tested)."""
    W = words.shape[1]

    def one(key, wrow, n):
        for w in range(W):
            folded = jax.random.fold_in(key, wrow[w])
            key = jnp.where(w < n, folded, key)
        return key

    return jax.vmap(one)(seed_keys, words, n_words)


class BatchedTrainerPipeline:
    """Jitted init -> epoch-chunk -> finalize pipeline, vmapped over coalitions."""

    def __init__(self, trainer: MplTrainer, partners_count: int):
        self.trainer = trainer
        self.partners_count = partners_count
        self._init = trainer.jit_batched_init
        self._run = trainer.jit_batched_epoch_chunk
        self._fin = trainer.jit_batched_finalize
        # the donation policy bound into the jits above (the finalize
        # donation consumes the state, so scores_async must copy
        # nb_epochs_done out FIRST)
        self._fin_donates = buffer_donation_enabled()

    def scores(self, masks: jnp.ndarray, rngs: jnp.ndarray, stacked, val, test,
               base_rng) -> tuple[np.ndarray, np.ndarray]:
        """Returns (test_accuracies, epochs_trained) per coalition in the
        batch — epochs_trained feeds the engine's throughput accounting."""
        return self.scores_async(masks, rngs, stacked, val, test, base_rng)()

    @property
    def dispatches_async(self) -> bool:
        """True when the whole batch is one dispatch chain with no host
        decision inside — the precondition for overlapping two batches."""
        cfg = self.trainer.cfg
        chunk = cfg.patience if cfg.is_early_stopping else cfg.epoch_count
        chunk = max(1, min(chunk, cfg.epoch_count))
        return not cfg.is_early_stopping or chunk >= cfg.epoch_count

    def scores_async(self, masks: jnp.ndarray, rngs: jnp.ndarray, stacked,
                     val, test, base_rng, exes=None):
        """Dispatch the batch and return a zero-argument harvest thunk.

        With early stopping OFF (the bench/sweep configuration: one
        epoch-chunk spans the whole run) everything is dispatched
        asynchronously and the thunk blocks on the device arrays — so a
        caller can prep and dispatch the NEXT batch while this one
        computes (engine batch pipelining, MPLC_TPU_PIPELINE_BATCHES).
        With early stopping ON, the per-chunk host check (`all(done)`)
        forces a sync loop; the work is complete before the thunk is
        built and the thunk only fetches.

        `exes` (program bank, contrib/bank.py): an AOT-compiled
        {"init","run","fin"} bundle for exactly this batch width — the
        same jits, pre-lowered, so no call here can trigger an inline
        compile. Only the async single-chunk path can use it (the ES
        chunk loop needs n_epochs variants the bank doesn't carry)."""
        cfg = self.trainer.cfg
        banked = exes is not None and self.dispatches_async
        if banked:
            state = exes["init"](rngs)
            state = exes["run"](state, stacked, val, masks, rngs)
        else:
            state = self._init(rngs, self.partners_count)
            if self.dispatches_async:
                # single-chunk program: no host decision inside — stay
                # async. (A one-chunk ES run still never early-stops
                # mid-chunk, so skipping the post-chunk `done` fetch
                # changes nothing.)
                state = self._run(state, stacked, val, masks, rngs,
                                  cfg.epoch_count)
            else:
                chunk = max(1, min(cfg.patience, cfg.epoch_count))
                epochs_left = cfg.epoch_count
                while epochs_left > 0:
                    n = min(chunk, epochs_left)
                    state = self._run(state, stacked, val, masks, rngs, n)
                    epochs_left -= n
                    if bool(jax.device_get(jnp.all(state.done))):
                        break
        # close over the two small result arrays ONLY: holding the full
        # state pytree would pin the batch's params + optimizer buffers in
        # HBM until harvest — the dominant share of the in-flight footprint.
        # Under donation the finalize CONSUMES the state, so the epoch
        # counter must be copied out to its own buffer first.
        epochs_done = (jnp.copy(state.nb_epochs_done) if self._fin_donates
                       else state.nb_epochs_done)
        _, accs = (exes["fin"] if banked else self._fin)(state, test)

        def harvest():
            return (np.asarray(jax.device_get(accs)),
                    np.asarray(jax.device_get(epochs_done)))

        # device-fence capability (obs/devcost.py): force the batch's
        # small result arrays to the host NOW — a reliable "this batch's
        # device work is done" sync (the axon tunnel does not reliably
        # honor block_until_ready, so a host fetch is the fence). The
        # later harvest() re-fetch of the tiny arrays is noise.
        harvest.block = lambda: (jax.device_get(accs),
                                 jax.device_get(epochs_done))
        return harvest


class Batched2DTrainerPipeline(BatchedTrainerPipeline):
    """Coalition-batched training on a 2-D [coal, part] mesh: the mask
    batch shards over `coal` AND the partner dimension shards over `part`
    inside every coalition training (shard_map; per-round aggregation is
    one psum over `part` — mplc_tpu/parallel/partner_shard.py). For large
    partner counts where one device shouldn't hold the whole stacked
    partner axis; the masked (non-slot) path, since slot execution rebinds
    partners dynamically and can't be statically partner-sharded.

    RNG streams are keyed by GLOBAL partner index throughout the trainer,
    so results match the unsharded masked path to float tolerance. The
    early-stopping chunk loop is inherited: only `_init`/`_run`/`_fin`
    are replaced with shard_map'd equivalents."""

    def __init__(self, trainer: MplTrainer, partners_count: int, mesh):
        from functools import partial

        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..mpl.engine import TrainState
        from ..parallel.partner_shard import (shard_map_norep, stacked_specs,
                                              train_state_specs)

        cfg = trainer.cfg
        assert cfg.partner_axis == "part"
        self.trainer = trainer
        self.partners_count = partners_count
        self.mesh = mesh
        self.coal_devices = mesh.shape["coal"]
        self.part_shards = mesh.shape["part"]
        self._local_partners = partners_count // self.part_shards

        st = train_state_specs("part", lflip=cfg.approach == "lflip")
        # prefix every leaf's spec with the coalition-batch axis
        st_b = TrainState(*[P("coal", *s) for s in st])
        sp = stacked_specs("part")

        def init_fn(rngs):
            return jax.vmap(lambda r: trainer.init_state(
                r, self._local_partners))(rngs)

        # no-donation by policy: the rng batch is the only input and the
        # caller passes it again to the epoch chunk
        init2d = jax.jit(shard_map_norep(
            init_fn, mesh=mesh, in_specs=(P("coal"),), out_specs=st_b))
        # base-signature shim: partners_count is baked into init_fn
        self._init = lambda rngs, _partners_count: init2d(rngs)

        # same donation policy as the 1-D jits: the state argument is dead
        # after every epoch-chunk / finalize call here too
        donate = (0,) if buffer_donation_enabled() else ()
        self._fin_donates = bool(donate)

        hoist = trainer._det_hoist_streams()

        def run_fn(state, stacked, val, masks, rngs, n_epochs):
            return jax.vmap(trainer.epoch_chunk,
                            in_axes=(0, None, None, 0, 0, None))(
                state, stacked, val, masks, rngs, n_epochs)

        def run_fn_streams(state, stacked, val, masks, rngs, streams,
                           n_epochs):
            return jax.vmap(trainer._epoch_chunk_streams,
                            in_axes=(0, None, None, 0, 0, 0, None))(
                state, stacked, val, masks, rngs, streams, n_epochs)

        # keyed by n_epochs; exposed as an attribute so the compiler-level
        # sharding tests can .lower() the exact jitted program this
        # pipeline executes (tests/test_sharding.py)
        self._run_cache = run_cache = {}

        def run(state, stacked, val, masks, rngs, n_epochs):
            if n_epochs not in run_cache:
                if hoist:
                    # deterministic-reduce: the hoisted stream stacks ride
                    # in as data, partner-sliced over `part` like the
                    # stacked tensors (obs/numerics.py — in-program
                    # stream generation next to the aggregation
                    # collective is what breaks cross-topology
                    # bit-identity)
                    stream_specs = (P("coal", None, "part", None),
                                    P("coal", None, None, "part", None))
                    run_cache[n_epochs] = jax.jit(shard_map_norep(
                        partial(run_fn_streams, n_epochs=n_epochs),
                        mesh=mesh,
                        in_specs=(st_b, sp, P(), P("coal", "part"),
                                  P("coal"), stream_specs),
                        out_specs=st_b), donate_argnums=donate)
                else:
                    run_cache[n_epochs] = jax.jit(shard_map_norep(
                        partial(run_fn, n_epochs=n_epochs), mesh=mesh,
                        in_specs=(st_b, sp, P(), P("coal", "part"),
                                  P("coal")),
                        out_specs=st_b), donate_argnums=donate)
            if hoist:
                streams = trainer.jit_gen_streams(
                    rngs, n_epochs, stacked.mask, batched=True,
                    start_epoch=state.epoch)
                return run_cache[n_epochs](state, stacked, val, masks,
                                           rngs, streams)
            return run_cache[n_epochs](state, stacked, val, masks, rngs)

        self._run = run
        # params are replicated over `part` after aggregation; finalize is
        # an ordinary vmapped eval, GSPMD-partitioned over the coal axis
        self._fin = jax.jit(jax.vmap(trainer.finalize, in_axes=(0, None)),
                            donate_argnums=donate)
        self.batch_sharding = NamedSharding(mesh, P("coal", "part"))
        self.rng_sharding = NamedSharding(mesh, P("coal"))


class CharacteristicEngine:
    """Memoizing, batching, device-sharding characteristic function v(S)."""

    # class-level defaults so engine subclasses that bypass __init__ (the
    # test suite's FakeEngine) still satisfy the ensemble/fault surface
    seed_ensemble = 1
    _partner_faults: dict = {}
    _forever_dropped: frozenset = frozenset()
    program_bank = None
    # device-time accounting defaults (obs/devcost.py): engine doubles
    # that bypass __init__ run unfenced and unmetered
    device_meter = None
    _fence_interval = 0
    # numeric-truth plane defaults (obs/numerics.py): doubles that bypass
    # __init__ run unledgered and unaudited
    numerics_ledger = None
    _numerics_audit = False
    _ledger_ctx: dict = {}
    # set when a legacy (pre-checksum) cache was loaded: the next
    # save_cache to that file rewrites it in the integrity format
    _cache_needs_upgrade = False
    _legacy_cache_path: "str | None" = None
    # fleet width pinning (parallel/fleet.py): {(pipe, slot_count): width}
    # from the FULL sweep's plan, so a shard evaluating only its slice
    # still compiles the same (slot, width) programs as every other shard
    _fleet_widths: "dict | None" = None

    def __init__(self, scenario, share_data_from: "CharacteristicEngine | None" = None,
                 seed_ensemble: int | None = None):
        # Persistent compilation cache (MPLC_TPU_COMPILE_CACHE_DIR):
        # configured before this engine's first trace/compile, so repeated
        # sweeps — and service restarts — reload executables from disk
        # instead of recompiling the slot pipelines. Idempotent no-op when
        # the knob is unset.
        from ..utils import enable_compile_cache_from_env
        enable_compile_cache_from_env()
        self.scenario = scenario
        self.partners_list = sorted(scenario.partners_list, key=lambda p: p.id)
        self.partners_count = len(self.partners_list)
        self.model = scenario.dataset.model
        self.seed = getattr(scenario, "seed", 0)

        # Partner-level fault model (MPLC_TPU_PARTNER_FAULT_PLAN,
        # faults.py): dropout/straggler entries become static TrainConfig
        # tuples compiled into the fedavg trainers below; noisy/glabel
        # entries are data-plane and were already applied by
        # Scenario.data_corruption. Partners dropped from epoch 1 never
        # participate, so the per-coalition rng stream is canonicalized
        # over the membership WITHOUT them — that is what makes a
        # dropout@pK:epoch1 sweep bit-identical to the fault-free sweep
        # of the partner-excluded coalitions (equality-tested).
        stashed = getattr(scenario, "_partner_fault_plan", None)
        if stashed is not None:
            # Scenario.data_corruption already parsed (and clipped) the
            # plan — reuse it so the fingerprint describes the exact plan
            # whose data faults were applied, even if the env mutated
            # since, and the clip warning fires once per run
            self._partner_faults = stashed
        else:
            self._partner_faults = faults.clip_partner_plan(
                faults.partner_fault_plan_from_env(), self.partners_count)
        self._forever_dropped = faults.forever_dropped(self._partner_faults)
        drop_epochs, straggler_delays = faults.trainer_fault_arrays(
            self._partner_faults, self.partners_count)
        if faults.data_fault_specs(self._partner_faults) and \
                not getattr(scenario, "_data_faults_applied", False):
            # trainer-plane entries are enforced right here, but
            # noisy/glabel corruption happens in Scenario.data_corruption
            # — a direct-engine caller that skipped it would compute a
            # CLEAN game while the cache fingerprint names the plan (the
            # data digest still refuses cross-run reuse, but the mislabel
            # deserves a loud warning at the source)
            import warnings
            warnings.warn(
                f"{faults.PARTNER_FAULT_PLAN_ENV} carries data-plane "
                "(noisy/glabel) entries but Scenario.data_corruption() "
                "was never run — this engine is computing the UNcorrupted "
                "game", stacklevel=2)

        # Seed-ensemble sweeps (seed_ensemble=K / MPLC_TPU_SEED_ENSEMBLE):
        # every coalition trains K replicas under K distinct base seeds,
        # packed as EXTRA ROWS of the same slot-batch buckets — one
        # sweep's dispatch structure, K x rows, not K sequential sweeps.
        # Replica 0 uses the engine's base seed unchanged, so the point
        # estimates (charac_fct_values) are bit-identical to a K=1 run;
        # all replicas land in charac_fct_samples for CI / rank-stability
        # reporting (contrib/shapley.trust_summary).
        if seed_ensemble is not None:
            if int(seed_ensemble) < 1:
                raise ValueError(
                    f"seed_ensemble must be >= 1, got {seed_ensemble}")
            self.seed_ensemble = int(seed_ensemble)
        else:
            self.seed_ensemble = constants._env_positive_int(
                constants.SEED_ENSEMBLE_ENV, 1)
        base_key = jax.random.PRNGKey(self.seed)
        if self.seed_ensemble > 1:
            self._ensemble_rows = np.stack(
                [np.asarray(base_key, np.uint32)]
                + [np.asarray(jax.random.fold_in(base_key, 0x5EED0000 + j),
                              np.uint32)
                   for j in range(1, self.seed_ensemble)])
        else:
            self._ensemble_rows = None
        # per-coalition replica values: {subset: np.ndarray [K]} (empty
        # unless seed_ensemble > 1)
        self.charac_fct_samples: dict[tuple, np.ndarray] = {}

        label_dim = self.model.label_dim()
        if share_data_from is not None:
            # reuse another engine's device arrays (same scenario data) —
            # avoids a second HBM copy of the stacked train + eval sets
            self.stacked = share_data_from.stacked
            self.val = share_data_from.val
            self.test = share_data_from.test
        else:
            self.stacked = StackedPartners.build(self.partners_list, label_dim)
            nv = len(scenario.dataset.x_val)
            nt = len(scenario.dataset.x_test)
            chunk_v = min(constants.EVAL_CHUNK_SIZE,
                          max(128, 1 << (max(nv - 1, 1)).bit_length()))
            chunk_t = min(constants.EVAL_CHUNK_SIZE,
                          max(128, 1 << (max(nt - 1, 1)).bit_length()))
            self.val = EvalSet(*stack_eval_set(scenario.dataset.x_val,
                                               scenario.dataset.y_val, label_dim, chunk_v))
            self.test = EvalSet(*stack_eval_set(scenario.dataset.x_test,
                                                scenario.dataset.y_test, label_dim, chunk_t))

        base = dict(
            aggregator=scenario.aggregation_name,
            epoch_count=scenario.epoch_count,
            minibatch_count=scenario.minibatch_count,
            gradient_updates_per_pass=scenario.gradient_updates_per_pass_count,
            # The reference always trains coalitions with early stopping on
            # (contributivity.py:102-106), but with epoch_count <= patience
            # the stop condition can never fire (both the [e]-vs-[e-patience]
            # rule and the single trainer's wait counter need > patience
            # epochs) — so the flag's only effect would be one wasted val
            # eval per epoch per coalition. Numerics are identical either
            # way, and the epoch-chunk rng streams don't depend on the flag
            # (chunk = min(patience, epoch_count) in both cases here).
            is_early_stopping=scenario.epoch_count > constants.PATIENCE,
            compute_dtype=getattr(scenario, "compute_dtype", "float32"),
            record_partner_val=False,
            # coalition sweeps never read the per-minibatch val history;
            # only the one early-stopping column per epoch is evaluated
            record_val_history=False,
        )
        if drop_epochs is not None or straggler_delays is not None:
            if scenario.multi_partner_learning_approach_key != "fedavg":
                raise ValueError(
                    "MPLC_TPU_PARTNER_FAULT_PLAN dropout/straggler entries "
                    "require the fedavg approach (their mask/renormalize "
                    "and stale-params semantics are FedAvg aggregation "
                    "semantics); got "
                    f"'{scenario.multi_partner_learning_approach_key}'")
            base.update(partner_drop_epochs=drop_epochs,
                        partner_straggler_delays=straggler_delays)
        multi_cfg = TrainConfig(approach=scenario.multi_partner_learning_approach_key,
                                **base)
        single_cfg = TrainConfig(approach="single", **base)
        self._multi_cfg = multi_cfg
        self.multi_pipe = BatchedTrainerPipeline(
            MplTrainer.get(self.model, multi_cfg), self.partners_count)
        self.single_pipe = BatchedTrainerPipeline(
            MplTrainer.get(self.model, single_cfg), self.partners_count)
        # Slot execution (fedavg + the seq family): a size-k coalition
        # trains k partner slots instead of P masked ones — ~2x less compute
        # on a full Shapley sweep. For the seq approaches the win is the
        # P-|S| wasted no-op partner visits per minibatch the masked scan
        # pays. One pipeline per coalition size, built lazily.
        self._use_slots = (multi_cfg.approach in (
            "fedavg", "seq-pure", "seq-with-final-agg", "seqavg")
            and os.environ.get("MPLC_TPU_NO_SLOTS") != "1")
        self._slot_pow2 = os.environ.get("MPLC_TPU_SLOT_POW2") == "1"
        # Slot-bucket merging (the default between `exact` and `pow2`):
        # adjacent coalition sizes share one slot program — size k rides
        # size k+1's width for even k — so a 10-partner sweep compiles 5
        # slot programs instead of 9 and the smaller size's tail coalitions
        # fill batch rows the larger size would have padded. The `-1`
        # unused-slot convention makes the mixed widths exact, not
        # approximate (_slot_buckets). MPLC_TPU_SLOT_MERGE=0 restores the
        # tight per-size grouping; an explicit MPLC_TPU_SLOT_POW2=1 wins.
        self._slot_merge = (not self._slot_pow2
                            and os.environ.get("MPLC_TPU_SLOT_MERGE")
                            not in ("0", "exact"))
        # Batch pipelining: dispatch batch i+1 while batch i computes, so
        # the device never idles through host-side batch prep, transfers
        # and result fetches between batches (the dispatch-gap component of
        # the non-MFU time). Default ON (results are identical — same
        # executables, same per-coalition rng streams, only the harvest
        # point moves); MPLC_TPU_PIPELINE_BATCHES=0 opts out.
        self._pipeline_batches = \
            os.environ.get("MPLC_TPU_PIPELINE_BATCHES", "1") != "0"
        self._slot_pipes: dict[int, BatchedTrainerPipeline] = {}
        # 2-D singles pipelines, keyed by bucket width (the data-sliced
        # singles path binds partners_count to the batch width)
        self._singles_pipes: dict[int, BatchedTrainerPipeline] = {}
        self._seed_key = jax.random.PRNGKey(self.seed)
        # fold words per 32 partner indices (matches _coalition_rng's loop)
        self._rng_word_count = max(1, (self.partners_count + 31) // 32)

        # 2-D [coal, part] mode (MPLC_TPU_PARTNER_SHARDS=p): shard the
        # partner dimension over p devices inside every coalition training,
        # coalitions over the remaining n_dev/p. For partner counts / models
        # too large for one device's HBM; numerics identical to the 1-D
        # masked path (global-index rng keying).
        self._pipe2d = None
        _env = os.environ.get("MPLC_TPU_PARTNER_SHARDS")
        if _env:
            part_shards = int(_env)  # env var wins over the Scenario param
            if part_shards < 1:
                raise ValueError(
                    f"MPLC_TPU_PARTNER_SHARDS must be >= 1, got {_env!r}")
        else:
            part_shards = int(getattr(scenario, "partner_shards", None) or 1)
        # write the effective value back so to_dataframe/results.csv record
        # the mode actually run, even under the env override
        scenario.partner_shards = part_shards
        # Deterministic-reduce (obs/numerics.py): the masked fedavg/lflip
        # path ALWAYS runs through the [coal x part] shard_map pipeline —
        # with part_shards=1 when unsharded — because the bit-identity
        # contract holds WITHIN the shard_map program family (the audit's
        # localization: a plain-jit embedding of the same pass rounds
        # differently than its shard_map twin). part=1 is the unsharded
        # reference: the whole partner axis is resident per device and the
        # gather collective over the singleton axis moves nothing.
        det2d = (bool(multi_cfg.deterministic_reduce)
                 and multi_cfg.approach in ("fedavg", "lflip")
                 and multi_cfg.partner_drop_epochs is None
                 and multi_cfg.partner_straggler_delays is None)
        if part_shards > 1 or det2d:
            if self.seed_ensemble > 1:
                raise ValueError(
                    "seed-ensemble sweeps (MPLC_TPU_SEED_ENSEMBLE > 1) are "
                    "not supported in the 2-D partner-sharded mode (nor "
                    "under MPLC_TPU_DETERMINISTIC_REDUCE, which routes "
                    "through the same pipeline)")
            n_dev = len(jax.devices())
            if part_shards > 1 and multi_cfg.approach not in ("fedavg",
                                                              "lflip"):
                raise ValueError(
                    "MPLC_TPU_PARTNER_SHARDS requires a partner-parallel "
                    f"approach (fedavg/lflip), got {multi_cfg.approach!r}")
            if self.partners_count % part_shards or n_dev % part_shards:
                raise ValueError(
                    f"MPLC_TPU_PARTNER_SHARDS={part_shards} must divide both "
                    f"the partner count ({self.partners_count}) and the "
                    f"device count ({n_dev})")
            mesh = make_2d_mesh(n_dev // part_shards, part_shards)
            cfg2d = dataclasses.replace(multi_cfg, partner_axis="part")
            self._pipe2d = Batched2DTrainerPipeline(
                MplTrainer.get(self.model, cfg2d), self.partners_count, mesh)
            self._use_slots = False
        # record the slot-bucketing mode actually run in results.csv (same
        # rationale as the partner_shards write-back above) — after the 2-D
        # branch, which disables slot execution entirely
        scenario.slot_bucketing = (
            "masked" if not self._use_slots
            else "pow2" if self._slot_pow2
            else "merge" if self._slot_merge else "exact")

        self.charac_fct_values: dict[tuple, float] = {(): 0.0}
        self.increments_values = [dict() for _ in range(self.partners_count)]
        self.first_charac_fct_calls_count = 0
        # throughput accounting over non-padding coalitions: total training
        # epochs executed, and training samples consumed per active partner
        # per epoch — size_i // MB * MB for the multi/slot trainers (the
        # static minibatch window) but the full size_i for the single
        # trainer (its step grid covers every valid row,
        # mpl/engine.py _single_epoch). Padded batch slots are excluded, so
        # sample rates derived from these are conservative.
        self.epochs_trained = 0
        self.samples_trained = 0
        sizes_np = np.asarray(self.stacked.sizes)
        mbc = multi_cfg.minibatch_count
        self._epoch_samples_multi = sizes_np // mbc * mbc
        self._epoch_samples_single = sizes_np
        # When set, the memo cache is persisted after EVERY device batch.
        # With batch pipelining (the default) a second batch can be in
        # flight when a hard kill lands, so a crash mid-sweep loses up to
        # TWO batches of trained coalitions; with the overlap opted out
        # (MPLC_TPU_PIPELINE_BATCHES=0) at most one. (The reference loses
        # everything — it checkpoints nothing.)
        self.autosave_path = None
        # Optional callable(done_in_group, remaining_in_call, slot_count)
        # invoked after every completed device batch — long sweeps (and the
        # bench) surface per-batch progress instead of going silent for the
        # whole call.
        self.progress = None

        # Fault tolerance (faults.py). All knobs are read HERE, once per
        # engine, with warn+fallback parses: a typo'd value degrades to the
        # default instead of killing an hours-long sweep mid-run. Recovery
        # must never change v(S) — every path below re-runs batches through
        # the same per-coalition rng-fold streams, so recovered sweeps are
        # bit-identical to fault-free ones (equality-tested in
        # tests/test_faults.py).
        self._max_retries = constants._env_positive_int(
            constants.MAX_RETRIES_ENV, 3)
        self._retry_backoff = constants._env_nonneg_float(
            constants.RETRY_BACKOFF_ENV, 0.5)
        self._max_cap_halvings = constants._env_positive_int(
            constants.MAX_CAP_HALVINGS_ENV, 3)
        # rungs already taken down the OOM ladder: every halving applies to
        # ALL subsequent _device_batch_cap computations, so re-bucketing
        # the remaining subsets reuses the ordinary width machinery
        self._cap_halvings = 0
        self._cpu_degraded = False
        self._cpu_data = None  # lazily host-pinned copy for the CPU path
        # 1-based device-batch ordinal (dispatch order, shared across the
        # engine's paths): the unit the fault plan addresses. A RETRY of a
        # batch keeps its ordinal, so `transient@batchK` means "batch K
        # fails once, then its bit-identical retry goes through".
        self._batch_ordinal = 0
        self._faults = faults.FaultInjector.from_env()

        # Sampled device fences + the device-seconds meter
        # (obs/devcost.py, MPLC_TPU_DEVICE_FENCE_RATE): every
        # `_fence_interval`-th batch ordinal is dispatched with the
        # pipeline overlap drained and its results host-fetched
        # immediately — a true device-step-seconds sample. Deterministic
        # in the ordinal, so runs replay; never changes v(S) (only the
        # harvest point moves — equality-tested in tests/test_devcost.py).
        self._fence_interval = devcost.fence_interval()
        self.device_meter = devcost.DeviceMeter(self._fence_interval)

        # Numeric-truth plane (obs/numerics.py): the opt-in value-
        # provenance ledger (MPLC_TPU_NUMERICS_LEDGER names the output
        # file; one ledger per engine keyed by the cache fingerprint) and
        # the fence-sampled per-device reduction audit
        # (MPLC_TPU_NUMERICS_AUDIT=1 — runs a SEPARATE instrumented
        # capture per audited coalition, so audit-on vs audit-off v(S)
        # is bit-identical; equality-tested).
        self._numerics_audit = obs_numerics.audit_enabled()
        self._audited_subsets: set = set()
        self.numerics_audits: list = []
        self._ledger_ctx = {}
        _ledger_path = obs_numerics.ledger_path_from_env()
        if _ledger_path:
            import hashlib as _hashlib
            import json as _json
            fp_digest = _hashlib.sha256(
                _json.dumps(self._fingerprint(),
                            sort_keys=True).encode()).hexdigest()[:16]
            self.numerics_ledger = obs_numerics.ValueLedger(
                fp_digest,
                meta={
                    "topology": "2d" if self._pipe2d is not None else "1d",
                    "part_shards": (self._pipe2d.part_shards
                                    if self._pipe2d is not None else 1),
                    "n_devices": len(jax.devices()),
                    "reduction_mode": ("deterministic"
                                       if multi_cfg.deterministic_reduce
                                       else "default"),
                    # a bf16 ledger and an fp32 ledger are different
                    # measurements of the same game: diff_ledgers
                    # consumers read the mode from meta
                    "precision": multi_cfg.precision,
                    "slot_bucketing": scenario.slot_bucketing,
                },
                path=_ledger_path)
        else:
            self.numerics_ledger = None

        self._sharding = coalition_sharding()

        # Program bank (contrib/bank.py): AOT-compiled slot programs with
        # compile/execute overlap. None when disabled — every program then
        # compiles inline at first dispatch, the pre-bank behavior.
        # Deterministic-reduce is a correctness mode and runs bank-less:
        # its hoisted-stream trainers dispatch through wrapper callables
        # the bank cannot `.lower()`, and its masked path runs the
        # (unbanked) 2-D-family pipeline anyway.
        self.program_bank = (ProgramBank(self)
                             if bank_enabled()
                             and not multi_cfg.deterministic_reduce
                             else None)

    # ------------------------------------------------------------------

    def _coalition_rng(self, subset: tuple) -> jax.Array:
        """Deterministic per-coalition rng, independent of batch composition
        — same coalition always trains identically. The membership bitmask
        is folded in 32-bit words so partner counts >= 32 don't overflow
        fold_in's uint32 operand (identical stream to the single fold for
        < 32 partners: the loop runs once)."""
        bits = 0
        for i in subset:
            bits |= 1 << int(i)
        key = jax.random.PRNGKey(self.seed)
        while True:
            key = jax.random.fold_in(key, bits & 0xFFFFFFFF)
            bits >>= 32
            if not bits:
                return key

    def _rng_fold_words(self, subsets: list[tuple]) -> tuple[np.ndarray,
                                                             np.ndarray]:
        """Whole-call rng prep for `_fold_bitmask_keys`: the [N, W] uint32
        membership-bitmask words of every subset (one NumPy scatter, no
        device dispatch) plus the per-row fold count — the index of the
        highest non-zero word + 1, minimum one, exactly the scalar loop's
        iteration count."""
        n = len(subsets)
        W = self._rng_word_count
        words = np.zeros((n, W), np.uint32)
        lens = np.fromiter((len(s) for s in subsets), np.intp, n)
        total = int(lens.sum())
        if total:
            rows = np.repeat(np.arange(n), lens)
            members = np.fromiter((int(i) for s in subsets for i in s),
                                  np.int64, total)
            np.bitwise_or.at(
                words, (rows, members >> 5),
                (np.uint32(1) << (members & 31).astype(np.uint32)))
        nz = words != 0
        n_words = np.where(nz.any(axis=1),
                           W - np.argmax(nz[:, ::-1], axis=1),
                           1).astype(np.int32)
        return words, n_words

    def _coalition_arrays(self, subsets: list[tuple],
                          slot_count: int | None) -> np.ndarray:
        """Whole-call coalition-argument prep: the [N, slot_count] int32
        slot-id rows (-1 = unused slot) or [N, P] float32 masks for every
        subset, built with one NumPy scatter instead of a per-batch Python
        fill loop."""
        n = len(subsets)
        lens = np.fromiter((len(s) for s in subsets), np.intp, n)
        total = int(lens.sum())
        rows = np.repeat(np.arange(n), lens)
        members = np.fromiter((int(i) for s in subsets for i in sorted(s)),
                              np.int64, total)
        if slot_count is not None:
            coal = np.full((n, slot_count), -1, np.int32)
            starts = np.cumsum(lens) - lens
            cols = np.arange(total) - np.repeat(starts, lens)
            coal[rows, cols] = members
        else:
            coal = np.zeros((n, self.partners_count), np.float32)
            coal[rows, members] = 1.0
        return coal

    def _batch_rngs(self, words: np.ndarray, n_words: np.ndarray,
                    sel: np.ndarray,
                    seed_rows: np.ndarray | None = None) -> jax.Array:
        """[b, 2] per-coalition keys for one padded batch (rows selected by
        `sel` from the whole-call fold words), bit-identical to stacking
        `_coalition_rng` per subset — equality-tested. With `seed_rows`
        (seed-ensemble sweeps) each row folds its OWN base key: replica-0
        rows carry the engine key and reproduce the shared-key stream."""
        if seed_rows is not None:
            return _fold_bitmask_keys_seeded(jnp.asarray(seed_rows[sel]),
                                             jnp.asarray(words[sel]),
                                             jnp.asarray(n_words[sel]))
        return _fold_bitmask_keys(self._seed_key, jnp.asarray(words[sel]),
                                  jnp.asarray(n_words[sel]))

    def _effective_subset(self, subset: tuple) -> tuple:
        """The coalition's membership minus forever-dropped partners (the
        rng-canonicalization set: a partner dropped from epoch 1 never
        trains, so the stream must match the partner-excluded run's)."""
        return tuple(i for i in subset if i not in self._forever_dropped)

    def _incomplete(self, subset: tuple) -> bool:
        """True when the subset still needs device work: no point estimate,
        or (seed-ensemble) any replica row not yet harvested."""
        if subset not in self.charac_fct_values:
            return True
        if self.seed_ensemble == 1:
            return False
        arr = self.charac_fct_samples.get(subset)
        return arr is None or bool(np.isnan(arr).any())

    def _store_sample(self, subset: tuple, rep: int, value: float) -> None:
        arr = self.charac_fct_samples.get(subset)
        if arr is None:
            arr = self.charac_fct_samples[subset] = np.full(
                self.seed_ensemble, np.nan)
        arr[rep] = value

    def _device_batch_cap(self, slot_count: int | None = None,
                          overlap: bool = False) -> int:
        """Coalitions per device per compiled batch.

        Ceiling = constants.MAX_COALITIONS_PER_DEVICE_BATCH (16) by
        default: larger power-of-two buckets would each compile their own
        program per slot size, exploding compile time for marginal dispatch
        savings. With MPLC_TPU_SLOT_MERGE bounding the program count the
        ceiling is worth raising — MPLC_TPU_BATCH_CAP_CEILING lifts it
        (same sweep protocol as the cap-32 bisect,
        scripts/tune_coalition_cap.py). The cap autotunes DOWN when the
        per-coalition HBM footprint (params x (1 global + slots trained in
        flight + adam moments + grads) plus the eval-chunk activation
        window) would overflow ~50% of device memory. Override with
        MPLC_TPU_COALITIONS_PER_DEVICE (a malformed value warns and falls
        back to the autotune instead of crashing mid-sweep).

        Every RESOURCE_EXHAUSTED recovery (`_degrade_cap`) halves the
        result — env override included: the operator's number was measured
        on a non-OOMing run, and the ladder exists precisely because that
        measurement stopped holding.
        """
        env_cap = constants._env_positive_int(
            "MPLC_TPU_COALITIONS_PER_DEVICE", 0)
        if env_cap:
            return max(1, env_cap >> self._cap_halvings)
        return self._autotuned_cap(slot_count, overlap,
                                   buffer_donation_enabled())

    def _model_param_bytes(self) -> int:
        if getattr(self, "_param_bytes", None) is None:
            shapes = jax.eval_shape(self.model.init, jax.random.PRNGKey(0))
            self._param_bytes = sum(
                int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(shapes))
        return self._param_bytes

    def _per_coalition_bytes(self, k: int, donate: bool) -> int:
        """Modeled HBM footprint of one in-flight coalition at slot count
        `k`. One TrainState copy's param side is ~(2k + 2) param-sizes
        (k slot copies + 2 adam moments per slot amortized + the global
        params and grad workspace); WITHOUT buffer donation the epoch
        chunk's input and output state coexist across the executable
        boundary — two copies — which is exactly the duplication
        `donate_argnums` removes (mpl/engine.py jit properties)."""
        state_bytes = self._model_param_bytes() * (2 * k + 2)
        per_coal = state_bytes * (1 if donate else 2)
        # activation window: eval chunk + training sub-batch, fudge x8 for
        # conv intermediates
        sample_bytes = int(np.prod(self.stacked.x.shape[2:])) * 4
        per_coal += 8 * sample_bytes * max(
            constants.EVAL_CHUNK_SIZE,
            self.stacked.x.shape[1] // max(1, self.multi_pipe.trainer.cfg.minibatch_count))
        return per_coal

    def _device_hbm_bytes(self) -> int:
        if getattr(self, "_hbm_bytes", None) is None:
            # one device query per engine, not one per _run_batch call —
            # memory_stats crosses the tunnel on remote backends. The
            # cached value is INVALIDATED on every engine.degrade event
            # (`_degrade_cap`): after OOM cap-halving or CPU degradation
            # the autotuner must reason from post-fault memory, not the
            # pre-fault snapshot.
            try:
                stats = jax.local_devices()[0].memory_stats()
                self._hbm_bytes = int(stats.get("bytes_limit", 8 << 30))
            except Exception:
                self._hbm_bytes = 8 << 30
        return self._hbm_bytes

    def _autotuned_cap(self, slot_count: "int | None", overlap: bool,
                       donate: bool) -> int:
        k = slot_count if slot_count is not None else self.partners_count
        per_coal = self._per_coalition_bytes(k, donate)
        fit = max(1, int(0.5 * self._device_hbm_bytes() / max(per_coal, 1)))
        if overlap:
            # two batches genuinely in flight — halve the memory-derived
            # cap (the explicit env override above is left to the operator;
            # on a chip where the ceiling binds instead of memory, as on
            # v5e with the tiny sweep models, this changes nothing)
            fit = max(1, fit // 2)
        ceiling = constants._env_positive_int(
            constants.BATCH_CAP_CEILING_ENV,
            constants.MAX_COALITIONS_PER_DEVICE_BATCH)
        return max(1, min(ceiling, fit) >> self._cap_halvings)

    def _hbm_attrs(self, slot_count: "int | None" = None) -> dict:
        """The `engine.hbm` event payload behind the sweep report's hbm
        row: modeled per-coalition footprint, the donation saving, the
        autotuned cap with and without donation (donation is what lets
        the MPLC_TPU_COALITIONS_PER_DEVICE ceiling rise), and the
        device's measured peak from the high-water gauge."""
        donate = buffer_donation_enabled()
        k = slot_count if slot_count is not None else self.partners_count
        per_don = self._per_coalition_bytes(k, True)
        per_nodon = self._per_coalition_bytes(k, False)
        peak = obs_metrics.gauge("engine.device_mem_high_water_bytes").value
        # cap_before/after isolate the DONATION effect (overlap=False for
        # comparability); cap_effective is what _run_batch actually uses —
        # under default batch pipelining the memory-derived share is
        # halved for the two-in-flight overlap
        overlap = self._pipeline_batches and self.multi_pipe.dispatches_async
        return {
            "param_bytes": self._model_param_bytes(),
            "slot_count": k,
            "donation": donate,
            "per_coalition_bytes": per_don if donate else per_nodon,
            "donated_bytes_per_coalition": per_nodon - per_don if donate
            else 0,
            "cap_before_donation": self._autotuned_cap(slot_count, False,
                                                       False),
            "cap_after_donation": self._autotuned_cap(slot_count, False,
                                                      True),
            "cap_effective": self._device_batch_cap(slot_count, overlap),
            "hbm_bytes_limit": self._device_hbm_bytes(),
            "peak_in_use_bytes": peak,
        }

    def _planned_width(self, n_jobs: int, slot_count: "int | None",
                       pipe) -> int:
        """The deterministic 1-D bucket width for a call of `n_jobs` jobs —
        shared by _run_batch's dispatch loop, the program-bank prefetch
        plan and bench's warm-up skip, so the planned and executed widths
        can never diverge."""
        overlap = self._pipeline_batches and pipe.dispatches_async
        n_dev = max(self._sharding.num_devices if self._sharding else 1, 1)
        cap = self._device_batch_cap(slot_count, overlap)
        width = _bucket_size(min(n_jobs, n_dev * cap), n_dev, cap)
        if self._fleet_widths and not self._cap_halvings:
            # fleet shard (parallel/fleet.py): run this bucket at the
            # FULL sweep's planned width even when the slice is smaller,
            # so every shard executes the same programs and the shared
            # bank manifest serves W-1 of W shards. The pin never
            # shrinks a width, and the OOM ladder un-pins: a degraded
            # cap must re-bucket at the degraded width, not the plan's.
            pinned = self._fleet_widths.get((pipe, slot_count))
            if pinned:
                return max(width, pinned)
        return width

    def _bucket_plan(self, singles: list, multis: list) -> list:
        """[(pipe, slot_count, width)] in dispatch order for a 1-D
        evaluate() call — the program bank's prefetch schedule (and, fed
        with a full sweep's subsets via `sweep_plan`, the bench warm-up's
        needed-program list)."""
        if self._pipe2d is not None or self._cpu_degraded:
            return []
        K = self.seed_ensemble
        plan = []
        if singles:
            plan.append((self.single_pipe, None,
                         self._planned_width(len(singles) * K, None,
                                             self.single_pipe)))
        if multis:
            if self._use_slots:
                for slot_count, group in self._slot_buckets(multis):
                    pipe = self._slot_pipe(slot_count)
                    plan.append((pipe, slot_count,
                                 self._planned_width(len(group) * K,
                                                     slot_count, pipe)))
            else:
                plan.append((self.multi_pipe, None,
                             self._planned_width(len(multis) * K, None,
                                                 self.multi_pipe)))
        return plan

    def sweep_plan(self, subsets) -> list:
        """The bucket plan a full evaluate() over `subsets` would run,
        memo state ignored (every subset counted as missing) — what the
        bench warm-up needs to know to prove the program bank already
        holds a sweep's every program. MUST mirror evaluate()'s routing
        exactly: classify by EFFECTIVE size (minus forever-dropped
        partners) but keep the ORIGINAL keys — `_slot_buckets` widths
        come from the original membership, and all-dropped coalitions
        are stored as v=0 without ever dispatching."""
        keys = list(dict.fromkeys(
            tuple(sorted(int(i) for i in s)) for s in subsets))
        if self._forever_dropped:
            keys = [k for k in keys
                    if not all(i in self._forever_dropped for i in k)]
            lens = {k: len(self._effective_subset(k)) for k in keys}
        else:
            lens = {k: len(k) for k in keys}
        singles = [k for k in keys if lens[k] == 1]
        multis = [k for k in keys if lens[k] > 1]
        return self._bucket_plan(singles, multis)

    def pin_fleet_widths(self, subsets) -> dict:
        """Fleet-sweep width pinning (parallel/fleet.py): compute the
        FULL sweep's bucket plan over `subsets` and pin this engine's
        1-D bucket widths to it, so a shard evaluating only a slice
        still compiles exactly the plan's (slot_count, width) programs —
        the precondition for the shared program-bank manifest to serve
        every shard after the first. Returns {slot_count_or_None: width}
        for reporting. No-op (returns {}) where no 1-D plan exists (2-D
        mode, CPU-degraded engines) — equality there never depended on
        widths anyway."""
        plan = self.sweep_plan(subsets)
        self._fleet_widths = {(pipe, slot): width
                              for pipe, slot, width in plan} or None
        return {slot: width for _pipe, slot, width in plan}

    def _slot_pipe(self, k: int) -> BatchedTrainerPipeline:
        if k not in self._slot_pipes:
            cfg = dataclasses.replace(self._multi_cfg, slot_count=k)
            self._slot_pipes[k] = BatchedTrainerPipeline(
                MplTrainer.get(self.model, cfg), self.partners_count)
        return self._slot_pipes[k]

    def _singles_pipe(self, b: int) -> BatchedTrainerPipeline:
        """2-D-mode singles pipeline for bucket width `b`, cached so
        repeated `_run_singles_sliced` calls (IS/MC estimators re-request
        singles every block) stop re-wrapping the trainer per call."""
        if b not in self._singles_pipes:
            self._singles_pipes[b] = BatchedTrainerPipeline(
                self.single_pipe.trainer, b)
        return self._singles_pipes[b]

    def _maybe_fence(self, fetch, meta) -> None:
        """Sampled device fence (obs/devcost.py): when `meta["ordinal"]`
        is a fence ordinal, time a host fetch of the just-dispatched
        batch's results — the true device-step seconds behind the
        report's device row and the service's device-seconds metering.
        The caller drained any in-flight overlap first, so the sample
        times ONLY this batch. Never raises: a failing fetch here leaves
        the error to the harvest ladder (which re-dispatches/retries
        bit-identically), and the sample is simply not taken."""
        if not devcost.should_fence(meta.get("ordinal", 0),
                                    self._fence_interval):
            return
        block = getattr(fetch, "block", None)
        if block is None:
            return  # stubbed pipes (tests) have no fence capability
        t0 = time.perf_counter()
        try:
            block()
        except Exception:
            return  # the harvest ladder owns failures
        dur = time.perf_counter() - t0
        meta["device_sec"] = dur
        obs_metrics.histogram("engine.device_step_sec").observe(dur)
        obs_trace.event("engine.device_fence", dur=dur,
                        ordinal=meta.get("ordinal"), width=meta["width"],
                        slot_count=meta.get("slot_count"),
                        coalitions=meta["coalitions"],
                        interval=self._fence_interval)

    def _fence_next(self, pending) -> bool:
        """True when the NEXT batch ordinal is a fence sample and an
        in-flight batch must be drained first (so the fence times only
        its own batch). The prediction can go stale when a recovery
        path dispatches extra batches inside the drain — the worst case
        is one un-drained (slightly inflated) or one extra-drained
        sample, never a correctness issue."""
        return (pending is not None and self._fence_interval
                and devcost.should_fence(self._batch_ordinal + 1,
                                         self._fence_interval))

    def _retry_transient(self, op, site: str, ordinal: "int | None" = None):
        """Run `op` with bounded exponential backoff on transient runtime
        failures (`faults.is_transient`): up to MPLC_TPU_MAX_RETRIES
        retries. The per-coalition rng-fold streams make a re-dispatched
        batch bit-identical to the failed attempt, so a retry can never
        change v(S). OOM and non-transient errors propagate. `ordinal`
        (the 1-based batch number) rides the engine.retry event so trace
        tooling can flow-link a retry to the batch it recovered."""
        attempt = 0
        while True:
            try:
                return op()
            except Exception as e:
                if not faults.is_transient(e) or attempt >= self._max_retries:
                    raise
                attempt += 1
                self._backoff(site, attempt, e, ordinal)

    def _fetch_with_retry(self, fetch, meta):
        """Harvest with transient recovery: a failed result fetch
        re-dispatches the SAME batch (same rng streams — bit-identical)
        via `meta["redispatch"]` and fetches again, up to the retry
        budget. The fault plan's harvest boundary sits here. The
        re-dispatch runs INSIDE the try: during a correlated outage the
        re-dispatch itself fails transiently too, and that failure must
        consume a retry, not escape the ladder."""
        attempt = 0
        while True:
            try:
                if fetch is None:
                    fetch = meta["redispatch"]()
                self._faults.check("harvest", meta.get("ordinal", 0))
                return fetch()
            except Exception as e:
                if (not faults.is_transient(e)
                        or meta.get("redispatch") is None
                        or attempt >= self._max_retries):
                    raise
                attempt += 1
                self._backoff("harvest", attempt, e, meta.get("ordinal"))
                fetch = None  # re-dispatch on the next attempt

    def _backoff(self, site: str, attempt: int, err: BaseException,
                 ordinal: "int | None" = None) -> None:
        delay = min(self._retry_backoff * (2 ** (attempt - 1)),
                    constants.RETRY_BACKOFF_CAP_SEC)
        obs_metrics.counter("engine.retries").inc()
        obs_metrics.counter("engine.backoff_sec").inc(delay)
        obs_trace.event("engine.retry", site=site, attempt=attempt,
                        ordinal=ordinal, backoff_sec=delay,
                        error=str(err)[:200])
        logger.warning(
            "transient %s failure (attempt %d/%d, backing off %.2f s): %s",
            site, attempt, self._max_retries, delay, err)
        if delay:
            time.sleep(delay)

    def _degrade_cap(self, err: BaseException) -> None:
        """One rung down the OOM ladder: halve the per-device coalition
        cap (every later `_device_batch_cap` call sees it), or — past
        MPLC_TPU_MAX_CAP_HALVINGS rungs — flip the engine into the
        per-batch CPU path for everything still missing. Already-harvested
        v(S) values are kept either way: the memo cache makes the
        re-bucketing free."""
        self._cap_halvings += 1
        # the memoized memory snapshot described the PRE-fault device; the
        # autotuner must re-query after every degrade event (an OOM can
        # coincide with fragmentation or a shrunken bytes_limit, and the
        # CPU rung has entirely different memory) — stale-snapshot bug,
        # ISSUE 8 satellite
        self._hbm_bytes = None
        obs_metrics.counter("engine.cap_halvings").inc()
        if self._cap_halvings > self._max_cap_halvings:
            self._cpu_degraded = True
            obs_trace.event("engine.degrade", action="cpu_fallback",
                            halvings=self._cap_halvings, error=str(err)[:200])
            logger.warning(
                "device OOM after %d cap halvings — routing the remaining "
                "coalition batches through the per-batch CPU path (%s)",
                self._max_cap_halvings, err)
        else:
            obs_trace.event("engine.degrade", action="halve_cap",
                            halvings=self._cap_halvings, error=str(err)[:200])
            logger.warning(
                "device OOM — halving the per-device coalition cap (halving "
                "%d of %d) and re-bucketing the remaining subsets (%s)",
                self._cap_halvings, self._max_cap_halvings, err)

    def _ladder_exhausted(self, err: BaseException) -> "faults.LadderExhaustedError":
        """Build (and record) the classified terminal error for a 2-D
        sweep whose cap halvings ran out: the partner-sharded shard_map
        programs need the device mesh, so there is no CPU rung to take.
        The event lands in the resilience report row (ladder_exhausted),
        and the error is classified PERMANENT (`faults.is_transient` /
        `is_oom` both False) so the retry ladder can't loop on it and the
        sweep service quarantines only the owning tenant's job. Raise the
        returned error `from err` at the call site."""
        obs_metrics.counter("engine.ladder_exhausted").inc()
        obs_trace.event("engine.degrade", action="ladder_exhausted",
                        halvings=self._cap_halvings, error=str(err)[:200])
        # a terminal, PERMANENT failure is exactly what the crash flight
        # recorder exists for: dump the recent-span ring + metrics now,
        # while the dead batch's dispatch/degrade records are still in it
        from ..obs import flight as obs_flight
        postmortem = obs_flight.dump("ladder_exhausted", extra={
            "halvings": self._cap_halvings,
            "error": str(err)[:500]})
        return faults.LadderExhaustedError(
            f"device OOM persisted through {self._max_cap_halvings} "
            "cap-halvings and the 2-D partner-sharded mode has no CPU "
            "rung (shard_map programs need the device mesh) — the sweep "
            "cannot make progress at any cap. Remedies: lower "
            "MPLC_TPU_COALITIONS_PER_DEVICE or MPLC_TPU_PARTNER_SHARDS, "
            "shrink MPLC_TPU_EVAL_CHUNK, or "
            "run this scenario on the 1-D path (which degrades to CPU). "
            f"Last device error: {str(err)[:200]}"
            + (f" Postmortem flight record: {postmortem}"
               if postmortem else ""),
            halvings=self._cap_halvings, mode="2d",
            postmortem_path=postmortem)

    def _record_or_recover(self, prev, per_partner, slot_count, pipe) -> None:
        """`_record_group` plus the harvest-side OOM ladder: when FETCHING
        a batch's results exhausts device memory, the batch's coalitions
        re-run through `_run_batch` at the degraded cap (or the CPU path)
        instead of killing the sweep. Transient fetch failures were
        already retried inside `_record_group`; anything else propagates."""
        try:
            self._record_group(*prev, per_partner, slot_count)
        except Exception as e:
            if not faults.is_oom(e):
                raise
            self._degrade_cap(e)
            if self._cpu_degraded and getattr(pipe, "coal_devices", None):
                # no CPU path for the partner-sharded 2-D programs
                raise self._ladder_exhausted(e) from e
            if prev[3].get("ensemble"):
                # job-granular group: redo every subset with ANY replica
                # still missing (the re-run re-trains all K replicas —
                # deterministic streams make the overwrite a no-op)
                subs = list(dict.fromkeys(s for s, _ in prev[0]))
            else:
                subs = prev[0]
            redo = [s for s in subs if self._incomplete(s)]
            if redo:
                self._run_batch(redo, pipe, slot_count)

    def _run_batch(self, subsets: list[tuple], pipe,
                   slot_count: int | None = None) -> None:
        # NOTE: the dispatch/harvest recovery skeleton here (bucket-width
        # recompute on cap change, dispatch-OOM degrade-and-retry,
        # harvest-OOM rewind, batch-event emission) is deliberately
        # mirrored by ReconstructionEvaluator._run_batch
        # (contrib/reconstruct.py) for eval-only reconstruction batches —
        # ladder changes must land in both.
        # overlap is only possible when the pipe dispatches without host
        # decisions inside (no mid-run ES sync) — otherwise pipelining
        # degenerates to the sequential path and must not halve the cap
        overlap = self._pipeline_batches and pipe.dispatches_async
        is2d = bool(getattr(pipe, "coal_devices", None))
        # seed-ensemble sweeps run at JOB granularity: K replica rows per
        # subset ride the same buckets (the padding rows a single-seed
        # sweep wastes absorb them, so the dispatch count grows
        # sub-linearly in K — asserted via the engine.batches counter)
        K = self.seed_ensemble
        n_jobs = len(subsets) * K

        def bucket_width() -> int:
            # ONE bucket width for the whole call (the tail group pads up
            # to it rather than compiling its own smaller-width program) —
            # so a warm-up pass over min(len, n_dev*cap) subsets per size
            # compiles exactly the programs a full sweep executes.
            # Recomputed only when the OOM ladder moved, so fault-free runs
            # keep the single deterministic width per call.
            if is2d:
                n_dev = pipe.coal_devices      # 2-D mesh: coal axis only
                # each device holds only partners_count / part_shards
                # partner model copies — cap on the LOCAL count
                cap = self._device_batch_cap(pipe._local_partners, overlap)
                return _bucket_size(min(n_jobs, n_dev * cap), n_dev, cap)
            return self._planned_width(n_jobs, slot_count, pipe)

        b = bucket_width()
        # AOT program bank: serve this call's (slots, width) executables
        # from the bank (compiling foreground only if the background
        # prefetch hasn't reached them). A width change down the OOM
        # ladder drops back to the inline jit path — a banked bundle is
        # only valid for the exact width it was lowered at.
        exes = None
        if (self.program_bank is not None and not is2d
                and not self._cpu_degraded):
            exes = self.program_bank.acquire(pipe, slot_count, b)
        halvings_seen = self._cap_halvings
        per_partner = (self._epoch_samples_single
                       if pipe is self.single_pipe
                       else self._epoch_samples_multi)
        # partner passes executed per coalition-minibatch on this pipe: the
        # intensity accounting behind engine.partner_passes (slot execution
        # trains <= slot_count passes where the masked path trains P)
        passes_per_mb = (1 if pipe is self.single_pipe
                         else slot_count if slot_count is not None
                         else self.partners_count)

        # Whole-call host prep, once per bucket instead of once per batch:
        # one NumPy scatter builds every coalition row and every rng fold
        # word; per-batch work below shrinks to an index select + one
        # vmapped fold — the host-side share of the dispatch gap.
        with obs_trace.span("engine.prep", coalitions=n_jobs,
                            width=b, slot_count=slot_count):
            # rng streams are keyed by the EFFECTIVE membership (minus
            # forever-dropped partners), the identity for fault-free runs.
            # The single trainer additionally takes the effective mask (its
            # argmax must find the lone SURVIVOR; there is no aggregation
            # to renormalize) — the multi/slot trainers keep the full
            # membership and mask the dropped slot out in-trainer.
            eff = ([self._effective_subset(s) for s in subsets]
                   if self._forever_dropped else subsets)
            coal_all = self._coalition_arrays(
                eff if pipe is self.single_pipe else subsets, slot_count)
            words, n_words = self._rng_fold_words(eff)
            if K > 1:
                sub_idx = np.repeat(np.arange(len(subsets)), K)
                coal_all = coal_all[sub_idx]
                words = words[sub_idx]
                n_words = n_words[sub_idx]
                seed_rows = self._ensemble_rows[
                    np.tile(np.arange(K), len(subsets))]
                jobs = [(s, j) for s in subsets for j in range(K)]
            else:
                seed_rows = None
                jobs = subsets

        pending = None  # (group, fetch-thunk, remaining-after, meta) in flight
        try:
            i = 0
            while i < n_jobs:
                if self._cpu_degraded and not is2d:
                    # OOM ladder exhausted: drain the in-flight batch
                    # (its own fetch may OOM too — the recover path routes
                    # it through the CPU rung), then run everything left
                    # one small CPU batch at a time
                    if pending is not None:
                        prev, pending = pending, None
                        self._record_or_recover(prev, per_partner,
                                                slot_count, pipe)
                    self._run_groups_cpu(jobs, i, coal_all, words, n_words,
                                         pipe, slot_count, per_partner,
                                         passes_per_mb, seed_rows=seed_rows)
                    return
                if self._fence_next(pending):
                    # a fenced ordinal must time ONLY its own batch:
                    # drain the in-flight one first (values unaffected —
                    # only the harvest point moves)
                    prev, pending = pending, None
                    self._record_or_recover(prev, per_partner,
                                            slot_count, pipe)
                if self._cap_halvings != halvings_seen:
                    # an OOM (here or inside a harvest recovery) stepped the
                    # ladder down: re-bucket the REMAINING subsets through
                    # the ordinary width machinery at the degraded cap.
                    # The banked executables were lowered for the old
                    # width — drop them (the jit path compiles the
                    # degraded width inline)
                    halvings_seen = self._cap_halvings
                    b = bucket_width()
                    exes = None
                group = jobs[i:i + b]
                # padding rows replicate the batch's first coalition (the
                # same convention the old per-batch fill loop used)
                sel = np.full(b, i, np.intp)
                sel[:len(group)] = np.arange(i, i + len(group))
                self._batch_ordinal += 1
                attrs = {"width": b, "slot_count": slot_count,
                         "coalitions": len(group), "padding": b - len(group)}
                meta = {**attrs, "t0": time.perf_counter(),
                        "passes_per_mb": passes_per_mb,
                        "mb_count": pipe.trainer.cfg.minibatch_count,
                        "ordinal": self._batch_ordinal,
                        "ensemble": K > 1}
                # XLA-modeled cost of one bundle execution (init+run+fin
                # — exactly this batch), stamped from the banked
                # executables; inline-jit batches carry no cost and the
                # report falls back to the analytic proxy
                cost = (exes.get("cost") if exes else None) or {}
                if cost.get("flops"):
                    meta["flops"] = cost["flops"]
                    meta["bytes_accessed"] = cost.get("bytes_accessed")

                def dispatch(sel=sel, attrs=attrs,
                             ordinal=self._batch_ordinal, exes=exes):
                    # every device input is re-materialized from the host
                    # arrays on EVERY invocation — a retry of a donating
                    # dispatch must never reuse a buffer the failed
                    # attempt already donated (the donation/retry rule,
                    # doc/documentation.md "Program bank & donation")
                    with obs_trace.span("engine.dispatch", **attrs):
                        self._faults.check("dispatch", ordinal)
                        rngs = self._batch_rngs(words, n_words, sel,
                                                seed_rows)
                        coal = jnp.asarray(coal_all[sel])
                        if getattr(pipe, "batch_sharding", None) is not None:
                            coal = jax.device_put(coal, pipe.batch_sharding)
                            rngs = jax.device_put(rngs, pipe.rng_sharding)
                        elif self._sharding is not None:
                            coal = jax.device_put(
                                coal, self._sharding.batch_sharding)
                            rngs = jax.device_put(
                                rngs, self._sharding.batch_sharding)
                        if exes is not None:
                            return pipe.scores_async(
                                coal, rngs, self.stacked, self.val,
                                self.test, self._coalition_rng(()),
                                exes=exes)
                        # no exes kwarg on the bank-less call: test
                        # doubles stub scores_async with the historical
                        # signature
                        return pipe.scores_async(coal, rngs, self.stacked,
                                                 self.val, self.test,
                                                 self._coalition_rng(()))

                meta["redispatch"] = dispatch
                try:
                    fetch = self._retry_transient(
                        dispatch, "dispatch", meta["ordinal"])
                except Exception as e:
                    if not faults.is_oom(e):
                        raise
                    # RESOURCE_EXHAUSTED at dispatch: step the ladder down
                    # and retry THIS group (i unchanged) at the degraded
                    # width. The finished in-flight batch is preserved
                    # FIRST — and with async dispatch an OOM often surfaces
                    # at ITS fetch instead, so the drain goes through the
                    # recover path, not a bare harvest.
                    if pending is not None:
                        prev, pending = pending, None
                        self._record_or_recover(prev, per_partner,
                                                slot_count, pipe)
                    self._degrade_cap(e)
                    if self._cpu_degraded and is2d:
                        # 2-D takes the halving rungs but has no CPU rung:
                        # shard_map programs need the mesh
                        raise self._ladder_exhausted(e) from e
                    continue
                self._maybe_fence(fetch, meta)
                i += len(group)
                if overlap:
                    # harvest the PREVIOUS batch only after this one is in
                    # the device queue: the device crosses batch boundaries
                    # with zero idle while the host stores/saves/reports.
                    # Clear `pending` BEFORE harvesting: if the harvest
                    # itself raises, the finally below must not record the
                    # same batch a second time (double-counting the call
                    # and throughput bookkeeping).
                    if pending is not None:
                        prev, pending = pending, None
                        self._record_or_recover(prev, per_partner,
                                                slot_count, pipe)
                    pending = (group, fetch, n_jobs - i, meta)
                else:
                    self._record_or_recover(
                        (group, fetch, n_jobs - i, meta),
                        per_partner, slot_count, pipe)
            if pending is not None:
                # normal-exit drain: the last in-flight batch still gets
                # the harvest-side OOM ladder (the exception-unwind drain
                # below must preserve-and-propagate instead)
                prev, pending = pending, None
                self._record_or_recover(prev, per_partner, slot_count, pipe)
        finally:
            if pending is not None:
                # reached only while unwinding an exception: preserve the
                # finished in-flight batch (store + autosave) before the
                # unwind continues. A harvest that itself raised cleared
                # `pending` first, so it is never retried here.
                prev, pending = pending, None
                self._record_group(*prev, per_partner, slot_count)

    def _run_groups_cpu(self, jobs, start, coal_all, words, n_words,
                        pipe, slot_count, per_partner, passes_per_mb,
                        seed_rows=None) -> None:
        """Terminal rung of the OOM ladder: train the remaining groups one
        small batch at a time on the host CPU backend instead of
        abandoning the run (bench's process-level fallback restarts the
        whole workload at reduced scale; here everything already harvested
        is kept and only the tail pays CPU speed). Row-independent vmapped
        training makes the CPU values bit-identical to the device path's —
        equality-tested under injected faults. `jobs` are subsets, or
        (subset, replica) pairs under a seed ensemble — the caller's
        job-expanded `coal_all`/`words`/`seed_rows` arrays line up."""
        cpu_dev = jax.local_devices(backend="cpu")[0]
        if self._cpu_data is None:
            self._cpu_data = tuple(
                jax.tree_util.tree_map(lambda t: jax.device_put(t, cpu_dev), d)
                for d in (self.stacked, self.val, self.test))
        stacked, val, test = self._cpu_data
        cap = self._device_batch_cap(slot_count, False)
        b = _bucket_size(min(len(jobs) - start, cap), 1, cap)
        i = start
        while i < len(jobs):
            group = jobs[i:i + b]
            sel = np.full(b, i, np.intp)
            sel[:len(group)] = np.arange(i, i + len(group))
            i += len(group)
            self._batch_ordinal += 1
            attrs = {"width": b, "slot_count": slot_count,
                     "coalitions": len(group), "padding": b - len(group),
                     "degraded": "cpu"}
            meta = {**attrs, "t0": time.perf_counter(),
                    "passes_per_mb": passes_per_mb,
                    "mb_count": pipe.trainer.cfg.minibatch_count,
                    "ordinal": self._batch_ordinal,
                    "ensemble": seed_rows is not None}

            def dispatch(sel=sel, attrs=attrs, ordinal=self._batch_ordinal):
                with obs_trace.span("engine.dispatch", **attrs):
                    self._faults.check("dispatch", ordinal)
                    rngs = jax.device_put(
                        self._batch_rngs(words, n_words, sel, seed_rows),
                        cpu_dev)
                    coal = jax.device_put(jnp.asarray(coal_all[sel]), cpu_dev)
                    with jax.default_device(cpu_dev):
                        return pipe.scores_async(coal, rngs, stacked, val,
                                                 test, self._coalition_rng(()))

            meta["redispatch"] = dispatch
            fetch = self._retry_transient(
                dispatch, "dispatch", meta["ordinal"])
            # NO fence on the CPU rung: a CPU-rung sample is orders of
            # magnitude slower than a device one, and a mixed run's
            # fenced extrapolation (and per-tenant billing) would blend
            # the two rates. The rung is synchronous anyway — its host
            # span IS its compute time, and the meter bills it in its
            # own degraded class (obs/devcost.py).
            self._record_group(group, fetch, len(jobs) - i, meta,
                               per_partner, slot_count)

    def _record_group(self, group, fetch, remaining, meta, per_partner,
                      slot_count) -> None:
        """Per-batch bookkeeping shared by _run_batch and
        _run_singles_sliced: fetch results, memoize scores, account
        epochs/samples, telemetry, autosave, report progress."""
        with obs_trace.span("engine.harvest", width=meta["width"],
                            slot_count=slot_count,
                            coalitions=meta["coalitions"]):
            accs, epochs = self._fetch_with_retry(fetch, meta)
        batch_epochs = 0
        batch_samples = 0
        ensemble = bool(meta.get("ensemble"))
        # numeric-truth context for the ledger notes `_store` writes for
        # this batch's values (restored after the loop: stores outside a
        # batch must not inherit the last batch's float path)
        self._ledger_ctx = {"slot_count": slot_count,
                            "degraded": meta.get("degraded")}
        for item, acc, ep in zip(group, accs[:len(group)],
                                 epochs[:len(group)]):
            if ensemble:
                # job-granular row: (subset, replica). Replica 0 carries
                # the base-seed stream and IS the point estimate — the
                # extra replicas only feed charac_fct_samples. The
                # already-stored guard matters on the OOM-recovery redo
                # path (and ensemble resume): a subset whose replica rows
                # straddled batches can re-run ALL its replicas, and a
                # second _store of the (bit-identical) replica-0 value
                # would inflate first_charac_fct_calls_count.
                s, rep = item
                self._store_sample(s, int(rep), float(acc))
                if rep == 0 and s not in self.charac_fct_values:
                    self._store(s, float(acc))
            else:
                s = item
                self._store(s, float(acc))
            batch_epochs += int(ep)
            # throughput accounting over partners that actually trained:
            # forever-dropped members consumed zero samples
            batch_samples += int(ep) * int(
                sum(int(per_partner[i])
                    for i in self._effective_subset(s)))
        self._ledger_ctx = {}
        self.epochs_trained += batch_epochs
        self.samples_trained += batch_samples
        obs_metrics.counter("engine.batches").inc()
        if (self._numerics_audit and meta.get("device_sec") is not None
                and not ensemble and group
                and len(self.numerics_audits) < 4):
            # fence-sampled reduction audit (obs/numerics.py): audit the
            # fenced batch's first coalition through a separate capture
            # run — never the dispatched programs, so v(S) is untouched.
            # Bounded to 4 audits per engine: each costs one training.
            s0 = group[0]
            key = tuple(s0)
            if key not in self._audited_subsets:
                self._audited_subsets.add(key)
                res = obs_numerics.audit_coalition(self, s0)
                if res is not None:
                    self.numerics_audits.append(res)
        # partner passes executed on device for this batch, INCLUDING the
        # padded/inactive slot or mask rows (what the hardware ran, not just
        # the useful share): epochs x minibatches x passes-per-minibatch,
        # both captured at dispatch from the pipe that actually ran.
        batch_passes = (batch_epochs * meta.get("mb_count", 1)
                        * meta.get("passes_per_mb", 1))
        # per-batch telemetry: dur spans dispatch-start -> harvest-end (under
        # batch pipelining consecutive batches overlap, so these durations
        # sum to more than wall-clock — a utilization view). All host-side;
        # the only device sync is the harvest fetch that already happened.
        extra = {}
        if meta.get("degraded"):
            # earned on the OOM ladder's CPU rung, not the device path —
            # the sweep report's resilience row separates these out
            extra["degraded"] = meta["degraded"]
            obs_metrics.counter("engine.cpu_degraded_batches").inc()
            obs_metrics.counter("engine.cpu_degraded_coalitions").inc(
                len(group))
        if meta.get("device_sec") is not None:
            # this batch ran fenced: its measured device-step seconds
            # ride the event into the report's device/roofline rows
            extra["fenced"] = True
            extra["device_sec"] = meta["device_sec"]
        if meta.get("flops"):
            extra["flops"] = meta["flops"]
            if meta.get("bytes_accessed"):
                extra["bytes_accessed"] = meta["bytes_accessed"]
        dur = time.perf_counter() - meta["t0"]
        obs_trace.event(
            "engine.batch", dur=dur,
            width=meta["width"], slot_count=slot_count,
            ordinal=meta.get("ordinal"),
            coalitions=meta["coalitions"], padding=meta["padding"],
            epochs=batch_epochs, samples=batch_samples,
            partner_passes=batch_passes, **extra)
        if self.device_meter is not None:
            self.device_meter.note(
                len(group), span_sec=dur,
                device_sec=meta.get("device_sec"),
                flops=meta.get("flops"),
                bytes_accessed=meta.get("bytes_accessed"),
                degraded=bool(meta.get("degraded")))
        obs_metrics.counter("engine.epochs_trained").inc(batch_epochs)
        obs_metrics.counter("engine.samples_trained").inc(batch_samples)
        obs_metrics.counter("engine.partner_passes").inc(batch_passes)
        obs_metrics.histogram("engine.pad_waste_fraction").observe(
            meta["padding"] / meta["width"])
        obs_metrics.sample_device_memory()
        if self.autosave_path is not None:
            self.save_cache(self.autosave_path)
        if self.progress is not None:
            self.progress(len(group), remaining, slot_count)

    def _run_singles_sliced(self, singles: list[tuple]) -> None:
        """2-D mode singletons: a 1-partner coalition touches only its own
        partner's rows, so slice a [b, Nmax, ...] batch of just the needed
        partners instead of replicating the whole stacked axis per device
        (which the 2-D mode exists to avoid). The single trainer's rng
        streams are per-coalition, not partner-row-indexed, so the slice
        trains identically; the mask is the identity (coalition j owns
        slice row j).

        OOM recovery here is by RECURSION rather than _run_batch's
        in-loop re-bucketing: the batch width is baked into the identity
        mask and the per-width singles pipe, so after a cap halving the
        cleanest re-bucket is a fresh call over whatever is still missing
        (the memo cache keeps everything harvested). Like the rest of the
        2-D mode there is no CPU rung — the ladder ends when the halvings
        run out."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        n_dev = self._pipe2d.coal_devices
        pipe_overlap = (self._pipeline_batches
                        and self.single_pipe.dispatches_async)
        cap = self._device_batch_cap(1, pipe_overlap)
        b = _bucket_size(min(len(singles), n_dev * cap), n_dev, cap)
        coal_sh = NamedSharding(self._pipe2d.mesh, P("coal"))
        rep_sh = NamedSharding(self._pipe2d.mesh, P())
        pipe = self._singles_pipe(b)
        overlap = self._pipeline_batches and pipe.dispatches_async
        # NOTE: the bucket/pad loop below mirrors _run_batch (which can't
        # be reused directly: the data tensor varies per batch here); the
        # per-batch bookkeeping is shared via _record_group. Keep the two
        # pad loops in step when changing either. The per-batch host-side
        # data-slice rebuild is exactly the dispatch gap batch pipelining
        # hides, so the overlap applies here too (same pending/drain
        # protocol as _run_batch).
        with obs_trace.span("engine.prep", coalitions=len(singles),
                            width=b, slot_count=None):
            words, n_words = self._rng_fold_words(singles)
            ids_all = np.fromiter((s[0] for s in singles), np.int32,
                                  len(singles))
            # the identity coalition mask is batch-invariant: build and
            # place it once per call, not once per batch
            eye = jax.device_put(jnp.eye(b, dtype=jnp.float32), coal_sh)

        def recover_oom(err) -> None:
            """Step the ladder down and re-run whatever is still missing
            through a fresh call at the degraded cap."""
            self._degrade_cap(err)
            if self._cpu_degraded:
                # 2-D singles ride the halving rungs only
                raise self._ladder_exhausted(err) from err
            redo = [s for s in singles if s not in self.charac_fct_values]
            if redo:
                self._run_singles_sliced(redo)

        def harvest_prev(prev) -> bool:
            """Harvest a drained batch; on fetch-OOM recover via
            recursion and report True (the caller must stop: everything
            still missing — including any batch it has in flight — was
            completed by the recursive call)."""
            try:
                self._record_group(*prev, self._epoch_samples_single, None)
                return False
            except Exception as e:
                if not faults.is_oom(e):
                    raise
                recover_oom(e)
                return True

        pending = None
        try:
            i = 0
            while i < len(singles):
                if self._fence_next(pending):
                    # same pre-drain rule as _run_batch: a fenced
                    # ordinal times only its own batch
                    prev, pending = pending, None
                    if harvest_prev(prev):
                        return
                group = singles[i:i + b]
                sel = np.full(b, i, np.intp)
                sel[:len(group)] = np.arange(i, i + len(group))
                i += len(group)
                self._batch_ordinal += 1
                attrs = {"width": b, "slot_count": None,
                         "coalitions": len(group), "padding": b - len(group)}
                meta = {**attrs, "t0": time.perf_counter(),
                        "passes_per_mb": 1,
                        "mb_count": pipe.trainer.cfg.minibatch_count,
                        "ordinal": self._batch_ordinal}

                def dispatch(sel=sel, attrs=attrs,
                             ordinal=self._batch_ordinal):
                    with obs_trace.span("engine.dispatch", **attrs):
                        self._faults.check("dispatch", ordinal)
                        ids = ids_all[sel]
                        sliced = StackedPartners(
                            x=jax.device_put(jnp.take(self.stacked.x, ids, axis=0), rep_sh),
                            y=jax.device_put(jnp.take(self.stacked.y, ids, axis=0), rep_sh),
                            mask=jax.device_put(jnp.take(self.stacked.mask, ids, axis=0), rep_sh),
                            sizes=jax.device_put(jnp.take(self.stacked.sizes, ids, axis=0), rep_sh))
                        rngs = jax.device_put(
                            self._batch_rngs(words, n_words, sel), coal_sh)
                        return pipe.scores_async(eye, rngs, sliced, self.val,
                                                 self.test,
                                                 self._coalition_rng(()))

                meta["redispatch"] = dispatch
                try:
                    fetch = self._retry_transient(
                        dispatch, "dispatch", meta["ordinal"])
                except Exception as e:
                    if not faults.is_oom(e):
                        raise
                    if pending is not None:
                        prev, pending = pending, None
                        if harvest_prev(prev):
                            return
                    recover_oom(e)
                    return
                self._maybe_fence(fetch, meta)
                if overlap:
                    if pending is not None:
                        prev, pending = pending, None
                        if harvest_prev(prev):
                            # the recursion completed every missing single;
                            # the current in-flight fetch is abandoned (its
                            # coalitions were retrained at the lower cap)
                            return
                    pending = (group, fetch, len(singles) - i, meta)
                else:
                    if harvest_prev((group, fetch, len(singles) - i, meta)):
                        return
            if pending is not None:
                # normal-exit drain, with the harvest-side OOM rung
                prev, pending = pending, None
                harvest_prev(prev)
        finally:
            if pending is not None:
                # same drain contract as _run_batch: reached only while
                # unwinding an exception — harvest-on-exit, never
                # re-harvest a batch whose fetch already raised
                prev, pending = pending, None
                self._record_group(*prev, self._epoch_samples_single, None)

    def _store(self, subset: tuple, value: float) -> None:
        self.charac_fct_values[subset] = value
        self.first_charac_fct_calls_count += 1
        if self.numerics_ledger is not None:
            # value provenance: the exact harvested bits + the float path
            # that produced them (batch slot width, OOM rungs taken, CPU
            # degradation) — `_ledger_ctx` is stamped per batch by
            # _record_group; stores outside a batch (null coalitions,
            # journal-recovered seeds) carry the defaults
            ctx = self._ledger_ctx
            self.numerics_ledger.record(
                subset, value, source="exact",
                slot_width=ctx.get("slot_count"),
                cap_halvings=self._cap_halvings,
                degraded=bool(ctx.get("degraded")))
        # marginal-increment bookkeeping (reference contributivity.py:116-134)
        sset = set(subset)
        for i in range(self.partners_count):
            if i in sset:
                without = tuple(sorted(sset - {i}))
                if without in self.charac_fct_values:
                    self.increments_values[i][without] = \
                        value - self.charac_fct_values[without]
            else:
                with_i = tuple(sorted(sset | {i}))
                if with_i in self.charac_fct_values:
                    self.increments_values[i][subset] = \
                        self.charac_fct_values[with_i] - value

    # ------------------------------------------------------------------

    def evaluate(self, subsets) -> np.ndarray:
        """Batched memoized v(S) for a list of subsets (any iterables of
        partner indices). Returns values in input order."""
        keys = [tuple(sorted(int(i) for i in s)) for s in subsets]
        unique = dict.fromkeys(keys)  # stable-unique
        missing = [k for k in unique if self._incomplete(k)]
        n_requested_missing = len(missing)
        if self._forever_dropped:
            # a coalition whose EVERY member is dropped from epoch 1 never
            # produces a model: its value is v(empty) = 0 by definition —
            # stored without training (and the deviation that makes the
            # dropped partner an exact null player, so the faulty game's
            # Shapley values equal the partner-excluded game's)
            live = []
            for k in missing:
                if all(i in self._forever_dropped for i in k):
                    self._store(k, 0.0)
                    if self.seed_ensemble > 1:
                        self.charac_fct_samples[k] = np.zeros(
                            self.seed_ensemble)
                else:
                    live.append(k)
            missing = live
            # null coalitions are neither memo hits (nothing was cached)
            # nor misses (nothing trains) — their own bucket keeps the
            # memo hit rate an honest before/after for perf PRs
            obs_metrics.counter("engine.null_coalitions").inc(
                n_requested_missing - len(missing))
        # memo accounting over unique keys: intra-call duplicates don't
        # inflate the hit rate
        method = _memo_counters(len(unique) - n_requested_missing,
                                len(missing))
        obs_metrics.counter("engine.coalitions_evaluated").inc(len(missing))
        with obs_trace.span("engine.evaluate", requested=len(unique),
                            missing=len(missing), method=method):
            if self._forever_dropped:
                # route by EFFECTIVE size: a coalition reduced to one
                # survivor is a single-partner training (the reference's
                # SinglePartnerLearning routing applies to who actually
                # trains, not to who enrolled)
                lens = {k: len(self._effective_subset(k)) for k in missing}
            else:
                lens = {k: len(k) for k in missing}
            singles = [k for k in missing if lens[k] == 1]
            multis = [k for k in missing if lens[k] > 1]
            if missing and self.program_bank is not None:
                # compile/execute overlap: the background worker AOT-
                # compiles bucket k+1's programs while bucket k
                # executes; only the first bucket's compile is serial
                self.program_bank.prefetch(
                    self._bucket_plan(singles, multis))
            if singles:
                if self._pipe2d is not None:
                    self._run_singles_sliced(singles)
                else:
                    self._run_batch(singles, self.single_pipe)
            if multis:
                if self._pipe2d is not None:
                    self._run_batch(multis, self._pipe2d)
                elif self._use_slots:
                    for slot_count, group in self._slot_buckets(multis):
                        self._run_batch(group, self._slot_pipe(slot_count),
                                        slot_count=slot_count)
                else:
                    self._run_batch(multis, self.multi_pipe)
            if missing:
                # one HBM snapshot per evaluate() call with device work,
                # emitted AFTER the call's batches so the high-water
                # gauge (sampled per harvest, refreshed here) includes
                # the sweep just run — feeds the report's hbm row
                slot_hint = (max((self._slot_width(lens[k]) for k in multis),
                                 default=None)
                             if multis and self._use_slots
                             and self._pipe2d is None else None)
                obs_metrics.sample_device_memory()
                obs_trace.event("engine.hbm", **self._hbm_attrs(slot_hint))
            if missing and self.numerics_ledger is not None:
                # persist the value-provenance ledger once per evaluate()
                # call that did device work (atomic, never raises)
                self.numerics_ledger.save()
        if self._cache_needs_upgrade and self.autosave_path is not None:
            # legacy-cache convergence: even a fully-memoized sweep (no
            # batch ran, so no per-batch autosave fired) rewrites the
            # loaded no-checksum file ITSELF in the integrity format —
            # autosaves pointed at a different path don't discharge the
            # obligation to the legacy file
            self.save_cache(self._legacy_cache_path)
        return np.array([self.charac_fct_values[k] for k in keys])

    def _slot_width(self, k: int) -> int:
        """Slot-program width a size-k coalition runs at under the active
        bucketing mode (exact / merge / pow2). bench._warm_engine mirrors
        the sweep's program set through this, so keep it the single source
        of the width rule."""
        if self._slot_pow2:
            return min(1 << (k - 1).bit_length(), self.partners_count)
        if self._slot_merge:
            # adjacent sizes pair up: even k rides size k+1's program, so
            # P-1 per-size programs become ceil((P-1)/2) and the even
            # size's coalitions fill batch rows the odd size would have
            # padded
            return min(k + (k % 2 == 0), self.partners_count)
        return k

    def _slot_buckets(self, multis: list[tuple]) -> list[tuple[int, list[tuple]]]:
        """Group coalitions by slot width.

        Default (`merge`): adjacent coalition sizes share one width — size
        k and k+1 (even k merging up) run as ONE batch stream at width
        k+1, so a 10-partner sweep compiles 5 slot programs instead of 9
        and the smaller size's tail fills padding rows of the larger
        size's batches. Costs at most one padded slot of compute per
        merged coalition. MPLC_TPU_SLOT_MERGE=0 restores the tight
        per-size grouping (`exact`: zero padded slot compute, most
        programs — fastest steady-state with a warm compile cache).
        MPLC_TPU_SLOT_POW2=1 rounds sizes UP to the next power of two
        (capped at the partner count): ~log2(P) programs, the cheapest
        cold start, the most padded compute. All three produce identical
        v(S): the trainer's -1 = unused-slot convention makes mixed sizes
        inside one bucket exact, not approximate (active mask zeroes the
        aggregation weight; rng keyed by global partner id —
        equality-tested across modes)."""
        by_width: dict[int, list[tuple]] = {}
        for s in multis:
            by_width.setdefault(self._slot_width(len(s)), []).append(s)
        return [(w, by_width[w]) for w in sorted(by_width)]

    def not_twice_characteristic(self, subset) -> float:
        """Reference-API single-subset entry (contributivity.py:92-136)."""
        return float(self.evaluate([np.atleast_1d(np.asarray(subset, int))])[0])

    # ------------------------------------------------------------------
    # checkpoint / resume: long Shapley sweeps are resumable because the
    # characteristic function is fully described by its memo cache. The
    # reference checkpoints only final model weights
    # (multi_partner_learning.py:117-128); persisting the coalition cache
    # is the improvement its structure invites (SURVEY.md §5).
    # ------------------------------------------------------------------

    def _data_digest(self) -> str:
        """Content hash of the actual training/eval device arrays. Subsumes
        every upstream data decision — split type, proportions, corruption,
        dataset_proportion, seeds — because any of them changes the bytes.
        x arrays are sampled with a stride to keep hashing cheap; labels and
        masks are hashed in full (corruption only touches y)."""
        if getattr(self, "_digest_cache", None) is not None:
            return self._digest_cache
        import hashlib
        h = hashlib.sha256()

        def add(arr, stride_cap_bytes=1 << 22):
            a = np.ascontiguousarray(np.asarray(arr))
            h.update(str(a.shape).encode())
            # stride over FLAT elements so sampling is uniform across the
            # whole array (striding axis 0 would only ever hash partner 0)
            flat = a.reshape(-1)
            stride = max(1, flat.nbytes // stride_cap_bytes)
            h.update(np.ascontiguousarray(flat[::stride]).tobytes())

        add(self.stacked.x)
        add(self.stacked.y, stride_cap_bytes=1 << 30)   # full labels
        add(self.stacked.sizes, stride_cap_bytes=1 << 30)
        add(self.val.x)
        add(self.val.y, stride_cap_bytes=1 << 30)
        add(self.test.x)
        add(self.test.y, stride_cap_bytes=1 << 30)
        self._digest_cache = h.hexdigest()[:16]
        return self._digest_cache

    def _fingerprint(self) -> dict:
        """Everything v(S) depends on: a cache from a run with a different
        value for any of these would describe a different game."""
        cfg = self.multi_pipe.trainer.cfg
        sc = self.scenario
        return {
            "partners_count": self.partners_count,
            "seed": self.seed,
            "dataset": getattr(sc.dataset, "name", "?"),
            "model": self.model.name,
            "approach": cfg.approach,
            "aggregator": cfg.aggregator,
            "epoch_count": cfg.epoch_count,
            "minibatch_count": cfg.minibatch_count,
            "gradient_updates_per_pass": cfg.gradient_updates_per_pass,
            # the wide-step deviation changes every trajectory at mult > 1:
            # a cache built under one mult describes a different game
            "step_width_mult": cfg.step_width_mult,
            # deterministic-reduce pins a DIFFERENT (fixed) reduction
            # order, so its v(S) trajectories are a different game from
            # the default order-sensitive reduction's
            "deterministic_reduce": bool(cfg.deterministic_reduce),
            # a partner-fault plan changes v(S) itself (dropped/straggling
            # partners train differently), so any two distinct plans
            # describe different games; the ensemble width changes what a
            # cache's sample rows mean
            "partner_fault_plan": faults.normalized_plan_repr(
                self._partner_faults),
            "seed_ensemble": self.seed_ensemble,
            "compute_dtype": cfg.compute_dtype,
            # non-fp32 precision modes are documented deviations that
            # change v(S) (bf16 compute / bf16 reconstruction
            # accumulate): a stale fp32 cache must refuse to serve a
            # bf16 game and vice versa
            "precision": cfg.precision,
            "split": [str(getattr(sc, "samples_split_type", "?")),
                      str(getattr(sc, "samples_split_description", "?"))],
            "corruption": [str(c) for c in
                           getattr(sc, "corrupted_datasets",
                                   ["not_corrupted"] * self.partners_count)],
            "partner_sizes": [int(s) for s in
                              np.asarray(self.stacked.sizes).tolist()],
            "data_digest": self._data_digest(),
        }

    def save_cache(self, path) -> None:
        """Persist v(S) memo + increment bookkeeping as JSON, durably.

        Three layers make an autosave survive hard kills: the payload
        carries a sha256 checksum (`load_cache` verifies it, so corrupted
        bytes can never silently poison v(S)); the temp file is flushed
        and fsync'd BEFORE the atomic `os.replace` — without that fsync a
        power loss can promote an empty or partial temp file over a good
        cache despite the rename itself being atomic; and the directory
        entry is fsync'd after the rename so the promotion is durable."""
        import hashlib
        import json
        import os as _os
        payload = {
            "fingerprint": self._fingerprint(),
            "first_charac_fct_calls_count": self.first_charac_fct_calls_count,
            "charac_fct_values": [[list(k), v]
                                  for k, v in self.charac_fct_values.items()],
            "increments_values": [[[list(k), v] for k, v in d.items()]
                                  for d in self.increments_values],
        }
        samples = getattr(self, "charac_fct_samples", None)
        if samples:
            # seed-ensemble replica rows (NaN = not yet harvested; resume
            # re-trains any subset with an incomplete row)
            payload["charac_fct_samples"] = [
                [list(k), [float(v) for v in arr]]
                for k, arr in samples.items()]
        # checksum over the payload's own serialization: verification
        # re-derives the same bytes from the parsed document (json dict
        # order and float repr both round-trip), so no second file or
        # length prefix is needed. The checksum field is spliced into the
        # already-serialized body — this runs after EVERY autosaved batch,
        # and a second full json.dumps of a 2^P-entry memo would double
        # the harvest path's host cost.
        body = json.dumps(payload)
        digest = hashlib.sha256(body.encode()).hexdigest()
        record_text = '{"payload_sha256": "%s", %s' % (digest, body[1:])
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(record_text)
            f.flush()
            _os.fsync(f.fileno())
        _os.replace(tmp, path)
        try:
            dfd = _os.open(_os.path.dirname(_os.path.abspath(str(path))),
                           _os.O_RDONLY)
            try:
                _os.fsync(dfd)
            finally:
                _os.close(dfd)
        except OSError:
            pass  # platforms/filesystems without directory fsync
        # every save emits the checksummed format — but the upgrade
        # obligation is to the FILE the legacy cache was loaded from, so
        # the flag clears only when that path was the one rewritten (an
        # autosave pointed elsewhere must not strand the legacy file
        # checksum-less while claiming it converged)
        if str(path) == getattr(self, "_legacy_cache_path", str(path)):
            self._cache_needs_upgrade = False

    def load_cache(self, path) -> None:
        """Restore a saved cache.

        Integrity failures — truncated or corrupt JSON, checksum
        mismatch, missing payload keys — raise `CacheIntegrityError`, so
        resume paths can quarantine the file and start cold
        (scenario.py). A VALID cache whose scenario differs in ANY
        v(S)-relevant way still raises a plain ValueError: that cache
        describes a different game. Caches saved before the checksum
        existed (no `payload_sha256` field) load unverified."""
        import hashlib
        import json
        try:
            with open(path) as f:
                payload = json.load(f)
            if not isinstance(payload, dict):
                raise ValueError(
                    f"top-level JSON is {type(payload).__name__}, not an object")
        except ValueError as e:
            raise CacheIntegrityError(
                f"coalition cache {path} is corrupt or truncated: {e}") from e
        expected = payload.pop("payload_sha256", None)
        if expected is not None:
            actual = hashlib.sha256(
                json.dumps(payload).encode()).hexdigest()
            if actual != expected:
                raise CacheIntegrityError(
                    f"coalition cache {path} failed its checksum (stored "
                    f"{expected[:12]}…, recomputed {actual[:12]}…): the "
                    "file was corrupted after it was written")
        else:
            # legacy pre-checksum cache: loads unverified — corruption in
            # it is UNDETECTABLE, which is exactly what the integrity
            # format exists to rule out. Warn once per process, and flag
            # the engine so the next autosave rewrites the file in the
            # checksummed format: every on-disk cache converges to the
            # integrity discipline without an explicit migration step.
            import warnings
            global _legacy_cache_warned
            if not _legacy_cache_warned:
                _legacy_cache_warned = True
                warnings.warn(
                    f"coalition cache {path} predates the checksum format "
                    "and loads UNVERIFIED (corruption in it cannot be "
                    "detected); it will be rewritten with a checksum on "
                    "the next autosave", DeprecationWarning, stacklevel=2)
            self._cache_needs_upgrade = True
            self._legacy_cache_path = str(path)
        missing = {"fingerprint", "first_charac_fct_calls_count",
                   "charac_fct_values", "increments_values"} - payload.keys()
        if missing:
            raise CacheIntegrityError(
                f"coalition cache {path} is missing keys {sorted(missing)}")
        theirs = payload.get("fingerprint", {})
        # caches saved before the wide-step knob existed ran at the only
        # stepping there was — today's mult=1; likewise pre-fault-plan /
        # pre-ensemble caches described the fault-free single-seed game
        theirs.setdefault("step_width_mult", 1)
        theirs.setdefault("partner_fault_plan", "")
        theirs.setdefault("seed_ensemble", 1)
        # pre-numerics caches ran the only reduction there was — the
        # default order-sensitive one
        theirs.setdefault("deterministic_reduce", False)
        # pre-precision caches ran the only precision there was — fp32
        theirs.setdefault("precision", "fp32")
        ours = self._fingerprint()
        if "partners_count" in theirs and \
                theirs["partners_count"] != ours["partners_count"]:
            raise ValueError(
                f"cache was built for {theirs['partners_count']} partners, "
                f"scenario has {ours['partners_count']}")
        mismatched = {k: (theirs.get(k), v) for k, v in ours.items()
                      if theirs.get(k) != v}
        if mismatched:
            raise ValueError(
                "coalition cache was built under a different scenario setup — "
                "characteristic values would not be comparable. Mismatches "
                f"(cache vs scenario): {mismatched}")
        self.charac_fct_values = {tuple(k): v
                                  for k, v in payload["charac_fct_values"]}
        self.increments_values = [{tuple(k): v for k, v in entries}
                                  for entries in payload["increments_values"]]
        self.first_charac_fct_calls_count = payload["first_charac_fct_calls_count"]
        self.charac_fct_samples = {
            tuple(k): np.asarray(v, float)
            for k, v in payload.get("charac_fct_samples", [])}
