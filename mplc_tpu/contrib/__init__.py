from .contributivity import Contributivity, KrigingModel, power_set
from .engine import CharacteristicEngine
from .shapley import (shapley_from_characteristic, powerset_order,
                      subset_to_bitmask, bitmask_to_subset)

__all__ = [
    "Contributivity", "KrigingModel", "power_set", "CharacteristicEngine",
    "shapley_from_characteristic", "powerset_order", "subset_to_bitmask",
    "bitmask_to_subset",
]
