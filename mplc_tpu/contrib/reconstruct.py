"""Retrain-free coalition reconstruction (GTG-Shapley, arXiv:2109.02053).

Every estimator before this module pays a full retrain-from-scratch per
coalition, so even the fused/pipelined sweep engine is bounded by
2^P x epochs of TRAINING work. GTG-Shapley's observation: during ONE
grand-coalition FedAvg run, record every aggregation round's per-partner
parameter delta and weight; any coalition S's model can then be
*reconstructed* by replaying the recorded rounds restricted to S —

    M_S^r = M_S^{r-1} + sum_{p in S} w~_p^r * delta_p^r,
    w~ = the recorded weights renormalized over S

— a weighted aggregation, i.e. the same computational shape as a
slot-engine step, fused here as one `lax.scan` over the recorded rounds,
vmapped over a batch of coalition masks. v(S) then costs one EVAL-ONLY
batch instead of a training run, changing the asymptotics: training
passes become O(P x epochs) total (the single recording run) instead of
O(2^P x P x epochs).

Execution contract (mirrors contrib/engine.py deliberately):

  - Reconstructed coalitions pack into the SAME merged slot buckets as
    trained ones (`engine._slot_buckets` / `_bucket_size` / the engine's
    device-batch cap), so eval programs bucket and pad exactly like the
    training sweep's — `engine.batch` events are emitted per batch with
    `eval_only=True`, zero epochs and zero partner passes.
  - Every dispatch/harvest boundary rides the engine's PR-4 recovery
    ladder: the shared fault injector fires at the engine's batch
    ordinals, transients retry bit-identically, RESOURCE_EXHAUSTED steps
    the shared cap-halving ladder down (re-bucketing the remaining
    subsets), and the exhausted ladder falls back to a host-CPU
    reconstruction of the tail. Row-independent vmapped evaluation makes
    every recovered value bit-identical to the fault-free one
    (equality-tested in tests/test_reconstruct.py).
  - Reconstructed values live in their OWN memo (`self.values`), never in
    `engine.charac_fct_values`: reconstruction is an approximation of the
    retrained v(S), and the exact memo (and its persisted caches) must
    never be silently poisoned by it.

Interaction with the partner fault model: dropped partners record
exactly-zero deltas and zero weights (masked-to-zero gradients), so a
reconstruction over any S renormalizes over the survivors exactly like
the live trainer. With seed ensembles the recording run uses the
engine's base seed — replica 0's game — and the retrain-free estimators
derive their trust row from Monte-Carlo sample blocks instead of seed
replicas (contrib/contributivity.py).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import constants
from .. import faults
from ..mpl.engine import MplTrainer
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .engine import _bucket_size, _memo_counters


@dataclasses.dataclass
class RecordedRun:
    """One grand-coalition training's recorded update stream."""
    init_params: object       # pytree: the run's initial global params
    deltas: object            # pytree, leaves [R, P, ...]: per-round deltas
    weights: jax.Array        # [R, P] normalized aggregation weights
    rounds: int               # R = epoch_count x minibatch_count
    partners_count: int
    epochs_done: int          # epochs actually trained (early stopping)
    training_passes: int      # partner passes the recording run paid
    memory_bytes: int         # recorded-update device memory footprint

    def describe(self) -> dict:
        return {"rounds": self.rounds, "partners": self.partners_count,
                "epochs": self.epochs_done,
                "training_passes": self.training_passes,
                "memory_bytes": self.memory_bytes}


def _check_not_2d(engine) -> None:
    """Fail fast (same guard pattern as seed_ensemble): update recording
    and the 2-D coalition x data mode are mutually exclusive — the
    recorded [rounds, partners, ...] stack needs the whole partner axis
    resident, which is exactly what the 2-D mode exists to avoid."""
    if getattr(engine, "_pipe2d", None) is not None:
        raise ValueError(
            "update recording (retrain-free GTG-Shapley/SVARM) is not "
            "supported in the 2-D partner-sharded mode "
            "(MPLC_TPU_PARTNER_SHARDS > 1): the recorded per-partner "
            "update stack needs the whole partner axis resident per "
            "device. Run the retrain-free estimators on the 1-D coalition "
            "mesh, or use the retraining estimators in 2-D mode.")


def record_updates(engine) -> RecordedRun:
    """Train the grand coalition ONCE with update recording on and return
    the recorded stream. The run trains through the engine's own
    TrainConfig (same epochs/minibatches/aggregator/fault plan) on the
    masked fedavg path, keyed by the grand coalition's effective rng
    stream — so the recorded trajectory is the same game the engine's
    cache fingerprint describes. The recording dispatch is a batch
    boundary for the fault plan: transients retry bit-identically (the
    stream is deterministic); an OOM here propagates — a single
    grand-coalition training has no narrower width to degrade to."""
    _check_not_2d(engine)
    cfg = dataclasses.replace(engine._multi_cfg, record_updates=True)
    trainer = MplTrainer.get(engine.model, cfg)
    P = engine.partners_count
    full = tuple(range(P))
    eff = engine._effective_subset(full)
    if not eff:
        raise ValueError("every partner is dropped from epoch 1 — there is "
                         "no grand-coalition run to record")
    rng = engine._coalition_rng(eff)
    mask = jnp.asarray(engine._coalition_arrays([full], None)[0])

    engine._batch_ordinal += 1
    ordinal = engine._batch_ordinal
    span = obs_trace.start_span("recon.record", partners=P,
                                rounds=cfg.epoch_count * cfg.minibatch_count)
    t0 = time.perf_counter()

    def dispatch():
        with obs_trace.span("engine.dispatch", width=1, slot_count=None,
                            coalitions=1, padding=0, recording=True):
            engine._faults.check("dispatch", ordinal)
            state = trainer.init_state(rng, P)
            # COPY the init params out of the state: the epoch-chunk jit
            # donates its state argument (mpl/engine.py buffer donation),
            # so the state's own param buffers are consumed by the first
            # chunk — but the recorded stream replays deltas from exactly
            # these initial params. The copy is enqueued before the
            # donating call, so ordering is safe.
            init_params = jax.tree_util.tree_map(jnp.copy, state.params)
            if cfg.is_early_stopping:
                chunk = max(1, min(cfg.patience, cfg.epoch_count))
                epochs_left = cfg.epoch_count
                while epochs_left > 0:
                    n = min(chunk, epochs_left)
                    state = trainer.jit_epoch_chunk(state, engine.stacked,
                                                    engine.val, mask, rng,
                                                    n_epochs=n)
                    epochs_left -= n
                    if bool(jax.device_get(state.done)):
                        break
            else:
                state = trainer.jit_epoch_chunk(state, engine.stacked,
                                                engine.val, mask, rng,
                                                n_epochs=cfg.epoch_count)
            return init_params, state

    try:
        init_params, state = engine._retry_transient(dispatch, "dispatch")
    except BaseException:
        # the documented propagation path (exhausted retries, OOM — a
        # single grand-coalition run has nothing to degrade to, and crash
        # faults are BaseException): drop the open span without emitting
        # so the thread-local nesting stays intact for the caller
        span.cancel()
        raise
    epochs = int(jax.device_get(state.nb_epochs_done))
    rounds = cfg.epoch_count * cfg.minibatch_count
    passes = epochs * cfg.minibatch_count * P
    samples = epochs * int(sum(int(engine._epoch_samples_multi[i])
                               for i in eff))
    mem = int(sum(np.prod(l.shape) * l.dtype.itemsize
                  for l in jax.tree_util.tree_leaves(state.upd_h))
              + state.w_h.size * state.w_h.dtype.itemsize)
    rec = RecordedRun(init_params=init_params, deltas=state.upd_h,
                      weights=state.w_h, rounds=rounds, partners_count=P,
                      epochs_done=epochs, training_passes=passes,
                      memory_bytes=mem)
    # the recording run IS training work: it owns every training-side
    # counter the retrain-free sweep will show (the asymptotic claim —
    # "partner passes only from the recording run" — is asserted against
    # exactly these)
    engine.epochs_trained += epochs
    engine.samples_trained += samples
    obs_metrics.counter("engine.batches").inc()
    obs_metrics.counter("engine.epochs_trained").inc(epochs)
    obs_metrics.counter("engine.samples_trained").inc(samples)
    obs_metrics.counter("engine.partner_passes").inc(passes)
    rec_dur = time.perf_counter() - t0
    obs_trace.event("engine.batch", dur=rec_dur, width=1,
                    slot_count=None, coalitions=1, padding=0, epochs=epochs,
                    samples=samples, partner_passes=passes, recording=True)
    if engine.device_meter is not None:
        engine.device_meter.note(1, span_sec=rec_dur)
    for k, v in rec.describe().items():
        span.attrs[k] = v
    span.end()
    return rec


class ReconstructionEvaluator:
    """Memoizing, batching v(S) over RECONSTRUCTED coalition models.

    The estimator-facing mirror of `CharacteristicEngine.evaluate`: same
    bucket grouping, same cap/width machinery, same fault ladder, same
    span/event vocabulary — but each batch is one fused
    reconstruct-then-evaluate program instead of a training run."""

    # opt-in AOT path (the live tier): _apply serves each (rounds, width)
    # program from the engine's ProgramBank instead of the inline jit —
    # same lowering, bit-identical values, zero inline compiles on a warm
    # bank. Default off so the historical estimator path is byte-for-byte
    # unchanged.
    use_bank = False

    def __init__(self, engine, recorded: RecordedRun | None = None):
        _check_not_2d(engine)
        self.engine = engine
        self.recorded = recorded if recorded is not None \
            else record_updates(engine)
        self.values: dict[tuple, float] = {(): 0.0}
        self.reconstructions = 0
        # the engine's frozen precision mode, captured once: the memo, the
        # reconstruction programs and the banked executables all answer
        # for exactly this mode (a bf16 answer must never serve an fp32
        # query — the live tier keys its query cache on this too)
        self.precision = getattr(engine._multi_cfg, "precision", "fp32")
        # fused-kernel routing (MPLC_TPU_RECON_KERNEL, resolved when the
        # first program is built): (use_kernel, interpret). Part of the
        # ProgramBank recon key — a scan executable and a kernel
        # executable are different programs
        self._kernel = None
        self._fn = None
        self._fn_cpu = None
        self._fn_donates = None
        self._cpu_rec = None

    def kernel_plan(self) -> tuple:
        """(use_kernel, interpret) for this evaluator, resolved once from
        MPLC_TPU_RECON_KERNEL + the backend (ops/recon_kernel.resolve)."""
        if self._kernel is None:
            from ..ops import recon_kernel
            self._kernel = recon_kernel.resolve(constants.recon_kernel_mode())
        return self._kernel

    def reset_recorded(self, recorded: RecordedRun) -> None:
        """Swap in a new recorded stream (the live tier's round-stamp
        invalidation): the memo is derived from the OLD stream and must
        be dropped with it; the jitted program cache survives (jit
        retraces per recorded-round count, and the AOT bank keys on
        it)."""
        self.recorded = recorded
        self.values = {(): 0.0}
        self._cpu_rec = None

    # -- the fused reconstruct+eval program ------------------------------

    def _make_batch_eval(self, use_kernel: bool, interpret: bool):
        """One fused reconstruct+eval program. `use_kernel=False` is the
        per-round lax.scan reference; `use_kernel=True` routes the
        renormalize+accumulate through the fused Pallas kernel
        (ops/recon_kernel.py) — same contraction reassociated across
        rounds, so values are bit-identical where fp addition happens to
        associate and ledger-bounded otherwise."""
        trainer = self.engine.multi_pipe.trainer
        precision = self.precision

        def batch_eval(masks, init_params, deltas, weights, test):
            if use_kernel:
                from ..ops import recon_kernel
                params = recon_kernel.reconstruct_batch(
                    masks, init_params, deltas, weights,
                    precision=precision, interpret=interpret)
                return jax.vmap(lambda p: trainer.evaluate(p, test)[1])(
                    params)
            if precision == "bf16":
                # documented deviation (MPLC_TPU_PRECISION=bf16): the
                # recorded stream and the carried params accumulate in
                # bf16; the per-round renormalize stays fp32 (tiny)
                init_params = jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.bfloat16), init_params)
                deltas = jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.bfloat16), deltas)

            def one(mask):
                def round_step(params, xs):
                    delta, w = xs          # [P, ...] leaves, [P]
                    ws = w * mask
                    denom = jnp.sum(ws)
                    # rounds the recording never reached (early stop)
                    # and rounds where no member survived carry zero
                    # weight: the model passes through unchanged
                    wn = jnp.where(denom > 0,
                                   ws / jnp.maximum(denom, 1e-12), 0.0)
                    upd = jax.tree_util.tree_map(
                        lambda d: jnp.tensordot(
                            wn.astype(d.dtype), d, axes=([0], [0])),
                        delta)
                    return jax.tree_util.tree_map(
                        lambda p, u: p + u, params, upd), None

                params, _ = lax.scan(round_step, init_params,
                                     (deltas, weights))
                return trainer.evaluate(params, test)[1]

            return jax.vmap(one)(masks)

        return batch_eval

    def _batch_eval_fn(self):
        if self._fn is None:
            use_kernel, interpret = self.kernel_plan()
            # donate the per-batch mask buffer (argument 0) into the
            # fused reconstruct+eval program; the recorded stream
            # (init_params/deltas/weights) and the test set are REUSED
            # across every batch and must never be donated. Retry safety:
            # the dispatch closure re-materializes masks from the host
            # array on every invocation (`_run_batch`).
            from ..mpl.engine import buffer_donation_enabled
            self._fn_donates = buffer_donation_enabled()
            self._fn = jax.jit(
                self._make_batch_eval(use_kernel, interpret),
                donate_argnums=(0,) if self._fn_donates else ())
        return self._fn

    def _cpu_eval_fn(self):
        """The terminal CPU rung's program. A compiled Pallas kernel
        cannot run on the host backend, so the rung falls back to the
        scan reference there (documented: CPU-recovered values of a
        kernel-mode run are ledger-bounded, not bit-identical, vs the
        kernel's); interpret-mode kernels run anywhere, so they keep the
        rung bit-identical with the fault-free path."""
        if self._fn_cpu is None:
            use_kernel, interpret = self.kernel_plan()
            if not use_kernel or interpret:
                # same program as the main path — share the jit object so
                # the historical (scan / interpret) rung stays literally
                # the same function, traced per device as before
                self._fn_cpu = self._batch_eval_fn()
            else:
                from ..mpl.engine import buffer_donation_enabled
                self._fn_cpu = jax.jit(
                    self._make_batch_eval(False, False),
                    donate_argnums=(0,)
                    if buffer_donation_enabled() else ())
        return self._fn_cpu

    def _apply(self, masks: jax.Array) -> jax.Array:
        rec = self.recorded
        fn = self._batch_eval_fn()
        if self.use_bank and self.engine.program_bank is not None:
            # live-tier warm path: the AOT-banked executable for exactly
            # this (rounds, width) program — the same jit, pre-lowered
            # (bit-identical values); None falls back to the inline jit
            exe = self.engine.program_bank.acquire_recon(
                self, int(masks.shape[0]))
            if exe is not None:
                fn = exe
        return fn(masks, rec.init_params, rec.deltas,
                  rec.weights, self.engine.test)

    def _apply_cpu(self, masks: np.ndarray) -> jax.Array:
        """Terminal OOM-ladder rung: reconstruct+evaluate on the host CPU
        with a host-pinned copy of the recorded stream (same program, same
        row-independent math — bit-identical values)."""
        cpu = jax.local_devices(backend="cpu")[0]
        if self._cpu_rec is None:
            put = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda a: jax.device_put(a, cpu), t)
            rec = self.recorded
            self._cpu_rec = (put(rec.init_params), put(rec.deltas),
                             put(rec.weights), put(self.engine.test))
        ip, d, w, test = self._cpu_rec
        with jax.default_device(cpu):
            return self._cpu_eval_fn()(
                jax.device_put(jnp.asarray(masks), cpu), ip, d, w, test)

    # -- estimator-facing API --------------------------------------------

    def evaluate(self, subsets) -> np.ndarray:
        """Batched memoized reconstructed v(S); values in input order."""
        eng = self.engine
        keys = [tuple(sorted(int(i) for i in s)) for s in subsets]
        unique = dict.fromkeys(keys)
        missing = [k for k in unique if k not in self.values]
        n_requested_missing = len(missing)
        if eng._forever_dropped:
            # same exact-null-player rule as the engine: a coalition whose
            # EVERY member is dropped from epoch 1 has v = 0 by definition
            # — its recorded weights are all-zero, so the scan would pass
            # the INIT params through and score the untrained model's
            # chance accuracy instead, crediting a null player
            live = []
            for k in missing:
                if all(i in eng._forever_dropped for i in k):
                    self.values[k] = 0.0
                else:
                    live.append(k)
            obs_metrics.counter("engine.null_coalitions").inc(
                n_requested_missing - len(live))
            missing = live
        method = _memo_counters(len(unique) - n_requested_missing,
                                len(missing))
        with obs_trace.span("engine.evaluate", requested=len(unique),
                            missing=len(missing), mode="reconstruct",
                            method=method):
            # same routing as the training sweep: singles as their own
            # group, multis through the engine's merged slot buckets so
            # reconstructed batches share the sweep's exact widths
            singles = [k for k in missing if len(k) == 1]
            multis = [k for k in missing if len(k) > 1]
            if singles:
                self._run_batch(singles, None)
            for slot_count, group in eng._slot_buckets(multis):
                self._run_batch(group, slot_count)
        return np.array([self.values[k] for k in keys])

    def _run_batch(self, subsets: list[tuple],
                   slot_count: int | None) -> None:
        eng = self.engine
        n = len(subsets)

        def bucket_width() -> int:
            n_dev = 1 if eng._cpu_degraded else max(
                eng._sharding.num_devices if eng._sharding else 1, 1)
            cap = eng._device_batch_cap(slot_count, False)
            return _bucket_size(min(n, n_dev * cap), n_dev, cap)

        b = bucket_width()
        halvings_seen = eng._cap_halvings
        with obs_trace.span("engine.prep", coalitions=n, width=b,
                            slot_count=slot_count):
            masks_all = eng._coalition_arrays(subsets, None)

        i = 0
        while i < n:
            if eng._cap_halvings != halvings_seen or \
                    (eng._cpu_degraded and b > 1):
                halvings_seen = eng._cap_halvings
                b = bucket_width()
            group = subsets[i:i + b]
            sel = np.full(b, i, np.intp)
            sel[:len(group)] = np.arange(i, i + len(group))
            eng._batch_ordinal += 1
            on_cpu = eng._cpu_degraded  # terminal rung at dispatch time
            attrs = {"width": b, "slot_count": slot_count,
                     "coalitions": len(group), "padding": b - len(group),
                     "eval_only": True}
            if on_cpu:
                attrs["degraded"] = "cpu"
            meta = {**attrs, "t0": time.perf_counter(),
                    "ordinal": eng._batch_ordinal}

            def dispatch(sel=sel, attrs=attrs, ordinal=eng._batch_ordinal):
                with obs_trace.span("engine.dispatch", **attrs):
                    eng._faults.check("dispatch", ordinal)
                    if eng._cpu_degraded:
                        accs = self._apply_cpu(masks_all[sel])
                    else:
                        m = jnp.asarray(masks_all[sel])
                        if eng._sharding is not None:
                            m = jax.device_put(
                                m, eng._sharding.batch_sharding)
                        accs = self._apply(m)
                    return lambda: np.asarray(jax.device_get(accs))

            meta["redispatch"] = dispatch
            try:
                fetch = eng._retry_transient(dispatch, "dispatch")
            except Exception as e:
                if not faults.is_oom(e) or on_cpu:
                    # the CPU rung is TERMINAL (matches the engine's
                    # _run_groups_cpu): an OOM there must propagate, not
                    # re-enter the ladder and livelock on the same batch
                    raise
                # dispatch-side OOM: step the shared ladder down and retry
                # THIS group (i unchanged) at the degraded width; past the
                # last rung the loop re-enters via the CPU path above
                eng._degrade_cap(e)
                continue
            i += len(group)
            try:
                with obs_trace.span("engine.harvest", width=b,
                                    slot_count=slot_count,
                                    coalitions=len(group)):
                    accs = eng._fetch_with_retry(fetch, meta)
            except Exception as e:
                if not faults.is_oom(e) or on_cpu:
                    raise  # CPU rung is terminal here too
                # harvest-side OOM: nothing of this group was memoized yet
                # — rewind and re-dispatch it at the degraded width
                eng._degrade_cap(e)
                i -= len(group)
                continue
            for s, acc in zip(group, accs[:len(group)]):
                self.values[s] = float(acc)
                if eng.numerics_ledger is not None:
                    # value provenance for reconstructed v(S): same ledger
                    # as the exact memo, tagged by source so a drift diff
                    # can't silently mix reconstruction against retraining
                    eng.numerics_ledger.record(
                        s, float(acc), source="reconstruction",
                        slot_width=slot_count,
                        cap_halvings=eng._cap_halvings,
                        degraded=bool(meta.get("degraded")))
            self.reconstructions += len(group)
            obs_metrics.counter("engine.batches").inc()
            obs_metrics.counter("engine.reconstructions").inc(len(group))
            obs_metrics.histogram("engine.pad_waste_fraction").observe(
                (b - len(group)) / b)
            extra = {}
            if meta.get("degraded"):
                extra["degraded"] = meta["degraded"]
                obs_metrics.counter("engine.cpu_degraded_batches").inc()
                obs_metrics.counter("engine.cpu_degraded_coalitions").inc(
                    len(group))
            # eval-only batch: zero epochs / samples / partner passes — the
            # sweep report's reconstruction row derives the eval-vs-train
            # split from exactly this shape
            dur = time.perf_counter() - meta["t0"]
            obs_trace.event("engine.batch", dur=dur, width=b,
                            slot_count=slot_count, coalitions=len(group),
                            padding=b - len(group), epochs=0, samples=0,
                            partner_passes=0, eval_only=True, **extra)
            if eng.device_meter is not None:
                # reconstruction batches carry no fence/cost sample (the
                # fused eval is inline-jit); eval_only keeps them out of
                # the fenced-training-rate extrapolation — they bill at
                # their own host span
                eng.device_meter.note(len(group), span_sec=dur,
                                      eval_only=True)
            if eng.progress is not None:
                eng.progress(len(group), n - i, slot_count)
