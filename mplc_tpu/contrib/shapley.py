"""Exact Shapley values from a characteristic-function table.

Replaces the reference's vendored susobhang70 implementation
(/root/reference/mplc/contributivity.py:1205-1253) — which rebuilds the
power set and calls `list.index` per term (O(4^n) lookups) — with direct
bit-twiddling over coalition bitmasks: O(n·2^n) with O(1) lookups.

Trust calibration: "On the Volatility of Shapley-Based Contribution
Metrics in Federated Learning" (PAPERS.md) shows point Shapley estimates
— and especially the partner RANKINGS derived from them — flip across
seeds. The seed-ensemble helpers below turn a K-replica characteristic
table (CharacteristicEngine's `charac_fct_samples`) into per-partner
confidence intervals and a Kendall-tau rank-stability score, rendered as
the `trust` row of the sweep report.
"""

from __future__ import annotations

from math import factorial

import numpy as np


def subset_to_bitmask(subset) -> int:
    m = 0
    for i in subset:
        m |= 1 << int(i)
    return m


def bitmask_to_subset(mask: int) -> tuple:
    out = []
    i = 0
    while mask:
        if mask & 1:
            out.append(i)
        mask >>= 1
        i += 1
    return tuple(out)


def powerset_order(n: int) -> list[tuple]:
    """The reference's coalition enumeration order: all subsets sorted by
    size then lexicographically (contributivity.py:149-151) — kept for
    results parity in logs/CSV."""
    from itertools import combinations
    return [tuple(c) for k in range(1, n + 1) for c in combinations(range(n), k)]


def shapley_from_characteristic(n: int, value_of: dict) -> np.ndarray:
    """value_of: dict mapping sorted subset tuple -> v(S); v(empty)=0.

    SV_i = sum_{S not containing i} |S|! (n-|S|-1)! / n! * (v(S+i) - v(S)).
    """
    v = np.zeros(1 << n)
    for subset, val in value_of.items():
        v[subset_to_bitmask(subset)] = val
    weights = np.array([factorial(k) * factorial(n - k - 1) / factorial(n)
                        for k in range(n)])
    sv = np.zeros(n)
    for mask in range(1 << n):
        size = bin(mask).count("1")
        for i in range(n):
            if not (mask >> i) & 1:
                sv[i] += weights[size] * (v[mask | (1 << i)] - v[mask])
    return sv


# ---------------------------------------------------------------------------
# Seed-ensemble trust calibration: CI + rank stability over K replicas
# ---------------------------------------------------------------------------

def shapley_sample_matrix(n: int, samples_of: dict) -> np.ndarray:
    """[K, n] per-replica Shapley values from a replica-valued
    characteristic table (`samples_of`: sorted subset tuple -> [K] array,
    CharacteristicEngine.charac_fct_samples). Replica j's Shapley vector
    is computed from replica j's v(S) slice — K independent games, one
    table."""
    if not samples_of:
        raise ValueError("empty replica table — run a seed-ensemble sweep "
                         "(seed_ensemble > 1) first")
    K = len(next(iter(samples_of.values())))
    rows = []
    for j in range(K):
        rows.append(shapley_from_characteristic(
            n, {s: float(arr[j]) for s, arr in samples_of.items()}))
    return np.stack(rows)


def kendall_tau(a, b) -> float:
    """Kendall's tau-a between the rankings induced by two score vectors:
    (concordant - discordant) / (n choose 2) over all index pairs. Ties
    count as discordant-free zeros; n < 2 returns 1.0 (a single partner
    cannot be mis-ranked)."""
    a = np.asarray(a, float)
    b = np.asarray(b, float)
    n = len(a)
    if n < 2:
        return 1.0
    conc = disc = 0
    for i in range(n):
        for j in range(i + 1, n):
            s = (a[i] - a[j]) * (b[i] - b[j])
            if s > 0:
                conc += 1
            elif s < 0:
                disc += 1
    return (conc - disc) / (n * (n - 1) / 2)


def rank_stability(sv_samples: np.ndarray) -> float:
    """Mean pairwise Kendall tau across the K replicas' Shapley rankings:
    1.0 = every seed agrees on the partner ordering, values near 0 = the
    ranking is noise (the volatility failure mode). K = 1 returns 1.0."""
    K = sv_samples.shape[0]
    if K < 2:
        return 1.0
    taus = [kendall_tau(sv_samples[i], sv_samples[j])
            for i in range(K) for j in range(i + 1, K)]
    return float(np.mean(taus))


def confidence_intervals(sv_samples: np.ndarray, alpha: float = 0.95
                         ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(mean, ci_low, ci_high) per partner over the K replica Shapley
    vectors: a Student-t interval on the mean at confidence `alpha`
    (half-width t_{K-1} * s / sqrt(K)). K = 1 collapses to zero-width
    intervals at the point estimate."""
    sv_samples = np.asarray(sv_samples, float)
    K = sv_samples.shape[0]
    mean = sv_samples.mean(axis=0)
    if K < 2:
        return mean, mean.copy(), mean.copy()
    from scipy.stats import t
    half = (t.ppf(0.5 + alpha / 2.0, K - 1)
            * sv_samples.std(axis=0, ddof=1) / np.sqrt(K))
    return mean, mean - half, mean + half


def trust_from_replicas(sv_samples, alpha: float = 0.95,
                        source: str = "replicas") -> dict:
    """The `trust` row dict from an explicit [K, n] replica Shapley
    matrix. Two producers share it: seed-ensemble sweeps (replicas =
    independent seeds, via `trust_summary`, source="seed_ensemble") and
    the retrain-free MC estimators (replicas = disjoint sample blocks of
    one run — Monte-Carlo uncertainty rather than seed volatility,
    source="mc_blocks"). `source` is carried in the row so a report/
    sidecar reader can tell seed volatility from sampling noise — the
    two rows are otherwise schema-identical. Plain lists and floats —
    JSON-ready for the telemetry sidecar."""
    sv = np.asarray(sv_samples, float)
    n = sv.shape[1]
    mean, lo, hi = confidence_intervals(sv, alpha)
    std = (sv.std(axis=0, ddof=1) if sv.shape[0] > 1
           else np.zeros(n))
    return {
        "ensemble": int(sv.shape[0]),
        "source": source,
        "alpha": float(alpha),
        "mean": [float(x) for x in mean],
        "std": [float(x) for x in std],
        "ci_low": [float(x) for x in lo],
        "ci_high": [float(x) for x in hi],
        "kendall_tau": rank_stability(sv),
    }


def trust_summary(n: int, samples_of: dict, alpha: float = 0.95) -> dict:
    """The sweep report's `trust` row: per-partner Shapley mean / std /
    CI bounds over the seed ensemble plus the Kendall-tau rank-stability
    score."""
    return trust_from_replicas(shapley_sample_matrix(n, samples_of), alpha,
                               source="seed_ensemble")
