"""Exact Shapley values from a characteristic-function table.

Replaces the reference's vendored susobhang70 implementation
(/root/reference/mplc/contributivity.py:1205-1253) — which rebuilds the
power set and calls `list.index` per term (O(4^n) lookups) — with direct
bit-twiddling over coalition bitmasks: O(n·2^n) with O(1) lookups.
"""

from __future__ import annotations

from math import factorial

import numpy as np


def subset_to_bitmask(subset) -> int:
    m = 0
    for i in subset:
        m |= 1 << int(i)
    return m


def bitmask_to_subset(mask: int) -> tuple:
    out = []
    i = 0
    while mask:
        if mask & 1:
            out.append(i)
        mask >>= 1
        i += 1
    return tuple(out)


def powerset_order(n: int) -> list[tuple]:
    """The reference's coalition enumeration order: all subsets sorted by
    size then lexicographically (contributivity.py:149-151) — kept for
    results parity in logs/CSV."""
    from itertools import combinations
    return [tuple(c) for k in range(1, n + 1) for c in combinations(range(n), k)]


def shapley_from_characteristic(n: int, value_of: dict) -> np.ndarray:
    """value_of: dict mapping sorted subset tuple -> v(S); v(empty)=0.

    SV_i = sum_{S not containing i} |S|! (n-|S|-1)! / n! * (v(S+i) - v(S)).
    """
    v = np.zeros(1 << n)
    for subset, val in value_of.items():
        v[subset_to_bitmask(subset)] = val
    weights = np.array([factorial(k) * factorial(n - k - 1) / factorial(n)
                        for k in range(n)])
    sv = np.zeros(n)
    for mask in range(1 << n):
        size = bin(mask).count("1")
        for i in range(n):
            if not (mask >> i) & 1:
                sv[i] += weights[size] * (v[mask | (1 << i)] - v[mask])
    return sv
