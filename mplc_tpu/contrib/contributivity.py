"""Contributivity measurement: all 14 methods of the reference engine.

Mirrors the reference API (/root/reference/mplc/contributivity.py:64-1198):
`Contributivity(scenario)` + `compute_contributivity(method_name, ...)`,
with `contributivity_scores / scores_std / normalized_scores /
computation_time_sec / first_charac_fct_calls_count` populated identically.

The execution model is inverted, though: every method now *requests batches
of coalitions* from the CharacteristicEngine (mplc_tpu/contrib/engine.py)
instead of training one subset at a time. Concretely:

  - exact Shapley prefetches all 2^N-1 coalitions in device-sized batches;
  - TMCS/ITMCS run a *wavefront* over K permutations at once: at prefix
    length j, all non-truncated permutations' prefixes are evaluated in one
    batch, preserving each permutation's truncation rule exactly;
  - the importance-sampling methods draw a block of iterations up front and
    evaluate the block's (S, S u {k}) pairs in one batch (the samples are
    i.i.d. so blocking only affects when the stopping rule is checked, not
    the estimator); the draws themselves come from tabulated/vectorized
    samplers (contrib/sampling.py) instead of the reference's O(2^(n-1))
    power-set walk per draw;
  - the stratified methods keep their per-iteration adaptive allocation
    rule bit-identically (the fixed-seed oracle pins in
    tests/test_estimator_regression.py) but batch through the engine with
    a *speculative lookahead*: each iteration's evaluate() call also
    carries the next `lookahead` iterations' draws, simulated on a CLONED
    rng under the current allocation, so consecutive iterations' (S,
    S u {k}) pairs pack into one device batch. v(S) is batch-invariant
    and memoized, so a missed speculation only warms the memo — it can
    never change the estimator's stream (lookahead=0 restores the
    strictly sequential evaluation schedule).

Reference quirks handled deliberately (see also SURVEY.md §7):
  - ITMCS's `size_of_rest` iterates positions of the *unpermuted* partner
    list (contributivity.py:298-301); implemented as the documented intent
    (sizes of the partners remaining in the permutation).
  - PVRL constructs its MPL with a long-stale positional signature upstream
    (contributivity.py:949-958, dead code); implemented here as documented:
    REINFORCE over per-epoch partner selection.
"""

from __future__ import annotations

import datetime
import logging
import time
from itertools import combinations
from math import comb, factorial

import numpy as np
from scipy.stats import norm

import jax
import jax.numpy as jnp

from .. import constants
from ..mpl.engine import MplTrainer, TrainConfig
from ..obs import trace as obs_trace
from .engine import CharacteristicEngine
from .sampling import (WithoutReplacementRanks, make_importance_sampler,
                       randbelow, svarm_batch_draws, svarm_warmup_draws,
                       unrank_combination)
from .shapley import (powerset_order, shapley_from_characteristic,
                      trust_from_replicas, trust_summary)

logger = logging.getLogger("mplc_tpu")


class KrigingModel:
    """Gaussian-process regressor with polynomial trend, used by AIS
    (reference contributivity.py:22-61). Vectorized numpy implementation."""

    def __init__(self, degre: int, covariance_func, cov_batch=None):
        self.degre = degre
        self.cov_f = covariance_func
        # optional vectorized covariance: (queries [B,d], train [M,d]) -> [B,M]
        self.cov_batch = cov_batch
        self.X = self.Y = self.beta = self.H = self.invK = None

    def fit(self, X, Y):
        X = [np.asarray(x, float) for x in X]
        Y = np.asarray(Y, float)
        self.X, self.Y = X, Y
        m = len(X)
        K = np.zeros((m, m))
        H = np.zeros((m, self.degre + 1))
        for i, a in enumerate(X):
            for j, b in enumerate(X):
                K[i, j] = self.cov_f(a, b)
            for j in range(self.degre + 1):
                H[i, j] = np.sum(a) ** j
        K += 1e-9 * np.eye(m)  # numerical jitter; reference inverts raw K
        self.H = H
        self.invK = np.linalg.inv(K)
        Ht_invK_H = H.T @ self.invK @ H
        self.beta = np.linalg.inv(Ht_invK_H) @ H.T @ self.invK @ self.Y

    def predict(self, x):
        x = np.asarray(x, float)
        gx = np.array([np.sum(x) ** i for i in range(self.degre + 1)])
        cx = np.array([self.cov_f(xi, x) for xi in self.X])
        return gx @ self.beta + cx @ self.invK @ (self.Y - self.H @ self.beta)

    def predict_batch(self, Xq):
        """Vectorized predict over [B, d] query rows; one matmul instead of
        B python-level predict calls (feeds the tabulated IS sampler)."""
        Xq = np.asarray(Xq, float)
        s = Xq.sum(axis=1)
        G = np.stack([s ** i for i in range(self.degre + 1)], axis=1)
        Xtr = np.stack(self.X)
        if self.cov_batch is not None:
            C = self.cov_batch(Xq, Xtr)
        else:
            C = np.array([[self.cov_f(xt, xq) for xt in Xtr] for xq in Xq])
        return G @ self.beta + C @ (self.invK @ (self.Y - self.H @ self.beta))


def power_set(lst):
    """Reference-compatible helper (contributivity.py:1205-1206)."""
    return [list(c) for i in range(len(lst)) for c in combinations(lst, i + 1)]


class Contributivity:
    def __init__(self, scenario, name: str = ""):
        self.name = name
        self.scenario = scenario
        nb_partners = len(scenario.partners_list)
        self.contributivity_scores = np.zeros(nb_partners)
        self.scores_std = np.zeros(nb_partners)
        self.normalized_scores = np.zeros(nb_partners)
        self.computation_time_sec = 0.0
        # seed-ensemble trust row (per-partner CI + Kendall-tau rank
        # stability) — populated by compute_SV when the engine runs with
        # seed_ensemble > 1, None otherwise
        self.trust = None
        # engine is shared per scenario so the coalition cache persists
        # across methods (same behavior as the reference's per-Contributivity
        # cache, but stronger: shared across methods in one scenario run).
        if getattr(scenario, "_charac_engine", None) is None:
            scenario._charac_engine = CharacteristicEngine(scenario)
        self.engine: CharacteristicEngine = scenario._charac_engine
        self._rng = np.random.default_rng(getattr(scenario, "seed", 0) + 17)

    # -- reference-API passthroughs -------------------------------------

    @property
    def charac_fct_values(self):
        return self.engine.charac_fct_values

    @property
    def increments_values(self):
        return self.engine.increments_values

    @property
    def first_charac_fct_calls_count(self):
        return self.engine.first_charac_fct_calls_count

    def not_twice_characteristic(self, subset):
        return self.engine.not_twice_characteristic(subset)

    def __str__(self):
        t = str(datetime.timedelta(seconds=self.computation_time_sec))
        out = "\n" + self.name + "\n"
        out += "Computation time: " + t + "\n"
        out += ("Number of characteristic function computed: "
                + str(self.first_charac_fct_calls_count) + "\n")
        out += f"Contributivity scores: {np.round(self.contributivity_scores, 3)}\n"
        out += f"Std of the contributivity scores: {np.round(self.scores_std, 3)}\n"
        out += f"Normalized contributivity scores: {np.round(self.normalized_scores, 3)}\n"
        return out

    def _method_span(self, method: str) -> obs_trace.Span:
        """The estimator's timing span: `computation_time_sec` is derived
        from it in `_finish` (single source of truth — the span IS the
        timer), and ending it emits one `contributivity` trace record per
        method run when telemetry is on."""
        return obs_trace.start_span("contributivity", method=method)

    def _finish(self, name, scores, std, t0):
        self.name = name
        self.contributivity_scores = np.asarray(scores, float)
        self.scores_std = np.asarray(std, float)
        total = np.sum(self.contributivity_scores)
        self.normalized_scores = self.contributivity_scores / (total if total else 1.0)
        if isinstance(t0, obs_trace.Span):
            t0.attrs["method"] = name  # final display name, not the seed label
            self.computation_time_sec = t0.end().duration
        else:
            # raw perf_counter() origin (external callers/tests): same
            # wall-clock semantics, no trace record
            self.computation_time_sec = time.perf_counter() - t0

    @property
    def _n(self):
        return len(self.scenario.partners_list)

    def _sizes(self):
        return np.array([len(p.y_train) for p in
                         sorted(self.scenario.partners_list, key=lambda q: q.id)])

    # ------------------------------------------------------------------
    # 1. exact Shapley — fully batched coalition sweep
    # ------------------------------------------------------------------

    def compute_SV(self):
        t0 = self._method_span("Shapley")
        logger.info("# Launching computation of Shapley Value of all partners")
        n = self._n
        coalitions = powerset_order(n)
        self.engine.evaluate(coalitions)  # batched prefetch of all 2^n - 1
        sv = shapley_from_characteristic(n, self.engine.charac_fct_values)
        std = np.zeros(n)
        samples = getattr(self.engine, "charac_fct_samples", None)
        if getattr(self.engine, "seed_ensemble", 1) > 1 and samples:
            # trust calibration: per-replica Shapley values from the K
            # seed replicas the sweep batched alongside the point run —
            # CI + rank stability become the report's `trust` row, and the
            # replica std is the honest scores_std (the point path's zeros
            # claim a certainty the volatility results refute)
            self.trust = trust_summary(n, samples)
            std = np.asarray(self.trust["std"])
            obs_trace.event("contrib.trust", **self.trust)
            logger.info(
                "# Seed-ensemble trust: K=%d, kendall_tau=%.3f",
                self.trust["ensemble"], self.trust["kendall_tau"])
        self._finish("Shapley", sv, std, t0)

    # ------------------------------------------------------------------
    # 2. independent scores
    # ------------------------------------------------------------------

    def compute_independent_scores(self):
        t0 = self._method_span("Independent scores raw")
        logger.info("# Launching computation of perf. scores of models trained "
                    "independently on each partner")
        n = self._n
        scores = self.engine.evaluate([(i,) for i in range(n)])
        self._finish("Independent scores raw", scores, np.zeros(n), t0)

    # ------------------------------------------------------------------
    # 3/4. truncated MC (+ interpolated variant) — permutation wavefront
    # ------------------------------------------------------------------

    def _truncated_permutation_sweep(self, n, v_all, eval_fn, values,
                                     sv_accuracy, alpha, truncation,
                                     interpolate, sizes, perm_batch,
                                     min_iter=100):
        """The truncated-permutation wavefront shared by TMCS/ITMCS (v
        from retraining) and GTG-Shapley (v from reconstruction):
        `perm_batch` permutations advance in lock-step, and at prefix
        length j only the non-truncated permutations' prefixes are
        evaluated — in one batch through `eval_fn` — preserving each
        permutation's truncation rule exactly. `values` is the live memo
        `eval_fn` fills. Returns (contributions [T, n], T)."""
        q = norm.ppf((1 - alpha) / 2, loc=0, scale=1)
        contributions = np.zeros((0, n))
        t = 0
        v_max = 0.0
        while t < min_iter or t < q ** 2 * v_max / sv_accuracy ** 2:
            k_round = perm_batch
            perms = [self._rng.permutation(n) for _ in range(k_round)]
            rows = np.zeros((k_round, n))
            prefix_vals = np.zeros(k_round)
            interp_slope = np.full(k_round, np.nan)  # ITMCS per-perm slope a
            for j in range(n):
                need = [k for k in range(k_round)
                        if abs(v_all - prefix_vals[k]) >= truncation]
                if need:
                    eval_fn([tuple(sorted(perms[k][:j + 1]))
                             for k in need])
                need_set = set(need)
                for k in range(k_round):
                    key = tuple(sorted(int(x) for x in perms[k][:j + 1]))
                    if k in need_set:
                        new_val = values[key]
                    elif interpolate:
                        if np.isnan(interp_slope[k]):
                            size_of_rest = sizes[perms[k][j:]].sum()
                            interp_slope[k] = ((v_all - prefix_vals[k])
                                               / max(size_of_rest, 1))
                        new_val = prefix_vals[k] + interp_slope[k] * sizes[perms[k][j]]
                    else:
                        new_val = prefix_vals[k]
                    rows[k, perms[k][j]] = new_val - prefix_vals[k]
                    prefix_vals[k] = new_val
            contributions = np.vstack([contributions, rows])
            t += k_round
            v_max = np.max(np.var(contributions, axis=0))
        return contributions, t

    def _tmc(self, sv_accuracy, alpha, truncation, interpolate, perm_batch=16):
        name = "ITMCS" if interpolate else "TMC Shapley"
        t0 = self._method_span(name)
        n = self._n
        v_all = float(self.engine.evaluate([tuple(range(n))])[0])
        if n == 1:
            self._finish(name, np.array([v_all]), np.array([0.0]), t0)
            return
        contributions, t = self._truncated_permutation_sweep(
            n, v_all, self.engine.evaluate, self.engine.charac_fct_values,
            sv_accuracy, alpha, truncation, interpolate, self._sizes(),
            perm_batch)
        sv = np.mean(contributions, axis=0)
        std = np.std(contributions, axis=0) / np.sqrt(t - 1)
        self._finish(name, sv, std, t0)

    def truncated_MC(self, sv_accuracy=0.01, alpha=0.9, truncation=0.05):
        logger.info("# Launching TMCS (truncated Monte-Carlo Shapley)")
        self._tmc(sv_accuracy, alpha, truncation, interpolate=False)

    def interpol_TMC(self, sv_accuracy=0.01, alpha=0.9, truncation=0.05):
        logger.info("# Launching ITMCS (interpolated truncated Monte-Carlo Shapley)")
        self._tmc(sv_accuracy, alpha, truncation, interpolate=True)

    # ------------------------------------------------------------------
    # 5/6/7. importance sampling (linear / regression / adaptive Kriging)
    # ------------------------------------------------------------------

    def _build_samplers(self, n, batch_fn_for):
        """One importance sampler per partner. `batch_fn_for(k)` returns a
        vectorized |approx increment| model over [B, n-1] membership masks
        of N\\{k}; the sampler tabulates the reference's IS proposal from it
        (see contrib/sampling.py — exact below MAX_EXACT_BITS partners,
        size-stratified above)."""
        return [make_importance_sampler(n, k, batch_fn_for(k), self._rng)
                for k in range(n)]

    def _is_sampling_loop(self, n, samplers, sv_accuracy, alpha,
                          t0, name, block=8, refit_every=None, refit_fn=None):
        q = -norm.ppf((1 - alpha) / 2, loc=0, scale=1)
        contributions = []
        t = 0
        v_max = 0.0
        since_refit = 0
        while t < 100 or t < 4 * q ** 2 * v_max / sv_accuracy ** 2:
            if refit_every is not None and refit_fn is not None and \
                    since_refit >= refit_every:
                samplers = refit_fn()
                since_refit = 0
            rounds = []
            requests = []
            for _ in range(block):
                row = []
                for k in range(n):
                    u = self._rng.uniform()
                    S, weight = samplers[k].draw(u, self._rng)
                    row.append((S, weight))
                    requests.append(tuple(sorted(S.tolist() + [k])))
                    requests.append(tuple(sorted(S.tolist())))
                rounds.append(row)
            self.engine.evaluate([r for r in requests if len(r) > 0])
            vals = self.engine.charac_fct_values
            for row in rounds:
                contrib_row = np.zeros(n)
                for k, (S, weight) in enumerate(row):
                    s_key = tuple(sorted(int(x) for x in S))
                    sk_key = tuple(sorted(list(s_key) + [k]))
                    increment = vals[sk_key] - vals.get(s_key, 0.0)
                    contrib_row[k] = increment * weight
                contributions.append(contrib_row)
            t += block
            since_refit += block
            v_max = np.max(np.var(np.asarray(contributions), axis=0))
        contributions = np.asarray(contributions)
        sv = np.mean(contributions, axis=0)
        std = np.std(contributions, axis=0) / np.sqrt(t - 1)
        self._finish(name, sv, std, t0)

    def IS_lin(self, sv_accuracy=0.01, alpha=0.95):
        """Linear-interpolation importance sampling (reference :326-439)."""
        t0 = self._method_span("IS_lin Shapley")
        logger.info("# Launching IS_lin Shapley")
        n = self._n
        v_all = float(self.engine.evaluate([tuple(range(n))])[0])
        if n == 1:
            self._finish("IS_lin Shapley", np.array([v_all]), np.array([0.0]), t0)
            return
        # batched prefetch of v(N\k) and v({k})
        self.engine.evaluate([tuple(sorted(set(range(n)) - {k})) for k in range(n)]
                             + [(k,) for k in range(n)])
        vals = self.engine.charac_fct_values
        last_inc = [v_all - vals[tuple(sorted(set(range(n)) - {k}))] for k in range(n)]
        first_inc = [vals[(k,)] for k in range(n)]
        sizes = self._sizes()
        size_of_i = sizes.sum()

        def batch_fn_for(k):
            sizes_k = sizes[np.delete(np.arange(n), k)]

            def batch(masks):
                beta = (masks @ sizes_k) / size_of_i
                return (1 - beta) * first_inc[k] + beta * last_inc[k]
            return batch

        samplers = self._build_samplers(n, batch_fn_for)
        self._is_sampling_loop(n, samplers, sv_accuracy, alpha,
                               t0, "IS_lin Shapley")

    def IS_reg(self, sv_accuracy=0.01, alpha=0.95):
        """Regression importance sampling (reference :443-569). Falls back to
        exact SV for n < 4 like the reference."""
        t0 = self._method_span("IS_reg Shapley")
        logger.info("# Launching IS_reg Shapley")
        n = self._n
        if n < 4:
            # compute_SV times itself through its own span; drop this one
            # so the nesting stack stays clean on the early exit
            t0.cancel()
            self.compute_SV()
            self.name = "IS_reg Shapley values"
            return
        # warm-up: (n+2) permutations' prefix chains, fully batched
        perm = self._rng.permutation(n)
        chains = [perm.copy(), np.flip(perm)]
        p = np.flip(perm)
        for _ in range(n):
            p = np.append(p[-1], p[:-1])
            chains.append(p.copy())
        requests = [tuple(sorted(int(x) for x in chain[:j + 1]))
                    for chain in chains for j in range(n)]
        self.engine.evaluate(requests)

        sizes = self._sizes()

        def makedata(subset):
            s = sizes[np.asarray(subset, int)].sum() if len(subset) else 0.0
            return np.array([s, s ** 2])

        from sklearn.linear_model import LinearRegression
        models = []
        for k in range(n):
            x = [makedata(subset) for subset in self.engine.increments_values[k]]
            y = list(self.engine.increments_values[k].values())
            model_k = LinearRegression()
            model_k.fit(np.array(x), np.array(y))
            models.append(model_k)

        def batch_fn_for(k):
            sizes_k = sizes[np.delete(np.arange(n), k)]
            model_k = models[k]

            def batch(masks):
                w = masks @ sizes_k
                return model_k.predict(np.stack([w, w * w], axis=1))
            return batch

        samplers = self._build_samplers(n, batch_fn_for)
        self._is_sampling_loop(n, samplers, sv_accuracy, alpha,
                               t0, "IS_reg Shapley")

    def AIS_Kriging(self, sv_accuracy=0.01, alpha=0.95, update=50):
        """Adaptive Kriging importance sampling (reference :573-723)."""
        t0 = self._method_span("AIS Shapley")
        logger.info("# Launching AIS Kriging Shapley")
        n = self._n
        # seed evaluations: full set, singletons, pairs + their complements
        requests = [tuple(range(n))]
        for k1 in range(n):
            requests.append((k1,))
            requests.append(tuple(sorted(set(range(n)) - {k1})))
            for k2 in range(n):
                if k1 != k2:
                    requests.append(tuple(sorted((k1, k2))))
                    requests.append(tuple(sorted(set(range(n)) - {k1, k2})))
        self.engine.evaluate(list(dict.fromkeys(requests)))

        sizes = self._sizes()

        def make_coordinate(subset, k):
            coord = np.zeros(n)
            for i in np.asarray(subset, int):
                coord[i] = sizes[i]
            return np.delete(coord, k)

        def dist(x1, x2):
            return np.sqrt(np.sum((np.asarray(x1) - np.asarray(x2)) ** 2))

        phi = np.array([np.median(make_coordinate(np.delete(np.arange(n), k), k))
                        for k in range(n)])

        def make_cov(k):
            return lambda x1, x2: np.exp(-dist(x1, x2) ** 2 / max(phi[k] ** 2, 1e-12))

        def make_cov_batch(k):
            denom = max(phi[k] ** 2, 1e-12)

            def cb(A, B):
                # ||a-b||^2 via the inner-product identity: holds only the
                # [B, M] result, never a [B, M, d] broadcast intermediate
                # (the table B can be 2^16 rows)
                d2 = ((A * A).sum(1)[:, None] + (B * B).sum(1)[None, :]
                      - 2.0 * (A @ B.T))
                return np.exp(-np.maximum(d2, 0.0) / denom)
            return cb

        def refit():
            models = []
            for k in range(n):
                x = [make_coordinate(subset, k)
                     for subset in self.engine.increments_values[k]]
                y = list(self.engine.increments_values[k].values())
                m = KrigingModel(2, make_cov(k), cov_batch=make_cov_batch(k))
                m.fit(x, y)
                models.append(m)

            def batch_fn_for(k):
                sizes_k = sizes[np.delete(np.arange(n), k)]
                model_k = models[k]

                def batch(masks):
                    return model_k.predict_batch(masks * sizes_k)
                return batch

            return self._build_samplers(n, batch_fn_for)

        samplers = refit()
        self._is_sampling_loop(n, samplers, sv_accuracy, alpha,
                               t0, "AIS Shapley", block=min(8, update),
                               refit_every=update, refit_fn=refit)

    # ------------------------------------------------------------------
    # 8/9. stratified Monte-Carlo (with and without replacement)
    # ------------------------------------------------------------------

    @staticmethod
    def _smcs_e(t: int, N: int) -> float:
        """SMCS's exploration/exploitation schedule (reference :739-741)."""
        gamma, beta = 0.2, 0.0075
        return (1 + 1 / (1 + np.exp(gamma / beta))
                - 1 / (1 + np.exp(-(t - gamma * N) / (beta * N))))

    def _spec_rng(self) -> np.random.Generator:
        """A CLONE of the estimator rng continuing from its live state:
        the stratified methods' speculative lookahead draws from it, so
        speculation can never perturb the real stream (the fixed-seed
        pins vs the sequential allocation rule stay bit-identical)."""
        g = np.random.Generator(type(self._rng.bit_generator)())
        g.bit_generator.state = self._rng.bit_generator.state
        return g

    def _smcs_draw_plan(self, rng, e, N, sigma2):
        """One SMCS iteration's [(k, strata, S)] draw plan — the exact
        reference draw sequence, parameterized over the generator so the
        speculative lookahead can replay it on a cloned rng."""
        plan = []
        for k in range(N):
            if np.sum(sigma2[k]) == 0:
                p = np.repeat(1 / N, N)
            else:
                p = np.repeat(1 / N, N) * (1 - e) + sigma2[k] / np.sum(sigma2[k]) * e
            strata = rng.choice(np.arange(N), 1, p=p)[0]
            # uniform draw of a size-`strata` subset of N\{k}: the
            # reference walks the C(N-1, strata) combinations summing a
            # constant probability per step (contributivity.py:757-768);
            # the walk's stopping index is just floor(u * C) — unrank it
            # directly instead of enumerating.
            u = rng.uniform()
            list_k = np.delete(np.arange(N), k)
            total = comb(N - 1, int(strata))
            if total <= 2 ** 53:
                idx = min(int(u * total), total - 1)
            else:
                # float inverse-CDF can't index strata larger than 2^53
                idx = randbelow(rng, total)
            S = np.array(list_k[unrank_combination(N - 1, int(strata), idx)],
                         int)
            plan.append((k, strata, S))
        return plan

    @staticmethod
    def _pair_requests(plan) -> list:
        reqs = []
        for k, _strata, S in plan:
            reqs.append(tuple(sorted(S.tolist() + [k])))
            if len(S):
                reqs.append(tuple(sorted(S.tolist())))
        return reqs

    def Stratified_MC(self, sv_accuracy=0.01, alpha=0.95, lookahead=4):
        """Stratified MC Shapley (reference :727-819): per-partner strata by
        coalition size, adaptive allocation toward high-variance strata.

        The allocation rule stays per-iteration adaptive (bit-identical
        to the sequential reference loop — the oracle pin in
        tests/test_estimator_regression.py), but each iteration's
        engine.evaluate call ALSO carries the next `lookahead`
        iterations' draws, simulated on a cloned rng under the current
        sigma2 — so consecutive iterations' pairs pack into one device
        batch and the later iterations mostly hit the memo. A missed
        speculation only warms the memo (v(S) is batch-invariant);
        lookahead=0 restores the strictly sequential schedule."""
        t0 = self._method_span("Stratified MC Shapley")
        logger.info("# Launching Stratified MC Shapley")
        N = self._n
        v_all = float(self.engine.evaluate([tuple(range(N))])[0])
        if N == 1:
            self._finish("Stratified MC Shapley", np.array([v_all]), np.array([0.0]), t0)
            return
        t = 0
        sigma2 = np.zeros((N, N))
        mu = np.zeros((N, N))
        v_max = 0.0
        continuer = [[True] * N for _ in range(N)]
        contributions = [[list() for _ in range(N)] for _ in range(N)]
        while np.any(continuer) or (1 - alpha) < v_max / sv_accuracy ** 2:
            t += 1
            plan = self._smcs_draw_plan(self._rng, self._smcs_e(t, N), N,
                                        sigma2)
            # batch this iteration's 2N evaluations, plus the speculative
            # lookahead's (cloned rng, frozen sigma2 — extra memo warmth,
            # never a changed stream)
            reqs = self._pair_requests(plan)
            if lookahead:
                srng = self._spec_rng()
                for j in range(1, int(lookahead) + 1):
                    reqs += self._pair_requests(self._smcs_draw_plan(
                        srng, self._smcs_e(t + j, N), N, sigma2))
            self.engine.evaluate(reqs)
            vals = self.engine.charac_fct_values
            for k, strata, S in plan:
                s_key = tuple(sorted(int(x) for x in S))
                increment = vals[tuple(sorted(list(s_key) + [k]))] - vals.get(s_key, 0.0)
                contributions[k][strata].append(increment)
                sigma2[k, strata] = np.var(contributions[k][strata])
                mu[k, strata] = np.mean(contributions[k][strata])
            shap = np.mean(mu, axis=1)
            var = np.zeros(N)
            for k in range(N):
                for strata in range(N):
                    n_ks = len(contributions[k][strata])
                    if n_ks == 0:
                        var[k] = np.inf
                    else:
                        var[k] += sigma2[k, strata] ** 2 / n_ks
                    if n_ks > 20:
                        continuer[k][strata] = False
                var[k] /= N ** 2
            v_max = np.max(var)
        self._finish("Stratified MC Shapley", shap, np.sqrt(var), t0)

    @staticmethod
    def _clone_pool(pool: WithoutReplacementRanks) -> WithoutReplacementRanks:
        clone = WithoutReplacementRanks(pool.total)
        clone._moved = dict(pool._moved)
        return clone

    def _wr_draw_plan(self, rng, N, sigma2, continuer, pools):
        """One WR_SMC iteration's [(k, strata, S)] draw plan — the exact
        reference draw sequence over the PASSED continuer/pool state, so
        the real loop mutates its live state while the speculative
        lookahead replays on clones."""
        plan = []
        for k in range(N):
            if np.any(continuer[k]):
                p = np.array(continuer[k], float) / np.sum(continuer[k])
            elif np.sum(sigma2[k]) == 0:
                continue
            else:
                p = sigma2[k] / np.sum(sigma2[k])
            strata = rng.choice(np.arange(N), 1, p=p)[0]
            if pools[k][strata].total <= 0:  # __len__ caps at sys.maxsize
                continuer[k][strata] = False
                continue
            rank = pools[k][strata].pop_random(rng)
            list_k = np.delete(np.arange(N), k)
            subset = tuple(int(i) for i in
                           list_k[unrank_combination(N - 1, int(strata), rank)])
            plan.append((k, strata, np.array(subset, int)))
        return plan

    def without_replacment_SMC(self, sv_accuracy=0.01, alpha=0.95,
                               lookahead=4):
        """Without-replacement stratified MC (reference :823-938). Same
        speculative-lookahead batching as `Stratified_MC` — the
        lookahead replays the draw sequence on a cloned rng with CLONED
        without-replacement pools and continuer state, so the real
        stream (and its pool mutations) is untouched and the fixed-seed
        oracle pin holds bit-identically; lookahead=0 restores the
        strictly sequential evaluation schedule."""
        t0 = self._method_span("WR_SMC Shapley")
        logger.info("# Launching WR_SMC Shapley")
        N = self._n
        v_all = float(self.engine.evaluate([tuple(range(N))])[0])
        if N == 1:
            self._finish("WR_SMC Shapley", np.array([v_all]), np.array([0.0]), t0)
            return
        t = 0
        sigma2 = np.zeros((N, N))
        mu = np.zeros((N, N))
        v_max = 0.0
        continuer = [[True] * N for _ in range(N)]
        inc_generated = [[dict() for _ in range(N)] for _ in range(N)]
        # without-replacement pools over combination *ranks* (sparse
        # Fisher-Yates) — the reference materializes every subset of every
        # stratum up front (contributivity.py:838-843), which is exponential
        # memory; ranks are unranked lazily at draw time instead.
        pools = [[WithoutReplacementRanks(comb(N - 1, strata))
                  for strata in range(N)] for _ in range(N)]
        while np.any(continuer) or (1 - alpha) < v_max / sv_accuracy ** 2:
            t += 1
            plan = self._wr_draw_plan(self._rng, N, sigma2, continuer, pools)
            reqs = self._pair_requests(plan)
            if lookahead:
                srng = self._spec_rng()
                spools = [[self._clone_pool(p) for p in row]
                          for row in pools]
                scont = [list(row) for row in continuer]
                for _ in range(int(lookahead)):
                    reqs += self._pair_requests(self._wr_draw_plan(
                        srng, N, sigma2, scont, spools))
            if reqs:
                self.engine.evaluate(reqs)
            vals = self.engine.charac_fct_values
            for k, strata, S in plan:
                s_key = tuple(sorted(int(x) for x in S))
                increment = vals[tuple(sorted(list(s_key) + [k]))] - vals.get(s_key, 0.0)
                inc_generated[k][strata][s_key] = increment
                m = len(inc_generated[k][strata])
                mu[k, strata] = (mu[k, strata] * (m - 1) + increment) / m
                var_s = sum((v - mu[k, strata]) ** 2
                            for v in inc_generated[k][strata].values())
                sigma2[k, strata] = var_s / (m - 1) if m > 1 else 0.0
                sigma2[k, strata] *= (1 / m - factorial(N - 1 - strata)
                                      * factorial(strata) / factorial(N - 1))
            shap = np.mean(mu, axis=1)
            var = np.zeros(N)
            for k in range(N):
                for strata in range(N):
                    n_ks = len(inc_generated[k][strata])
                    if n_ks == 0:
                        var[k] = np.inf
                    else:
                        var[k] += sigma2[k, strata] ** 2 / n_ks
                    if n_ks > 20:
                        continuer[k][strata] = False
                    total = (factorial(N - 1) /
                             (factorial(N - 1 - strata) * factorial(strata)))
                    if n_ks >= total:
                        continuer[k][strata] = False
                var[k] /= N ** 2
            v_max = np.max(var)
        self._finish("WR_SMC Shapley", shap, np.sqrt(var), t0)

    # ------------------------------------------------------------------
    # 15/16. Retrain-free estimators: GTG-Shapley reconstruction + SVARM
    # (contrib/reconstruct.py — v(S) from ONE recorded grand-coalition
    # run; coalition evals are eval-only batches through the engine's
    # merged slot buckets, never training runs)
    # ------------------------------------------------------------------

    def _reconstructor(self):
        """The engine's shared ReconstructionEvaluator, recording the
        grand coalition on first use — ONE training run per scenario,
        reused across retrain-free methods (the recording analog of the
        shared coalition memo). Tests may pre-seat
        `engine._reconstruction` with an analytic stub."""
        eng = self.engine
        if getattr(eng, "_reconstruction", None) is None:
            from .reconstruct import ReconstructionEvaluator
            eng._reconstruction = ReconstructionEvaluator(eng)
        return eng._reconstruction

    def _set_mc_trust(self, contributions, alpha, method):
        """Feed the PR-6 trust row from a Monte-Carlo run: the iteration
        rows split into up to 5 disjoint blocks whose means are
        independent unbiased pseudo-replicas — Monte-Carlo uncertainty
        (replica std, Kendall-tau rank stability, CIs) in the same report
        row seed ensembles use, tagged source="mc_blocks" + the method
        name so the row can't impersonate a seed-ensemble one."""
        T = len(contributions)
        if T < 2:
            return
        blocks = np.array_split(np.asarray(contributions), min(5, T), axis=0)
        reps = np.stack([b.mean(axis=0) for b in blocks])
        self.trust = {**trust_from_replicas(reps, alpha, source="mc_blocks"),
                      "method": method}
        obs_trace.event("contrib.trust", **self.trust)

    def exact_reconstructed(self, alpha=0.95):
        """Exact Shapley over RECONSTRUCTED coalition models: the full
        2^P - 1 powerset evaluated through the shared
        ReconstructionEvaluator (eval-only batches; the one recorded
        grand-coalition run is the only training), then the exact
        closed-form Shapley sum. The adaptive planner's `exact` row —
        zero sampling error, so the trust contract is met by
        construction (scores_std is exactly zero)."""
        t0 = self._method_span("exact (reconstructed)")
        logger.info("# Launching exact Shapley over reconstructed models")
        n = self._n
        try:
            recon = self._reconstructor()
        except BaseException:
            # same span hygiene as GTG_Shapley/SVARM
            t0.cancel()
            raise
        recon.evaluate(powerset_order(n))
        sv = np.asarray(shapley_from_characteristic(n, recon.values))
        self._finish("exact (reconstructed)", sv, np.zeros(n), t0)

    def GTG_Shapley(self, sv_accuracy=0.01, alpha=0.95, truncation=None,
                    perm_batch=16, min_iter=100):
        """GTG-Shapley (arXiv:2109.02053): truncated-permutation Shapley
        over RECONSTRUCTED coalition models — zero coalition training
        passes beyond the one recorded grand-coalition run. The paper's
        within-round truncation rule prunes a permutation's remaining
        positions once |v(N) - v(prefix)| < `truncation` (default from
        MPLC_TPU_GTG_TRUNCATION, 0.05); with the whole recorded
        trajectory replayed per reconstruction, the full training run is
        the one "round" the rule applies within (the per-round
        decomposition of the paper collapses — documented deviation,
        doc/documentation.md "Retrain-free estimators")."""
        t0 = self._method_span("GTG-Shapley")
        logger.info("# Launching GTG-Shapley (retrain-free reconstruction)")
        n = self._n
        try:
            recon = self._reconstructor()
        except BaseException:
            # the reconstructor raises in normal use (2-D guard, all-
            # partners-dropped, propagated recording OOM): drop the open
            # method span or every later engine.evaluate would attribute
            # its memo traffic to this method via active_span
            t0.cancel()
            raise
        if truncation is None:
            truncation = constants._env_nonneg_float(
                constants.GTG_TRUNCATION_ENV, 0.05)
        v_all = float(recon.evaluate([tuple(range(n))])[0])
        if n == 1:
            self._finish("GTG-Shapley", np.array([v_all]),
                         np.array([0.0]), t0)
            return
        contributions, t = self._truncated_permutation_sweep(
            n, v_all, recon.evaluate, recon.values, sv_accuracy, alpha,
            truncation, False, self._sizes(), perm_batch, min_iter)
        sv = np.mean(contributions, axis=0)
        std = np.std(contributions, axis=0) / np.sqrt(t - 1)
        self._set_mc_trust(contributions, alpha, "GTG-Shapley")
        self._finish("GTG-Shapley", sv, std, t0)

    def SVARM(self, budget=None, alpha=0.95, block=64):
        """SVARM ("Approximating the Shapley Value without Marginal
        Contributions", arXiv:2302.00736): stratified sampling where ONE
        evaluated coalition A updates the plus-strata estimates of every
        member and the minus-strata estimates of every non-member — no
        paired (S, S u {i}) marginals, so whole sample blocks pack into
        single eval batches. Runs retrain-free over reconstructed models;
        strata 0 and n-1 are exact anchors, every other (partner, size)
        stratum gets a guaranteed warm-up sample, then `budget` sampled
        coalitions (MPLC_TPU_SVARM_SAMPLES; auto max(4 n^2, 128))."""
        t0 = self._method_span("SVARM")
        logger.info("# Launching SVARM (stratified, marginal-free sampling)")
        n = self._n
        try:
            recon = self._reconstructor()
        except BaseException:
            # same span hygiene as GTG_Shapley: a leaked open method span
            # would mis-attribute every later method's memo counters
            t0.cancel()
            raise
        full = tuple(range(n))
        v_all = float(recon.evaluate([full])[0])
        if n == 1:
            self._finish("SVARM", np.array([v_all]), np.array([0.0]), t0)
            return
        if budget is None:
            budget = constants._env_nonneg_int(
                constants.SVARM_SAMPLES_ENV, 0) or max(4 * n * n, 128)
        # exact anchors: strata s=0 (v({i}), v(empty)) and s=n-1
        # (v(N), v(N \ {i})) need no sampling at all
        recon.evaluate([(i,) for i in range(n)]
                       + [tuple(sorted(set(range(n)) - {i}))
                          for i in range(n)])
        vals = recon.values
        exact_plus = np.full((n, n), np.nan)
        exact_minus = np.full((n, n), np.nan)
        for i in range(n):
            exact_plus[i, 0] = vals[(i,)]
            exact_minus[i, 0] = 0.0
            exact_plus[i, n - 1] = v_all
            exact_minus[i, n - 1] = vals[tuple(sorted(set(range(n)) - {i}))]
        psum = np.zeros((n, n))
        psq = np.zeros((n, n))
        pcnt = np.zeros((n, n))
        msum = np.zeros((n, n))
        msq = np.zeros((n, n))
        mcnt = np.zeros((n, n))
        K_rep = 5  # pseudo-replica accumulators for the trust row
        rp = np.zeros((K_rep, n, n))
        rpc = np.zeros((K_rep, n, n))
        rm = np.zeros((K_rep, n, n))
        rmc = np.zeros((K_rep, n, n))

        # guaranteed coverage: one warm-up draw per non-exact stratum,
        # updating only its designated (sign, i, s) cell
        warm = svarm_warmup_draws(n, self._rng)
        recon.evaluate([w[3] for w in warm if w[3]])
        for sign, i, s, A in warm:
            v = vals[A] if A else 0.0
            if sign == "plus":
                psum[i, s] += v
                psq[i, s] += v * v
                pcnt[i, s] += 1
            else:
                msum[i, s] += v
                msq[i, s] += v * v
                mcnt[i, s] += 1

        it = 0
        drawn = 0
        # n < 3 has no non-exact stratum: the anchors above already
        # determine every phi exactly and svarm_batch_draws returns []
        while n >= 3 and drawn < budget:
            # each draw is an (A+, A-) PAIR — two sampled coalitions —
            # so the coalition budget buys ceil(remaining / 2) pairs
            draws = svarm_batch_draws(
                n, min(block, max(1, (budget - drawn + 1) // 2)),
                self._rng)
            recon.evaluate([a for pair in draws for a in pair if a])
            for ap, am in draws:
                rep = it % K_rep
                it += 1
                va = vals[ap]
                sa = len(ap) - 1
                for i in ap:
                    if np.isnan(exact_plus[i, sa]):
                        psum[i, sa] += va
                        psq[i, sa] += va * va
                        pcnt[i, sa] += 1
                        rp[rep, i, sa] += va
                        rpc[rep, i, sa] += 1
                vb = vals[am] if am else 0.0
                sb = len(am)
                in_a = set(am)
                for i in range(n):
                    if i in in_a or not np.isnan(exact_minus[i, sb]):
                        continue
                    msum[i, sb] += vb
                    msq[i, sb] += vb * vb
                    mcnt[i, sb] += 1
                    rm[rep, i, sb] += vb
                    rmc[rep, i, sb] += 1
            drawn += 2 * len(draws)

        pmean = np.where(~np.isnan(exact_plus), np.nan_to_num(exact_plus),
                         psum / np.maximum(pcnt, 1))
        mmean = np.where(~np.isnan(exact_minus), np.nan_to_num(exact_minus),
                         msum / np.maximum(mcnt, 1))
        sv = (pmean - mmean).mean(axis=1)

        def sem2(sumv, sq, cnt):
            # variance of each stratum MEAN (unbiased sample variance /
            # count); exact strata carry count 0 and contribute 0
            c = np.maximum(cnt, 1)
            var = np.maximum(sq / c - (sumv / c) ** 2, 0.0)
            var = np.where(cnt > 1, var * cnt / np.maximum(cnt - 1, 1), 0.0)
            return np.where(cnt > 0, var / c, 0.0)

        var_i = (sem2(psum, psq, pcnt) + sem2(msum, msq, mcnt)).sum(axis=1) \
            / n ** 2
        std = np.sqrt(var_i)

        reps = np.zeros((K_rep, n))
        for r in range(K_rep):
            pm = np.where(~np.isnan(exact_plus), np.nan_to_num(exact_plus),
                          np.where(rpc[r] > 0,
                                   rp[r] / np.maximum(rpc[r], 1), pmean))
            mm = np.where(~np.isnan(exact_minus),
                          np.nan_to_num(exact_minus),
                          np.where(rmc[r] > 0,
                                   rm[r] / np.maximum(rmc[r], 1), mmean))
            reps[r] = (pm - mm).mean(axis=1)
        self.trust = {**trust_from_replicas(reps, alpha, source="mc_blocks"),
                      "method": "SVARM"}
        obs_trace.event("contrib.trust", **self.trust)
        self._finish("SVARM", sv, std, t0)

    # ------------------------------------------------------------------
    # 10/11/12. Federated step-by-step scores (history post-processing)
    # ------------------------------------------------------------------

    def compute_relative_perf_matrix(self):
        """Reference contributivity.py:1079-1115: per-round ratio of each
        partner's val accuracy to the collective model's."""
        init_skip = 0.1
        final_skip = 0.1
        mpl = self.scenario.mpl
        coll = np.asarray(mpl.history.history["mpl_model"]["val_accuracy"])
        partner_mats = [np.asarray(v["val_accuracy"])
                        for k, v in mpl.history.history.items() if k != "mpl_model"]
        per_partner = np.stack(partner_mats, axis=-1)  # [E, MB, P]
        E, MB, P = per_partner.shape
        first = int(np.round(E * MB * init_skip))
        last = int(np.round(E * MB * (1 - final_skip)))
        coll_flat = coll.reshape(E * MB)
        per_flat = per_partner.reshape(E * MB, P)
        with np.errstate(divide="ignore", invalid="ignore"):
            rel = np.divide(per_flat, coll_flat[:, None])
        return rel[first:last, :]

    def _sbs(self, importance_fn, name):
        sp = self._method_span(name)
        rel = self.compute_relative_perf_matrix()
        rounds = rel.shape[0]
        scores = importance_fn(rounds) @ np.nan_to_num(rel)
        self._finish(name, scores, np.zeros(self._n), sp)

    def federated_SBS_linear(self):
        logger.info("# Federated SBS linear")
        self._sbs(lambda r: np.arange(r, dtype=float),
                  "Federated step by step linear scores")

    def federated_SBS_quadratic(self):
        logger.info("# Federated SBS quadratic")
        self._sbs(lambda r: np.square(np.arange(r, dtype=float)),
                  "Federated step by step quadratic scores")

    def federated_SBS_constant(self):
        sp = self._method_span("Federated step by step constant scores")
        logger.info("# Federated SBS constant")
        rel = self.compute_relative_perf_matrix()
        scores = np.nanmean(rel, axis=0)
        self._finish("Federated step by step constant scores", scores,
                     np.zeros(self._n), sp)

    # ------------------------------------------------------------------
    # 13. LFlip
    # ------------------------------------------------------------------

    def flip_label(self):
        """Train MplLabelFlip; score = exp(-||theta_i - I||_F)
        (reference contributivity.py:1117-1132)."""
        t0 = self._method_span("Label Flip")
        from ..mpl.approaches import MplLabelFlip
        mpl = MplLabelFlip(self.scenario)
        mpl.fit()
        self.thetas_history = mpl.history.theta
        self.score = mpl.history.score
        last = mpl.history.theta[-1]
        scores = np.exp(-np.array([
            np.linalg.norm(last[i] - np.identity(last[i].shape[0]))
            for i in range(self._n)]))
        self._finish("Label Flip", scores, np.zeros(self._n), t0)

    # ------------------------------------------------------------------
    # 14. PVRL — REINFORCE partner valuation
    # ------------------------------------------------------------------

    def PVRL(self, learning_rate):
        """Per-epoch Bernoulli partner selection trained by REINFORCE on the
        val-loss improvement (reference contributivity.py:942-1013; the
        upstream constructor call is broken — this is the documented intent).
        Driven through the coalition-maskable trainer one epoch at a time:
        the selection mask is exactly a coalition mask."""
        t0 = self._method_span("PVRL")
        logger.info("# Launching PVRL")
        sc = self.scenario
        n = self._n
        eng = self.engine
        cfg = TrainConfig(
            approach=sc.multi_partner_learning_approach_key,
            aggregator=sc.aggregation_name,
            epoch_count=sc.epoch_count,
            minibatch_count=sc.minibatch_count,
            gradient_updates_per_pass=sc.gradient_updates_per_pass_count,
            is_early_stopping=False,
            compute_dtype=getattr(sc, "compute_dtype", "float32"),
            record_partner_val=False,
            # the reward is computed from a direct end-of-epoch eval below;
            # no per-minibatch val history needed
            record_val_history=False,
        )
        trainer = MplTrainer.get(sc.dataset.model, cfg)
        rng = jax.random.PRNGKey(getattr(sc, "seed", 0) + 99)
        state = trainer.init_state(rng, n)
        # the trainer's pinned jits: dedupes compiles across PVRL runs on
        # one (model, cfg) and routes them through the compile telemetry
        run = trainer.jit_epoch_chunk
        ev = trainer.jit_evaluate

        w = np.zeros(n)
        values = 1.0 / (1.0 + np.exp(-w))
        prev_loss = float(ev(state.params, eng.val)[0])
        for epoch in range(sc.epoch_count):
            is_in = np.zeros(n)
            while is_in.sum() == 0:
                is_in = self._rng.binomial(1, p=values)
            mask = jnp.asarray(is_in, jnp.float32)
            state = run(state, eng.stacked, eng.val, mask,
                        jax.random.fold_in(rng, epoch), n_epochs=1)
            # reward from the END-of-epoch model (a fresh eval of the
            # current params) — the [epoch, MB-1] history cell is recorded
            # at the START of the last minibatch and lags one aggregation
            loss = float(ev(state.params, eng.val)[0])
            G = -loss + prev_loss
            dp_dw = np.exp(w) / (1 + np.exp(w)) ** 2
            # The REINFORCE gradient has 1/(1-p) and prodp/(1-prodp) poles:
            # the reference divides by zero once any selection prob
            # saturates (contributivity.py:942-1013 intent). Clamp the probs
            # used in the gradient and bound the logits so the update can
            # never produce inf/NaN.
            safe = np.clip(values, 1e-6, 1.0 - 1e-6)
            prodp = np.prod(safe)
            grad = (is_in / safe - (1.0 - is_in) / (1.0 - safe)
                    - prodp / (1.0 - prodp) / (1.0 - safe))
            w = np.clip(w + learning_rate * G * dp_dw * grad, -10.0, 10.0)
            values = 1.0 / (1.0 + np.exp(-w))
            prev_loss = loss
        self._finish("PVRL", values, np.zeros(n), t0)

    # ------------------------------------------------------------------
    # dispatcher (reference contributivity.py:1134-1198)
    # ------------------------------------------------------------------

    def compute_contributivity(self, method_to_compute, sv_accuracy=0.01,
                               alpha=0.95, truncation=0.05, update=50,
                               accuracy_target=None, deadline_sec=None):
        if method_to_compute == "auto":
            # adaptive planner (contrib/planner.py): resolve the triple
            # (game size, accuracy target, deadline) to a concrete
            # estimator, journal the plan (the `contrib.plan` event — the
            # sweep service copies it into the WAL and the terminal
            # service.job event), then dispatch the CONCRETE method so a
            # replay of the journaled plan never re-plans
            from .planner import estimate_eval_seconds, plan_query
            eval_sec, basis = estimate_eval_seconds(self.engine)
            plan = plan_query(self._n, accuracy_target, deadline_sec,
                              eval_sec=eval_sec, cost_basis=basis,
                              live=False)
            self.plan = plan
            obs_trace.event("contrib.plan", **plan.describe())
            if plan.method == "exact":
                # the planner's exact row is the retrain-free exact
                # powerset (reconstructed models + exact Shapley), i.e.
                # GTG's machinery run to exhaustion — not the 2^P
                # RETRAINING sweep ("Shapley values"), whose cost model
                # is a different regime entirely
                self.exact_reconstructed(alpha=alpha)
            elif plan.method == "GTG-Shapley":
                self.GTG_Shapley(alpha=alpha, **plan.method_kw)
            else:
                self.SVARM(alpha=alpha, **plan.method_kw)
            return
        fedavg_only = ("Federated SBS linear", "Federated SBS quadratic",
                       "Federated SBS constant")
        if method_to_compute in fedavg_only and \
                self.scenario.multi_partner_learning_approach_key != "fedavg":
            logger.warning("Step by step contributivity methods are only suited "
                           "for federated averaging learning approaches")
        if method_to_compute == "Shapley values":
            self.compute_SV()
        elif method_to_compute == "Independent scores":
            self.compute_independent_scores()
        elif method_to_compute == "TMCS":
            self.truncated_MC(sv_accuracy=sv_accuracy, alpha=alpha,
                              truncation=truncation)
        elif method_to_compute == "ITMCS":
            self.interpol_TMC(sv_accuracy=sv_accuracy, alpha=alpha,
                              truncation=truncation)
        elif method_to_compute == "IS_lin_S":
            self.IS_lin(sv_accuracy=sv_accuracy, alpha=alpha)
        elif method_to_compute == "IS_reg_S":
            self.IS_reg(sv_accuracy=sv_accuracy, alpha=alpha)
        elif method_to_compute == "AIS_Kriging_S":
            self.AIS_Kriging(sv_accuracy=sv_accuracy, alpha=alpha, update=update)
        elif method_to_compute == "SMCS":
            self.Stratified_MC(sv_accuracy=sv_accuracy, alpha=alpha)
        elif method_to_compute == "WR_SMC":
            self.without_replacment_SMC(sv_accuracy=sv_accuracy, alpha=alpha)
        elif method_to_compute == "Federated SBS linear":
            self.federated_SBS_linear()
        elif method_to_compute == "Federated SBS quadratic":
            self.federated_SBS_quadratic()
        elif method_to_compute == "Federated SBS constant":
            self.federated_SBS_constant()
        elif method_to_compute == "PVRL":
            self.PVRL(learning_rate=0.2)
        elif method_to_compute == "LFlip":
            self.flip_label()
        elif method_to_compute == "GTG-Shapley":
            # truncation=None: GTG's own within-round threshold (the
            # MPLC_TPU_GTG_TRUNCATION default), not TMCS's `truncation`
            self.GTG_Shapley(sv_accuracy=sv_accuracy, alpha=alpha)
        elif method_to_compute == "SVARM":
            self.SVARM(alpha=alpha)
        else:
            logger.warning("Unrecognized name of method, statement ignored!")
