"""Framework-wide constants.

Mirrors the parameter surface of the reference simulator
(/root/reference/mplc/constants.py:1-55) so that configurations written for the
reference keep their meaning here.
"""

# ML defaults (reference: mplc/constants.py:7-12)
DEFAULT_BATCH_SIZE = 256
MAX_BATCH_SIZE = 2 ** 20
DEFAULT_GRADIENT_UPDATES_PER_PASS_COUNT = 8
PATIENCE = 10  # early-stopping patience, in epochs
DEFAULT_BATCH_COUNT = 20
DEFAULT_EPOCH_COUNT = 40

# Logging file names (reference: mplc/constants.py:17-18)
INFO_LOGGING_FILE_NAME = "info.log"
DEBUG_LOGGING_FILE_NAME = "debug.log"

# Paths
EXPERIMENTS_FOLDER_NAME = "experiments"

# Quick-demo shrink sizes (reference: mplc/constants.py:24-26)
TRAIN_SET_MAX_SIZE_QUICK_DEMO = 1000
VAL_SET_MAX_SIZE_QUICK_DEMO = 500
TEST_SET_MAX_SIZE_QUICK_DEMO = 500

# Contributivity method registry names (reference: mplc/constants.py:28-43)
CONTRIBUTIVITY_METHODS = [
    "Shapley values",
    "Independent scores",
    "TMCS",
    "ITMCS",
    "IS_lin_S",
    "IS_reg_S",
    "AIS_Kriging_S",
    "SMCS",
    "WR_SMC",
    "Federated SBS linear",
    "Federated SBS quadratic",
    "Federated SBS constant",
    "LFlip",
    "PVRL",
]

# Dataset tags (reference: mplc/constants.py:46-52)
MNIST = "mnist"
CIFAR10 = "cifar10"
TITANIC = "titanic"
ESC50 = "esc50"
IMDB = "imdb"
SUPPORTED_DATASETS_NAMES = [MNIST, CIFAR10, TITANIC, ESC50, IMDB]

# TPU-specific knobs (new in this framework)
# Max number of coalitions evaluated in a single compiled batch per device;
# larger requests are chunked so HBM stays bounded.
MAX_COALITIONS_PER_DEVICE_BATCH = 16
# Chunk size (samples) for validation/test-set evaluation inside jit, to bound
# the [coalitions x partners x samples] activation footprint. Env-overridable
# (MPLC_TPU_EVAL_CHUNK) so the coalition-cap crash bisect can halve the eval
# window to test whether wide-batch worker crashes are program-shape-bound
# (perf/r4/tune_cap32.log; VERDICT r4 weak #3).
import os as _os

EVAL_CHUNK_SIZE = int(_os.environ.get("MPLC_TPU_EVAL_CHUNK", "2048"))
